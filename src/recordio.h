// RecordIO: dmlc-compatible binary record format.
//
// TPU-native reimplementation of the reference's record layer
// (ref: 3rdparty/dmlc-core dmlc/recordio.h usage in src/io/io.cc;
// python/mxnet/recordio.py). Byte-identical on disk:
//
//   uint32 magic = 0xced7230a
//   uint32 lrec  = cflag << 29 | length   (cflag: 0 whole, 1 start,
//                                          2 middle, 3 end)
//   payload[length], zero-padded to a 4-byte boundary
//
// Writers split any payload that itself contains the magic word at those
// positions (dropping the 4 magic bytes); readers re-insert the magic when
// joining — dmlc-core RecordIOWriter/RecordIOReader semantics, which the
// pure-Python layer does not implement (it writes cflag=0 only).
#ifndef MXNET_TPU_RECORDIO_H_
#define MXNET_TPU_RECORDIO_H_

#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

namespace mxnet_tpu {

static const uint32_t kRecordIOMagic = 0xced7230a;
static const uint32_t kLRecKindBits = 29;
static const uint32_t kLRecLenMask = (1u << kLRecKindBits) - 1;

class RecordWriter {
 public:
  explicit RecordWriter(const std::string& path);
  ~RecordWriter();
  bool ok() const { return fp_ != nullptr; }
  // Write one record, splitting on embedded magic words like dmlc.
  void Write(const void* data, size_t size);
  uint64_t Tell();
  void Close();

 private:
  void WriteChunk(const void* data, size_t size, uint32_t cflag);
  std::FILE* fp_;
};

enum class ReadStatus {
  kRecord = 0,   // out holds a complete record
  kEOF = 1,      // clean end of stream
  kCorrupt = 2,  // bad magic / truncated split record / short payload
};

class RecordReader {
 public:
  explicit RecordReader(const std::string& path);
  ~RecordReader();
  bool ok() const { return fp_ != nullptr; }
  // Read the next (joined) record into out.
  ReadStatus Next(std::vector<char>* out);
  void Seek(uint64_t pos);
  uint64_t Tell();
  void Close();

 private:
  std::FILE* fp_;
};

}  // namespace mxnet_tpu

#endif  // MXNET_TPU_RECORDIO_H_
