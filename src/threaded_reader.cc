// See threaded_reader.h.
#include "threaded_reader.h"

#include <algorithm>
#include <random>

namespace mxnet_tpu {

ThreadedRecordReader::ThreadedRecordReader(const std::string& path,
                                           size_t capacity,
                                           bool shuffle_chunks,
                                           uint64_t seed)
    : path_(path), capacity_(capacity == 0 ? 256 : capacity),
      shuffle_(shuffle_chunks), seed_(seed), ok_(false) {
  RecordReader probe(path_);
  ok_ = probe.ok();
  if (ok_) worker_ = std::thread(&ThreadedRecordReader::Producer, this);
}

ThreadedRecordReader::~ThreadedRecordReader() { StopProducer(); }

void ThreadedRecordReader::StopProducer() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    stop_ = true;
  }
  cv_not_full_.notify_all();
  cv_not_empty_.notify_all();
  if (worker_.joinable()) worker_.join();
}

void ThreadedRecordReader::Producer() {
  RecordReader reader(path_);
  std::mt19937_64 rng(seed_);
  // shuffle window: read up to capacity records, emit in random order
  // (ref: iter_image_recordio_2.cc shuffle_chunk semantics)
  std::vector<std::vector<char>> window;
  std::vector<char> rec;
  bool source_eof = false;
  while (true) {
    if (!source_eof && window.size() < (shuffle_ ? capacity_ : 1)) {
      uint64_t at = reader.Tell();
      ReadStatus st = reader.Next(&rec);
      if (st == ReadStatus::kRecord) {
        window.emplace_back(std::move(rec));
        if (shuffle_ && window.size() < capacity_) continue;
      } else {
        if (st == ReadStatus::kCorrupt) {
          std::lock_guard<std::mutex> lk(mu_);
          error_ = "invalid RecordIO stream at offset " + std::to_string(at);
        }
        source_eof = true;
      }
    }
    if (window.empty() && source_eof) break;
    size_t pick = 0;
    if (shuffle_ && window.size() > 1) {
      pick = rng() % window.size();
      std::swap(window[pick], window.back());
    } else if (!window.empty()) {
      std::swap(window[0], window.back());
    }
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_not_full_.wait(lk, [this] {
        return queue_.size() < capacity_ || stop_;
      });
      if (stop_) return;
      queue_.emplace_back(std::move(window.back()));
    }
    window.pop_back();
    cv_not_empty_.notify_one();
  }
  {
    std::lock_guard<std::mutex> lk(mu_);
    eof_ = true;
  }
  cv_not_empty_.notify_all();
}

bool ThreadedRecordReader::Next(std::vector<char>* out) {
  std::unique_lock<std::mutex> lk(mu_);
  cv_not_empty_.wait(lk, [this] {
    return !queue_.empty() || eof_ || stop_;
  });
  if (queue_.empty()) return false;
  *out = std::move(queue_.front());
  queue_.pop_front();
  lk.unlock();
  cv_not_full_.notify_one();
  return true;
}

void ThreadedRecordReader::Reset() {
  StopProducer();
  {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.clear();
    eof_ = false;
    stop_ = false;
    error_.clear();
  }
  worker_ = std::thread(&ThreadedRecordReader::Producer, this);
}

}  // namespace mxnet_tpu
