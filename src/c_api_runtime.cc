// Training C ABI: NDArray create/copy, op invoke by name, autograd.
//
// Mirrors the core of the reference's 240-function C surface
// (ref: include/mxnet/c_api.h — MXNDArrayCreateEx :392,
// MXNDArraySyncCopyFromCPU :456, MXNDArraySyncCopyToCPU :465,
// MXNDArrayGetShape :575, MXImperativeInvokeEx
// src/c_api/c_api_ndarray.cc:132, MXAutogradMarkVariables c_api.h:1162,
// MXAutogradSetIsRecording :1143, MXAutogradBackwardEx :1222,
// MXNDArrayGetGrad :705, MXNDArrayWaitAll :528) — the seam all six
// reference language frontends attach to. Entry points marshal handles
// and strings, then dispatch into mxnet_tpu.c_runtime (Python), which
// shares the op registry, autograd tape, and XLA compile cache with the
// Python frontend: one runtime, many frontends, exactly the reference's
// architecture with jax/XLA standing where the C++ engine stood.
//
// Handles are PyObject* references to mxnet_tpu NDArrays; the caller
// owns them until MXTNDArrayFree. All entry points return 0/-1 with the
// message in MXTGetLastError() (src/c_api.cc).
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "c_error.h"
#include "py_embed.h"

// Exception->errno translation on every entry point (mxlint MX006):
// a C++ exception crossing the C ABI is UB; the macros turn it
// into the -1/MXTGetLastError() contract (see c_error.h).
#define API_BEGIN MXT_API_BEGIN
#define API_END MXT_API_END

namespace {

using mxnet_tpu::FailWith;
using mxnet_tpu::pyembed::EnsurePython;
using mxnet_tpu::pyembed::Gil;
using mxnet_tpu::pyembed::PyFail;

PyObject* Runtime() {
  static PyObject* mod = nullptr;  // borrowed forever (module is cached)
  if (mod == nullptr) mod = PyImport_ImportModule("mxnet_tpu.c_runtime");
  return mod;
}

// Call c_runtime.<fn>(*args); returns new reference or nullptr.
PyObject* CallRt(const char* fn, PyObject* args) {
  PyObject* mod = Runtime();
  if (mod == nullptr) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (f == nullptr) return nullptr;
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return out;
}

PyObject* HandleList(void** handles, uint32_t n) {
  PyObject* lst = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i) {
    PyObject* h = static_cast<PyObject*>(handles[i]);
    Py_INCREF(h);
    PyList_SET_ITEM(lst, i, h);
  }
  return lst;
}

}  // namespace

extern "C" {

// -- NDArray ----------------------------------------------------------------

int MXTNDArrayCreate(const int64_t* shape, uint32_t ndim, int dtype,
                     void** out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* shp = PyTuple_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
  PyObject* args = Py_BuildValue("(Ni)", shp, dtype);
  PyObject* res = CallRt("create", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArrayCreate");
  *out = res;
  return 0;
  API_END()
}

int MXTNDArrayFromData(const int64_t* shape, uint32_t ndim, int dtype,
                       const void* data, size_t nbytes, void** out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* shp = PyTuple_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
  PyObject* raw = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), static_cast<Py_ssize_t>(nbytes));
  PyObject* args = Py_BuildValue("(NiN)", shp, dtype, raw);
  PyObject* res = CallRt("from_bytes", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArrayFromData");
  *out = res;
  return 0;
  API_END()
}

int MXTNDArrayFree(void* handle) {
  API_BEGIN()
  if (handle == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
  API_END()
}

int MXTNDArrayGetShape(void* handle, uint32_t* out_ndim,
                       int64_t* out_shape /* >= 8 slots */) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("shape_of", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArrayGetShape");
  Py_ssize_t n = PyTuple_Size(res);
  if (n > 8) {
    Py_DECREF(res);
    return FailWith("MXTNDArrayGetShape: array has " + std::to_string(n) +
                    " dims, the ABI shape buffer holds 8");
  }
  *out_ndim = static_cast<uint32_t>(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    out_shape[i] = PyLong_AsLongLong(PyTuple_GET_ITEM(res, i));
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTNDArraySyncCopyToCPU(void* handle, void* data, size_t nbytes) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("to_bytes", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArraySyncCopyToCPU");
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    Py_DECREF(res);
    return PyFail("MXTNDArraySyncCopyToCPU: bytes");
  }
  if (static_cast<size_t>(len) != nbytes) {
    Py_DECREF(res);
    return FailWith("MXTNDArraySyncCopyToCPU: size mismatch (have " +
                    std::to_string(len) + " bytes, caller asked for " +
                    std::to_string(nbytes) + ")");
  }
  std::memcpy(data, buf, nbytes);
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTNDArrayWaitAll() {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("wait_all", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArrayWaitAll");
  Py_DECREF(res);
  return 0;
  API_END()
}

// -- op invoke --------------------------------------------------------------

// Invoke a registered op by name (ref: MXImperativeInvokeEx,
// src/c_api/c_api_ndarray.cc:132). Outputs: caller passes
// out_handles[max_outputs]; *num_outputs is set to the actual count.
int MXTImperativeInvoke(const char* op_name, uint32_t num_inputs,
                        void** inputs, uint32_t num_params,
                        const char** keys, const char** vals,
                        uint32_t* num_outputs, void** out_handles,
                        uint32_t max_outputs) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* ins = HandleList(inputs, num_inputs);
  PyObject* pk = PyList_New(num_params);
  PyObject* pv = PyList_New(num_params);
  for (uint32_t i = 0; i < num_params; ++i) {
    // decode as latin-1 so arbitrary C byte strings cannot yield NULL
    // (PyUnicode_FromString fails on non-UTF-8, and a NULL list slot
    // would crash the iterator later)
    PyObject* k = PyUnicode_DecodeLatin1(keys[i], strlen(keys[i]), "replace");
    PyObject* v = PyUnicode_DecodeLatin1(vals[i], strlen(vals[i]), "replace");
    if (k == nullptr || v == nullptr) {
      Py_XDECREF(k);
      Py_XDECREF(v);
      Py_DECREF(ins);
      Py_DECREF(pk);
      Py_DECREF(pv);
      return PyFail("MXTImperativeInvoke: bad param string");
    }
    PyList_SET_ITEM(pk, i, k);
    PyList_SET_ITEM(pv, i, v);
  }
  PyObject* args = Py_BuildValue("(sNNN)", op_name, ins, pk, pv);
  PyObject* res = CallRt("invoke", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTImperativeInvoke");
  Py_ssize_t n = PyList_Size(res);
  if (static_cast<uint32_t>(n) > max_outputs) {
    Py_DECREF(res);
    return FailWith("MXTImperativeInvoke: op produced " +
                    std::to_string(n) + " outputs, caller provided " +
                    std::to_string(max_outputs) + " slots");
  }
  *num_outputs = static_cast<uint32_t>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(res, i);
    Py_INCREF(o);
    out_handles[i] = o;
  }
  Py_DECREF(res);
  return 0;
  API_END()
}

// -- autograd ---------------------------------------------------------------

int MXTAutogradMarkVariables(uint32_t num, void** handles) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(N)", HandleList(handles, num));
  PyObject* res = CallRt("mark_variables", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTAutogradMarkVariables");
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTAutogradSetIsRecording(int is_recording) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt(is_recording ? "record_start" : "record_stop",
                         args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTAutogradSetIsRecording");
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTAutogradBackward(uint32_t num_outputs, void** outputs) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(N)", HandleList(outputs, num_outputs));
  PyObject* res = CallRt("backward", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTAutogradBackward");
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTNDArrayGetGrad(void* handle, void** out_grad) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("grad_of", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArrayGetGrad");
  *out_grad = res;
  return 0;
  API_END()
}

}  // extern "C"
