// Threaded prefetching record pipeline.
//
// TPU-native equivalent of the reference's dmlc::ThreadedIter double
// buffering + prefetcher stack (ref: src/io/iter_prefetcher.h,
// iter_image_recordio_2.cc ThreadedParser): a background IO thread reads
// and splits records off the file while the consumer drains a bounded
// ring — so record parsing never blocks the host->device feed. Runs
// entirely outside the Python GIL.
#ifndef MXNET_TPU_THREADED_READER_H_
#define MXNET_TPU_THREADED_READER_H_

#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "recordio.h"

namespace mxnet_tpu {

class ThreadedRecordReader {
 public:
  ThreadedRecordReader(const std::string& path, size_t capacity,
                       bool shuffle_chunks, uint64_t seed);
  ~ThreadedRecordReader();
  bool ok() const { return ok_; }
  // Pop the next record; false at end of stream. After a false return,
  // error() is non-empty if the stream ended on corruption, not EOF.
  bool Next(std::vector<char>* out);
  const std::string& error() const { return error_; }
  // Restart from the beginning of the file.
  void Reset();

 private:
  void Producer();
  void StopProducer();

  std::string path_;
  size_t capacity_;
  bool shuffle_;
  uint64_t seed_;
  bool ok_;

  std::string error_;
  std::mutex mu_;
  std::condition_variable cv_not_empty_;
  std::condition_variable cv_not_full_;
  std::deque<std::vector<char>> queue_;
  bool eof_ = false;
  bool stop_ = false;
  std::thread worker_;
};

}  // namespace mxnet_tpu

#endif  // MXNET_TPU_THREADED_READER_H_
