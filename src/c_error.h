// Shared error state for the C ABI (ref: src/c_api/c_api_error.cc —
// thread-local last-error retrievable via the GetLastError entry point).
#ifndef MXNET_TPU_C_ERROR_H_
#define MXNET_TPU_C_ERROR_H_

#include <string>

namespace mxnet_tpu {

// thread-local last error message, read by MXTGetLastError()
std::string& LastError();

// set the error and return -1 (the C ABI failure code)
int FailWith(const std::string& msg);

}  // namespace mxnet_tpu

#define MXT_API_BEGIN() try {
#define MXT_API_END()                                  \
  }                                                    \
  catch (const std::exception& e) {                    \
    return mxnet_tpu::FailWith(e.what());              \
  }                                                    \
  catch (...) {                                        \
    return mxnet_tpu::FailWith("unknown C++ exception"); \
  }                                                    \
  return 0;

#endif  // MXNET_TPU_C_ERROR_H_
