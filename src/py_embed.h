// Shared embedded-CPython helpers for the C ABI entry points.
//
// Used by c_api*.cc and c_predict_api.cc alike: the ABI works both
// embedded in a C/C++ application (initializes CPython on first use)
// and loaded into an existing Python process (uses the running
// interpreter via the GIL).
#ifndef MXNET_TPU_SRC_PY_EMBED_H_
#define MXNET_TPU_SRC_PY_EMBED_H_

#include <Python.h>

#include <dlfcn.h>
#include <stdio.h>

#include <mutex>
#include <string>

#include "c_error.h"

namespace mxnet_tpu {
namespace pyembed {

inline void EnsurePython() {
  static std::once_flag flag;
  std::call_once(flag, [] {
    if (!Py_IsInitialized()) {
      // Plugin hosts (perl XS, JNI, dlopen-based loaders) load this
      // library RTLD_LOCAL, so libpython arrives as a LOCAL-visibility
      // dependency — and numpy/jax C extensions, which expect python
      // symbols to be global, then fail to import with misleading
      // errors. Re-promote (or load) libpython RTLD_GLOBAL first; a
      // no-op when the host is python itself or links us directly.
      char soname[64];
      snprintf(soname, sizeof soname, "libpython%d.%d.so.1.0",
               PY_MAJOR_VERSION, PY_MINOR_VERSION);
      if (dlopen(soname, RTLD_NOLOAD | RTLD_GLOBAL | RTLD_LAZY) ==
          nullptr) {
        dlopen(soname, RTLD_GLOBAL | RTLD_LAZY);
      }
      Py_InitializeEx(0);
      PyEval_SaveThread();
    }
  });
}

class Gil {
 public:
  Gil() { state_ = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(state_); }
  Gil(const Gil&) = delete;
  Gil& operator=(const Gil&) = delete;

 private:
  PyGILState_STATE state_;
};

inline int PyFail(const char* what) {
  std::string msg = what;
  if (PyErr_Occurred()) {
    PyObject *type = nullptr, *val = nullptr, *tb = nullptr;
    PyErr_Fetch(&type, &val, &tb);
    PyErr_NormalizeException(&type, &val, &tb);
    if (val != nullptr) {
      PyObject* s = PyObject_Str(val);
      if (s != nullptr) {
        const char* u = PyUnicode_AsUTF8(s);
        if (u != nullptr) msg = std::string(what) + ": " + u;
        Py_DECREF(s);
      }
    }
    Py_XDECREF(type);
    Py_XDECREF(val);
    Py_XDECREF(tb);
    PyErr_Clear();
  }
  return FailWith(msg);
}

}  // namespace pyembed
}  // namespace mxnet_tpu

#endif  // MXNET_TPU_SRC_PY_EMBED_H_
