// Native C predict ABI: standalone inference entry points.
//
// Mirrors the reference's deployment ABI (ref: include/mxnet/c_predict_api.h
// — MXPredCreate :84, MXPredSetInput :254, MXPredForward :263,
// MXPredGetOutputShape :229, MXPredGetOutput :289, MXPredReshape :214,
// MXPredFree; src/c_api/c_predict_api.cc). The reference binds a
// GraphExecutor under the ABI; here each handle owns a
// mxnet_tpu.predictor.Predictor, whose bind compiles the whole graph into
// ONE XLA program — the compute path stays jax/XLA, the ABI stays C.
//
// Works both embedded in a C/C++ application (initializes CPython on first
// use; set PYTHONPATH so `import mxnet_tpu` resolves) and loaded into an
// existing Python process (uses the running interpreter via the GIL).
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "c_error.h"
#include "py_embed.h"

namespace {

using mxnet_tpu::FailWith;

struct PredState {
  PyObject* predictor = nullptr;          // mxnet_tpu.predictor.Predictor
  std::vector<uint32_t> shape_buf;        // storage for GetOutputShape
};

// Interpreter init + GIL + error helpers shared with the training ABI
// (src/py_embed.h) — ONE once_flag guards Py_InitializeEx across all
// ABI families, so concurrent first calls from different surfaces
// cannot double-initialize.
using mxnet_tpu::pyembed::EnsurePython;
using mxnet_tpu::pyembed::Gil;
using mxnet_tpu::pyembed::PyFail;

PyObject* PredictorModule() {
  return PyImport_ImportModule("mxnet_tpu.predictor");
}

// (names, shapes) python lists from the reference's packed shape arrays
bool BuildShapeArgs(uint32_t num_input_nodes, const char** input_keys,
                    const uint32_t* input_shape_indptr,
                    const uint32_t* input_shape_data, PyObject** out_names,
                    PyObject** out_shapes) {
  PyObject* names = PyList_New(num_input_nodes);
  PyObject* shapes = PyList_New(num_input_nodes);
  if (names == nullptr || shapes == nullptr) {
    Py_XDECREF(names);
    Py_XDECREF(shapes);
    return false;
  }
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(input_keys[i]));
    uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject* shp = PyTuple_New(hi - lo);
    for (uint32_t j = lo; j < hi; ++j) {
      PyTuple_SetItem(shp, j - lo,
                      PyLong_FromUnsignedLong(input_shape_data[j]));
    }
    PyList_SetItem(shapes, i, shp);
  }
  *out_names = names;
  *out_shapes = shapes;
  return true;
}

}  // namespace

extern "C" {

// ref: c_predict_api.h:84 MXPredCreate. dev_type/dev_id are accepted for
// signature parity; device placement is XLA's (single default device).
int MXTPredCreate(const char* symbol_json_str, const void* param_bytes,
                  int param_size, int dev_type, int dev_id,
                  uint32_t num_input_nodes, const char** input_keys,
                  const uint32_t* input_shape_indptr,
                  const uint32_t* input_shape_data, void** out) {
  (void)dev_type;
  (void)dev_id;
  MXT_API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* mod = PredictorModule();
  if (mod == nullptr) return PyFail("import mxnet_tpu.predictor failed");
  PyObject *names = nullptr, *shapes = nullptr;
  if (!BuildShapeArgs(num_input_nodes, input_keys, input_shape_indptr,
                      input_shape_data, &names, &shapes)) {
    Py_DECREF(mod);
    return FailWith("out of memory building inputs");
  }
  PyObject* pb;
  if (param_bytes != nullptr && param_size > 0) {
    pb = PyBytes_FromStringAndSize(static_cast<const char*>(param_bytes),
                                   param_size);
  } else {
    pb = Py_None;
    Py_INCREF(pb);
  }
  PyObject* pred = PyObject_CallMethod(mod, "_c_create", "sOOO",
                                       symbol_json_str, pb, names, shapes);
  Py_DECREF(pb);
  Py_DECREF(names);
  Py_DECREF(shapes);
  Py_DECREF(mod);
  if (pred == nullptr) return PyFail("MXTPredCreate failed");
  auto* st = new PredState();
  st->predictor = pred;
  *out = st;
  MXT_API_END()
}

// ref: c_predict_api.h:254 MXPredSetInput — float32 data, `size` elements.
int MXTPredSetInput(void* handle, const char* key, const float* data,
                    uint32_t size) {
  MXT_API_BEGIN()
  EnsurePython();
  Gil gil;
  auto* st = static_cast<PredState*>(handle);
  PyObject* mod = PredictorModule();
  if (mod == nullptr) return PyFail("import mxnet_tpu.predictor failed");
  PyObject* mv = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<float*>(data)),
      static_cast<Py_ssize_t>(size) * 4, PyBUF_READ);
  PyObject* r = PyObject_CallMethod(mod, "_c_set_input", "OsO",
                                    st->predictor, key, mv);
  Py_XDECREF(mv);
  Py_DECREF(mod);
  if (r == nullptr) return PyFail("MXTPredSetInput failed");
  Py_DECREF(r);
  MXT_API_END()
}

// ref: c_predict_api.h:263 MXPredForward.
int MXTPredForward(void* handle) {
  MXT_API_BEGIN()
  EnsurePython();
  Gil gil;
  auto* st = static_cast<PredState*>(handle);
  PyObject* r = PyObject_CallMethod(st->predictor, "forward", nullptr);
  if (r == nullptr) return PyFail("MXTPredForward failed");
  Py_DECREF(r);
  MXT_API_END()
}

// ref: c_predict_api.h:229 MXPredGetOutputShape. *shape_data points into
// handle-owned storage, valid until the next call on this handle.
int MXTPredGetOutputShape(void* handle, uint32_t index, uint32_t** shape_data,
                          uint32_t* shape_ndim) {
  MXT_API_BEGIN()
  EnsurePython();
  Gil gil;
  auto* st = static_cast<PredState*>(handle);
  PyObject* r = PyObject_CallMethod(st->predictor, "get_output_shape", "I",
                                    index);
  if (r == nullptr) return PyFail("MXTPredGetOutputShape failed");
  Py_ssize_t n = PySequence_Size(r);
  st->shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* it = PySequence_GetItem(r, i);
    st->shape_buf[i] = static_cast<uint32_t>(PyLong_AsUnsignedLong(it));
    Py_XDECREF(it);
  }
  Py_DECREF(r);
  *shape_data = st->shape_buf.data();
  *shape_ndim = static_cast<uint32_t>(n);
  MXT_API_END()
}

// ref: c_predict_api.h:289 MXPredGetOutput — copies `size` float32
// elements into caller memory.
int MXTPredGetOutput(void* handle, uint32_t index, float* data,
                     uint32_t size) {
  MXT_API_BEGIN()
  EnsurePython();
  Gil gil;
  auto* st = static_cast<PredState*>(handle);
  PyObject* mod = PredictorModule();
  if (mod == nullptr) return PyFail("import mxnet_tpu.predictor failed");
  PyObject* r = PyObject_CallMethod(mod, "_c_get_output", "OI",
                                    st->predictor, index);
  Py_DECREF(mod);
  if (r == nullptr) return PyFail("MXTPredGetOutput failed");
  char* buf = nullptr;
  Py_ssize_t nbytes = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &nbytes) != 0) {
    Py_DECREF(r);
    return PyFail("MXTPredGetOutput: bad buffer");
  }
  if (static_cast<uint64_t>(nbytes) != static_cast<uint64_t>(size) * 4) {
    Py_DECREF(r);
    return FailWith("MXTPredGetOutput: size mismatch (have " +
                    std::to_string(nbytes / 4) + " elements, caller asked " +
                    std::to_string(size) + ")");
  }
  std::memcpy(data, buf, nbytes);
  Py_DECREF(r);
  MXT_API_END()
}

// ref: c_predict_api.h:214 MXPredReshape — new handle at new input shapes,
// sharing the parameters with the original handle.
int MXTPredReshape(uint32_t num_input_nodes, const char** input_keys,
                   const uint32_t* input_shape_indptr,
                   const uint32_t* input_shape_data, void* handle,
                   void** out) {
  MXT_API_BEGIN()
  EnsurePython();
  Gil gil;
  auto* st = static_cast<PredState*>(handle);
  PyObject* mod = PredictorModule();
  if (mod == nullptr) return PyFail("import mxnet_tpu.predictor failed");
  PyObject *names = nullptr, *shapes = nullptr;
  if (!BuildShapeArgs(num_input_nodes, input_keys, input_shape_indptr,
                      input_shape_data, &names, &shapes)) {
    Py_DECREF(mod);
    return FailWith("out of memory building inputs");
  }
  PyObject* pred = PyObject_CallMethod(mod, "_c_reshape", "OOO",
                                       st->predictor, names, shapes);
  Py_DECREF(names);
  Py_DECREF(shapes);
  Py_DECREF(mod);
  if (pred == nullptr) return PyFail("MXTPredReshape failed");
  auto* st2 = new PredState();
  st2->predictor = pred;
  *out = st2;
  MXT_API_END()
}

// ref: c_predict_api.h MXPredFree.
int MXTPredFree(void* handle) {
  MXT_API_BEGIN()
  auto* st = static_cast<PredState*>(handle);
  if (st != nullptr && st->predictor != nullptr && Py_IsInitialized()) {
    Gil gil;
    Py_DECREF(st->predictor);
  }
  delete st;
  MXT_API_END()
}

}  // extern "C"
