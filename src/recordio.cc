// See recordio.h. ref: dmlc-core recordio semantics as used by
// src/io/iter_image_recordio_2.cc and python/mxnet/recordio.py.
#include "recordio.h"

#include <cstring>
#include <stdexcept>

namespace mxnet_tpu {

RecordWriter::RecordWriter(const std::string& path)
    : fp_(std::fopen(path.c_str(), "wb")) {}

RecordWriter::~RecordWriter() { Close(); }

void RecordWriter::Close() {
  if (fp_ != nullptr) {
    std::fclose(fp_);
    fp_ = nullptr;
  }
}

uint64_t RecordWriter::Tell() { return static_cast<uint64_t>(std::ftell(fp_)); }

void RecordWriter::WriteChunk(const void* data, size_t size, uint32_t cflag) {
  if (size > kLRecLenMask) {
    // the length field is 29 bits; dmlc-core CHECKs the same limit
    throw std::runtime_error(
        "RecordIO chunk exceeds 2^29-1 bytes; split the payload");
  }
  uint32_t header[2];
  header[0] = kRecordIOMagic;
  header[1] = (cflag << kLRecKindBits) | (static_cast<uint32_t>(size) & kLRecLenMask);
  std::fwrite(header, sizeof(uint32_t), 2, fp_);
  if (size != 0) std::fwrite(data, 1, size, fp_);
  size_t pad = (4 - size % 4) % 4;
  if (pad != 0) {
    const char zeros[4] = {0, 0, 0, 0};
    std::fwrite(zeros, 1, pad, fp_);
  }
}

void RecordWriter::Write(const void* data, size_t size) {
  const char* p = static_cast<const char*>(data);
  // find 4-byte-aligned embedded magic words; split there (dropping them)
  std::vector<size_t> splits;
  uint32_t magic = kRecordIOMagic;
  for (size_t i = 0; i + 4 <= size; i += 4) {
    if (std::memcmp(p + i, &magic, 4) == 0) splits.push_back(i);
  }
  if (splits.empty()) {
    WriteChunk(p, size, 0);
    return;
  }
  size_t begin = 0;
  for (size_t k = 0; k < splits.size(); ++k) {
    uint32_t cflag = (k == 0) ? 1 : 2;
    WriteChunk(p + begin, splits[k] - begin, cflag);
    begin = splits[k] + 4;  // the dropped magic word
  }
  WriteChunk(p + begin, size - begin, 3);
}

RecordReader::RecordReader(const std::string& path)
    : fp_(std::fopen(path.c_str(), "rb")) {}

RecordReader::~RecordReader() { Close(); }

void RecordReader::Close() {
  if (fp_ != nullptr) {
    std::fclose(fp_);
    fp_ = nullptr;
  }
}

void RecordReader::Seek(uint64_t pos) {
  std::fseek(fp_, static_cast<long>(pos), SEEK_SET);
}

uint64_t RecordReader::Tell() { return static_cast<uint64_t>(std::ftell(fp_)); }

ReadStatus RecordReader::Next(std::vector<char>* out) {
  out->clear();
  bool in_split = false;
  uint32_t magic_word = kRecordIOMagic;
  while (true) {
    uint32_t header[2];
    if (std::fread(header, sizeof(uint32_t), 2, fp_) != 2) {
      // clean EOF only at a record boundary; mid-split truncation is an
      // error (matches the Python fallback's IOError)
      return in_split ? ReadStatus::kCorrupt : ReadStatus::kEOF;
    }
    if (header[0] != kRecordIOMagic) return ReadStatus::kCorrupt;
    uint32_t cflag = header[1] >> kLRecKindBits;
    size_t length = header[1] & kLRecLenMask;
    if (in_split) {
      // re-insert the magic dropped by the writer between parts
      out->insert(out->end(), reinterpret_cast<char*>(&magic_word),
                  reinterpret_cast<char*>(&magic_word) + 4);
    }
    size_t old = out->size();
    out->resize(old + length);
    if (length != 0 && std::fread(out->data() + old, 1, length, fp_) != length) {
      return ReadStatus::kCorrupt;  // short payload
    }
    size_t pad = (4 - length % 4) % 4;
    if (pad != 0) std::fseek(fp_, static_cast<long>(pad), SEEK_CUR);
    if (cflag == 0 || cflag == 3) return ReadStatus::kRecord;
    in_split = true;
  }
}

}  // namespace mxnet_tpu
