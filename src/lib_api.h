// Plugin-author header for external operator libraries.
//
// Analog of the reference's include/mxnet/lib_api.h: a plugin MUST
// export initialize(int version) and return non-zero when compatible
// (ref: lib_api.h MXLIB_INITIALIZE_STR; src/c_api/c_api.cc:96 MXLoadLib
// treats zero as failure). Beyond that 1.6 contract, this framework
// defines an optional registration surface so a C plugin can publish
// host-side f32 kernels; the loader (mxnet_tpu/lib_api.py) wraps each
// one in jax.pure_callback so it composes with jit'ed graphs as an
// opaque host node.
//
// Build: gcc -shared -fPIC -O2 myops.c -o libmyops.so
#ifndef MXNET_TPU_SRC_LIB_API_H_
#define MXNET_TPU_SRC_LIB_API_H_

#include <stdint.h>

#define MXTPU_LIB_VERSION 10600  /* major*10000 + minor*100 + patch */

#ifdef __cplusplus
extern "C" {
#endif

/* Required. Return non-zero iff the library supports `version`. */
int initialize(int version);

/* Optional op-registration surface (all-or-nothing):             */
/* number of ops this library provides                            */
int _opRegSize(void);
/* name of op `idx` (static storage)                              */
const char* _opRegName(int idx);
/* infer the (single) output shape from the input shapes; write   */
/* into out_shape (capacity 8) / out_ndim; return 0 on success    */
int _opInferShape(int idx, int nin,
                  const int64_t* const* in_shapes, const int* in_ndims,
                  int64_t* out_shape, int* out_ndim);
/* compute the op on contiguous f32 host buffers; return 0 on     */
/* success                                                        */
int _opCompute(int idx, int nin,
               const float* const* inputs,
               const int64_t* const* in_shapes, const int* in_ndims,
               float* output, const int64_t* out_shape, int out_ndim);

#ifdef __cplusplus
}
#endif
#endif  // MXNET_TPU_SRC_LIB_API_H_
