// Frontier C ABI: Symbol, Executor, KVStore, DataIter, NDArray save/load.
//
// Widens the training surface of c_api_runtime.cc to the full set of
// families every reference language frontend is built on
// (ref: include/mxnet/c_api.h — MXSymbolCreateFromJSON/Compose family,
// MXExecutorSimpleBindEx, MXKVStoreInit/Push/Pull/PushPullEx,
// MXDataIterCreateIter/Next/GetData/GetLabel, MXNDArraySave/Load
// :638-672). Same architecture as c_api_runtime.cc: entry points
// marshal C types, dispatch to mxnet_tpu.c_runtime (embedded CPython),
// which shares the registry/tape/XLA cache with the Python frontend.
//
// Handle model: every handle is a PyObject* (NDArray, Symbol, Executor,
// KVStore, or iterator cursor). The per-family *Free functions all
// Py_DECREF — they exist because the reference ABI names them per
// family and frontends call them by those names.
//
// String/list lifetime: one thread_local return store backs ALL
// string/array-returning entry points, so a returned const char* /
// array stays valid only until the NEXT such ABI call on the same
// thread — copy out before making another call (the reference's
// MXAPIThreadLocalEntry has the same contract,
// ref: src/c_api/c_api_common.h).
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "c_error.h"
#include "py_embed.h"

namespace {

using mxnet_tpu::FailWith;
using mxnet_tpu::pyembed::EnsurePython;
using mxnet_tpu::pyembed::Gil;
using mxnet_tpu::pyembed::PyFail;

PyObject* Runtime() {
  static PyObject* mod = nullptr;  // borrowed forever (module is cached)
  if (mod == nullptr) mod = PyImport_ImportModule("mxnet_tpu.c_runtime");
  return mod;
}

PyObject* CallRt(const char* fn, PyObject* args) {
  PyObject* mod = Runtime();
  if (mod == nullptr) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (f == nullptr) return nullptr;
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return out;
}

PyObject* StrList(const char** strs, uint32_t n) {
  PyObject* lst = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i)
    PyList_SET_ITEM(lst, i, PyUnicode_DecodeLatin1(
        strs[i], strlen(strs[i]), "replace"));
  return lst;
}

PyObject* HandleList(void** handles, uint32_t n) {
  PyObject* lst = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i) {
    PyObject* h = static_cast<PyObject*>(handles[i]);
    Py_INCREF(h);
    PyList_SET_ITEM(lst, i, h);
  }
  return lst;
}

// Thread-local string-list return store (MXAPIThreadLocalEntry analog).
struct RetStore {
  std::vector<std::string> strings;
  std::vector<const char*> charp;
  std::vector<void*> handles;
  std::string str;
  std::vector<int64_t> shape_data;
  std::vector<uint32_t> shape_ndim;
  std::vector<const int64_t*> shape_ptr;
};
thread_local RetStore ret_store;

// Copy a Python list of str into the thread-local store; set *n/*out.
int ReturnStrList(PyObject* res, uint32_t* n, const char*** out,
                  const char* who) {
  if (!PyList_Check(res)) {
    Py_DECREF(res);
    return FailWith(std::string(who) + ": runtime returned non-list");
  }
  Py_ssize_t cnt = PyList_Size(res);
  ret_store.strings.clear();
  ret_store.charp.clear();
  for (Py_ssize_t i = 0; i < cnt; ++i) {
    PyObject* s = PyList_GET_ITEM(res, i);
    Py_ssize_t len = 0;
    const char* c = PyUnicode_AsUTF8AndSize(s, &len);
    if (c == nullptr) {
      Py_DECREF(res);
      return PyFail(who);
    }
    ret_store.strings.emplace_back(c, static_cast<size_t>(len));
  }
  for (auto& s : ret_store.strings) ret_store.charp.push_back(s.c_str());
  *n = static_cast<uint32_t>(cnt);
  *out = ret_store.charp.data();
  Py_DECREF(res);
  return 0;
}

// Build [[d0,d1,...], ...] from flat shape data.
PyObject* ShapeList(uint32_t num, const uint32_t* ndims,
                    const int64_t* flat) {
  PyObject* lst = PyList_New(num);
  size_t off = 0;
  for (uint32_t i = 0; i < num; ++i) {
    PyObject* shp = PyTuple_New(ndims[i]);
    for (uint32_t d = 0; d < ndims[i]; ++d)
      PyTuple_SET_ITEM(shp, d, PyLong_FromLongLong(flat[off + d]));
    off += ndims[i];
    PyList_SET_ITEM(lst, i, shp);
  }
  return lst;
}

// Common tail: return a single new-reference handle.
int ReturnHandle(PyObject* res, void** out, const char* who) {
  if (res == nullptr) return PyFail(who);
  *out = res;
  return 0;
}

// Common tail: ok/None result.
int ReturnOk(PyObject* res, const char* who) {
  if (res == nullptr) return PyFail(who);
  Py_DECREF(res);
  return 0;
}

}  // namespace

extern "C" {

// -- generic + misc ---------------------------------------------------------

int MXTGetVersion(int* out) {
  *out = 10600;
  return 0;
}

int MXTRandomSeed(int seed) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", seed);
  PyObject* res = CallRt("random_seed", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTRandomSeed");
}

int MXTListAllOpNames(uint32_t* out_size, const char*** out_array) {
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("list_all_ops", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTListAllOpNames");
  return ReturnStrList(res, out_size, out_array, "MXTListAllOpNames");
}

// Load an external operator library (ref: MXLoadLib c_api.cc:96).
int MXTLoadLib(const char* path) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", path);
  PyObject* res = CallRt("load_lib", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTLoadLib");
}

// -- Symbol -----------------------------------------------------------------

int MXTSymbolCreateFromJSON(const char* json, void** out) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", json);
  PyObject* res = CallRt("symbol_from_json", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCreateFromJSON");
}

int MXTSymbolCreateFromFile(const char* path, void** out) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", path);
  PyObject* res = CallRt("load_symbol_json", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCreateFromFile");
}

int MXTSymbolSaveToJSON(void* sym, const char** out_json) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_to_json", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolSaveToJSON");
  const char* c = PyUnicode_AsUTF8(res);
  if (c == nullptr) {
    Py_DECREF(res);
    return PyFail("MXTSymbolSaveToJSON");
  }
  ret_store.str = c;
  *out_json = ret_store.str.c_str();
  Py_DECREF(res);
  return 0;
}

int MXTSymbolSaveToFile(void* sym, const char* path) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(sym), path);
  PyObject* res = CallRt("symbol_save", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTSymbolSaveToFile");
}

int MXTSymbolCreateVariable(const char* name, void** out) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", name);
  PyObject* res = CallRt("symbol_var", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCreateVariable");
}

int MXTSymbolCreateAtomicSymbol(const char* op_name, uint32_t num_params,
                                const char** keys, const char** vals,
                                void** out) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(sNN)", op_name,
                                 StrList(keys, num_params),
                                 StrList(vals, num_params));
  PyObject* res = CallRt("symbol_create_atomic", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCreateAtomicSymbol");
}

// keys may be NULL => positional composition (reference semantics).
int MXTSymbolCompose(void* atomic, const char* name, uint32_t num_args,
                     const char** keys, void** args_handles, void** out) {
  Gil gil;
  PyObject* keylist = keys ? StrList(keys, num_args) : PyList_New(0);
  PyObject* args = Py_BuildValue("(OsNN)", static_cast<PyObject*>(atomic),
                                 name ? name : "", keylist,
                                 HandleList(args_handles, num_args));
  PyObject* res = CallRt("symbol_compose", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCompose");
}

int MXTSymbolListArguments(void* sym, uint32_t* out_size,
                           const char*** out_array) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_list_arguments", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolListArguments");
  return ReturnStrList(res, out_size, out_array, "MXTSymbolListArguments");
}

int MXTSymbolListOutputs(void* sym, uint32_t* out_size,
                         const char*** out_array) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_list_outputs", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolListOutputs");
  return ReturnStrList(res, out_size, out_array, "MXTSymbolListOutputs");
}

int MXTSymbolListAuxiliaryStates(void* sym, uint32_t* out_size,
                                 const char*** out_array) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_list_aux", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolListAuxiliaryStates");
  return ReturnStrList(res, out_size, out_array,
                       "MXTSymbolListAuxiliaryStates");
}

int MXTSymbolGetName(void* sym, const char** out_name) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_name", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolGetName");
  const char* c = PyUnicode_AsUTF8(res);
  ret_store.str = c ? c : "";
  *out_name = ret_store.str.c_str();
  Py_DECREF(res);
  return 0;
}

// Infer shapes from provided named input shapes.
// Outputs (valid until next call on this thread): three parallel arrays
// flattened — counts, per-entry ndim, and flat dims — for args, outputs
// and aux in sequence (ref: MXSymbolInferShape's triple return).
int MXTSymbolInferShape(void* sym, uint32_t num_provided,
                        const char** names, const uint32_t* ndims,
                        const int64_t* shapes_flat,
                        uint32_t* arg_count, uint32_t* out_count,
                        uint32_t* aux_count,
                        const uint32_t** all_ndims,
                        const int64_t** all_dims) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(sym),
                                 StrList(names, num_provided),
                                 ShapeList(num_provided, ndims, shapes_flat));
  PyObject* res = CallRt("symbol_infer_shape", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolInferShape");
  // res = ([argshapes], [outshapes], [auxshapes])
  ret_store.shape_ndim.clear();
  ret_store.shape_data.clear();
  uint32_t counts[3] = {0, 0, 0};
  for (int part = 0; part < 3; ++part) {
    PyObject* lst = PyTuple_GET_ITEM(res, part);
    Py_ssize_t cnt = PyList_Size(lst);
    counts[part] = static_cast<uint32_t>(cnt);
    for (Py_ssize_t i = 0; i < cnt; ++i) {
      PyObject* shp = PyList_GET_ITEM(lst, i);
      Py_ssize_t nd = PyTuple_Size(shp);
      ret_store.shape_ndim.push_back(static_cast<uint32_t>(nd));
      for (Py_ssize_t d = 0; d < nd; ++d)
        ret_store.shape_data.push_back(
            PyLong_AsLongLong(PyTuple_GET_ITEM(shp, d)));
    }
  }
  Py_DECREF(res);
  *arg_count = counts[0];
  *out_count = counts[1];
  *aux_count = counts[2];
  *all_ndims = ret_store.shape_ndim.data();
  *all_dims = ret_store.shape_data.data();
  return 0;
}

int MXTSymbolFree(void* sym) {
  if (sym == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(sym));
  return 0;
}

// -- Executor ---------------------------------------------------------------

int MXTExecutorSimpleBind(void* sym, uint32_t num_provided,
                          const char** names, const uint32_t* ndims,
                          const int64_t* shapes_flat,
                          const char* grad_req, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ONNs)", static_cast<PyObject*>(sym),
                                 StrList(names, num_provided),
                                 ShapeList(num_provided, ndims, shapes_flat),
                                 grad_req);
  PyObject* res = CallRt("executor_simple_bind", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTExecutorSimpleBind");
}

int MXTExecutorForward(void* exec, int is_train) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", static_cast<PyObject*>(exec),
                                 is_train);
  PyObject* res = CallRt("executor_forward", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTExecutorForward");
}

int MXTExecutorOutputs(void* exec, uint32_t* num_outputs,
                       void** out_handles, uint32_t max_outputs) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(exec));
  PyObject* res = CallRt("executor_outputs", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTExecutorOutputs");
  Py_ssize_t n = PyList_Size(res);
  if (static_cast<uint32_t>(n) > max_outputs) {
    Py_DECREF(res);
    return FailWith("MXTExecutorOutputs: " + std::to_string(n) +
                    " outputs, caller provided " +
                    std::to_string(max_outputs) + " slots");
  }
  *num_outputs = static_cast<uint32_t>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(res, i);
    Py_INCREF(o);
    out_handles[i] = o;
  }
  Py_DECREF(res);
  return 0;
}

// num_head_grads == 0 => implicit ones (reference backward() semantics).
int MXTExecutorBackward(void* exec, uint32_t num_head_grads,
                        void** head_grads) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(exec),
                                 HandleList(head_grads, num_head_grads));
  PyObject* res = CallRt("executor_backward", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTExecutorBackward");
}

int MXTExecutorArgArray(void* exec, const char* name, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(exec), name);
  PyObject* res = CallRt("executor_arg", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTExecutorArgArray");
}

int MXTExecutorGradArray(void* exec, const char* name, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(exec), name);
  PyObject* res = CallRt("executor_grad", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTExecutorGradArray");
}

int MXTExecutorAuxArray(void* exec, const char* name, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(exec), name);
  PyObject* res = CallRt("executor_aux", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTExecutorAuxArray");
}

int MXTExecutorFree(void* exec) {
  if (exec == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(exec));
  return 0;
}

// -- CachedOp ---------------------------------------------------------------
// The jit seam as a C surface (ref: include/mxnet/c_api.h:1241
// MXCreateCachedOp / :1257 MXInvokeCachedOp / :1252 MXFreeCachedOp):
// a Symbol compiles once per input signature; repeat invocations with
// the same shapes/dtypes reuse the XLA executable. GetStats exposes the
// (calls, compiles) counters so callers can assert cache behavior.

int MXTCachedOpCreate(void* sym, uint32_t num_flags, const char** keys,
                      const char** vals, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(sym),
                                 StrList(keys, num_flags),
                                 StrList(vals, num_flags));
  PyObject* res = CallRt("cachedop_create", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTCachedOpCreate");
}

int MXTCachedOpInvoke(void* op, uint32_t num_inputs, void** inputs,
                      uint32_t* num_outputs, void** out_handles,
                      uint32_t max_outputs) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(op),
                                 HandleList(inputs, num_inputs));
  PyObject* res = CallRt("cachedop_invoke", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTCachedOpInvoke");
  Py_ssize_t n = PyList_Size(res);
  if (static_cast<uint32_t>(n) > max_outputs) {
    Py_DECREF(res);
    return FailWith("MXTCachedOpInvoke: " + std::to_string(n) +
                    " outputs, caller provided " +
                    std::to_string(max_outputs) + " slots");
  }
  *num_outputs = static_cast<uint32_t>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(res, i);
    Py_INCREF(o);
    out_handles[i] = o;
  }
  Py_DECREF(res);
  return 0;
}

int MXTCachedOpGetStats(void* op, uint64_t* calls, uint64_t* compiles) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(op));
  PyObject* res = CallRt("cachedop_stats", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTCachedOpGetStats");
  unsigned long long c = 0, m = 0;
  if (!PyArg_ParseTuple(res, "KK", &c, &m)) {
    Py_DECREF(res);
    return PyFail("MXTCachedOpGetStats");
  }
  Py_DECREF(res);
  *calls = c;
  *compiles = m;
  return 0;
}

int MXTCachedOpFree(void* op) {
  if (op == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(op));
  return 0;
}

// -- KVStore ----------------------------------------------------------------

int MXTKVStoreCreate(const char* type, void** out) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", type);
  PyObject* res = CallRt("kv_create", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTKVStoreCreate");
}

int MXTKVStoreInit(void* kv, int key, void* nd) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OiO)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(nd));
  PyObject* res = CallRt("kv_init", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStoreInit");
}

int MXTKVStoreInitEx(void* kv, const char* key, void* nd) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OsO)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(nd));
  PyObject* res = CallRt("kv_init", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStoreInitEx");
}

int MXTKVStorePush(void* kv, int key, void* nd, int priority) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OiOi)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(nd), priority);
  PyObject* res = CallRt("kv_push", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStorePush");
}

int MXTKVStorePushEx(void* kv, const char* key, void* nd, int priority) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OsOi)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(nd), priority);
  PyObject* res = CallRt("kv_push", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStorePushEx");
}

int MXTKVStorePull(void* kv, int key, void* out_nd, int priority) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OiOi)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(out_nd), priority);
  PyObject* res = CallRt("kv_pull", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStorePull");
}

int MXTKVStorePullEx(void* kv, const char* key, void* out_nd, int priority) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OsOi)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(out_nd), priority);
  PyObject* res = CallRt("kv_pull", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStorePullEx");
}

// Fused push+pull (ref: MXKVStorePushPullEx) — in/out may alias.
int MXTKVStorePushPull(void* kv, int key, void* in_nd, void* out_nd,
                       int priority) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OiOOi)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(in_nd),
                                 static_cast<PyObject*>(out_nd), priority);
  PyObject* res = CallRt("kv_pushpull", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStorePushPull");
}

int MXTKVStoreGetRank(void* kv, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(kv));
  PyObject* res = CallRt("kv_rank", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTKVStoreGetRank");
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTKVStoreGetGroupSize(void* kv, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(kv));
  PyObject* res = CallRt("kv_size", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTKVStoreGetGroupSize");
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTKVStoreGetType(void* kv, const char** out_type) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(kv));
  PyObject* res = CallRt("kv_type", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTKVStoreGetType");
  const char* c = PyUnicode_AsUTF8(res);
  ret_store.str = c ? c : "";
  *out_type = ret_store.str.c_str();
  Py_DECREF(res);
  return 0;
}

// Build the optimizer server-side from name+params — the C-frontend
// analog of the pickled-optimizer UX (ref: MXKVStoreSetOptimizer /
// kvstore_server.py _controller).
int MXTKVStoreSetOptimizer(void* kv, const char* opt_name,
                           uint32_t num_params, const char** keys,
                           const char** vals) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OsNN)", static_cast<PyObject*>(kv),
                                 opt_name, StrList(keys, num_params),
                                 StrList(vals, num_params));
  PyObject* res = CallRt("kv_set_optimizer", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStoreSetOptimizer");
}

// Global barrier across workers (ref: MXKVStoreBarrier /
// ps::Postoffice::Barrier).
int MXTKVStoreBarrier(void* kv) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(kv));
  PyObject* res = CallRt("kv_barrier", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStoreBarrier");
}

int MXTKVStoreFree(void* kv) {
  if (kv == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(kv));
  return 0;
}

// -- DataIter ---------------------------------------------------------------

int MXTListDataIters(uint32_t* out_size, const char*** out_array) {
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("list_data_iters", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTListDataIters");
  return ReturnStrList(res, out_size, out_array, "MXTListDataIters");
}

int MXTDataIterCreate(const char* name, uint32_t num_params,
                      const char** keys, const char** vals, void** out) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(sNN)", name, StrList(keys, num_params),
                                 StrList(vals, num_params));
  PyObject* res = CallRt("data_iter_create", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTDataIterCreate");
}

int MXTDataIterNext(void* iter, int* out_more) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(iter));
  PyObject* res = CallRt("data_iter_next", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTDataIterNext");
  *out_more = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTDataIterGetData(void* iter, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(iter));
  PyObject* res = CallRt("data_iter_data", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTDataIterGetData");
}

int MXTDataIterGetLabel(void* iter, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(iter));
  PyObject* res = CallRt("data_iter_label", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTDataIterGetLabel");
}

int MXTDataIterBeforeFirst(void* iter) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(iter));
  PyObject* res = CallRt("data_iter_reset", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTDataIterBeforeFirst");
}

int MXTDataIterFree(void* iter) {
  if (iter == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(iter));
  return 0;
}

// -- NDArray save/load + in-place copy --------------------------------------

// names may be NULL => unnamed records (ref: MXNDArraySave c_api.h:659).
int MXTNDArraySave(const char* fname, uint32_t num, void** handles,
                   const char** names) {
  Gil gil;
  PyObject* namelist = names ? StrList(names, num) : PyList_New(0);
  PyObject* args = Py_BuildValue("(sNN)", fname, HandleList(handles, num),
                                 namelist);
  PyObject* res = CallRt("nd_save", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTNDArraySave");
}

// Returned handle/name arrays stay valid until the next Load on this
// thread; handles are owned by the caller (free each with
// MXTNDArrayFree). (ref: MXNDArrayLoad c_api.h:672)
int MXTNDArrayLoad(const char* fname, uint32_t* out_size, void*** out_arr,
                   uint32_t* out_name_size, const char*** out_names) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", fname);
  PyObject* res = CallRt("nd_load", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArrayLoad");
  PyObject* names = PyTuple_GET_ITEM(res, 0);
  PyObject* arrays = PyTuple_GET_ITEM(res, 1);
  Py_ssize_t nn = PyList_Size(names);
  Py_ssize_t na = PyList_Size(arrays);
  ret_store.strings.clear();
  ret_store.charp.clear();
  ret_store.handles.clear();
  for (Py_ssize_t i = 0; i < nn; ++i) {
    const char* c = PyUnicode_AsUTF8(PyList_GET_ITEM(names, i));
    ret_store.strings.emplace_back(c ? c : "");
  }
  for (auto& s : ret_store.strings) ret_store.charp.push_back(s.c_str());
  for (Py_ssize_t i = 0; i < na; ++i) {
    PyObject* a = PyList_GET_ITEM(arrays, i);
    Py_INCREF(a);
    ret_store.handles.push_back(a);
  }
  Py_DECREF(res);
  *out_size = static_cast<uint32_t>(na);
  *out_arr = ret_store.handles.data();
  *out_name_size = static_cast<uint32_t>(nn);
  *out_names = ret_store.charp.data();
  return 0;
}

int MXTNDArraySyncCopyFromCPU(void* handle, const void* data,
                              size_t nbytes) {
  Gil gil;
  PyObject* raw = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), static_cast<Py_ssize_t>(nbytes));
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(handle),
                                 raw);
  PyObject* res = CallRt("copy_from_bytes", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTNDArraySyncCopyFromCPU");
}

// -- NDArray views (ref: MXNDArrayReshape/Slice/At c_api.h) -----------------

int MXTNDArrayReshape(void* handle, uint32_t ndim, const int64_t* dims,
                      void** out) {
  Gil gil;
  PyObject* shp = PyList_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i)
    PyList_SET_ITEM(shp, i, PyLong_FromLongLong(dims[i]));
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(handle),
                                 shp);
  PyObject* res = CallRt("nd_reshape", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTNDArrayReshape");
}

int MXTNDArraySlice(void* handle, int64_t begin, int64_t end, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OLL)", static_cast<PyObject*>(handle),
                                 static_cast<long long>(begin),
                                 static_cast<long long>(end));
  PyObject* res = CallRt("nd_slice", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTNDArraySlice");
}

int MXTNDArrayAt(void* handle, int64_t idx, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OL)", static_cast<PyObject*>(handle),
                                 static_cast<long long>(idx));
  PyObject* res = CallRt("nd_at", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTNDArrayAt");
}

// -- autograd flags (ref: MXAutogradIsRecording/IsTraining/SetIsTraining) ---

int MXTAutogradIsRecording(int* out) {
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("autograd_is_recording", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTAutogradIsRecording");
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTAutogradIsTraining(int* out) {
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("autograd_is_training", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTAutogradIsTraining");
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXTAutogradSetIsTraining(int train_mode) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", train_mode);
  PyObject* res = CallRt("autograd_set_training", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTAutogradSetIsTraining");
}

// -- profiler (ref: MXSetProcessProfilerConfig/State, MXDumpProfile) --------

int MXTProfileSetConfig(uint32_t num_params, const char** keys,
                        const char** vals) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(NN)", StrList(keys, num_params),
                                 StrList(vals, num_params));
  PyObject* res = CallRt("profiler_set_config", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileSetConfig");
}

int MXTProfileSetState(int state) {  // 0 = stop, 1 = run
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", state);
  PyObject* res = CallRt("profiler_set_state", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileSetState");
}

int MXTProfileDump() {
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("profiler_dump", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileDump");
}

// -- Symbol attrs / views (ref: MXSymbolGetAttr/SetAttr/ListAttr,
//    MXSymbolGetInternals/GetOutput, MXSymbolCopy) --------------------------

int MXTSymbolGetAttr(void* sym, const char* key, const char** out,
                     int* success) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(sym), key);
  PyObject* res = CallRt("symbol_attr", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolGetAttr");
  if (res == Py_None) {  // attr missing (an empty string is PRESENT)
    Py_DECREF(res);
    ret_store.str.clear();
    *out = ret_store.str.c_str();
    *success = 0;
    return 0;
  }
  const char* c = PyUnicode_AsUTF8(res);
  if (c == nullptr) {
    Py_DECREF(res);
    return PyFail("MXTSymbolGetAttr");
  }
  ret_store.str = c;
  Py_DECREF(res);
  *success = 1;
  *out = ret_store.str.c_str();
  return 0;
}

int MXTSymbolSetAttr(void* sym, const char* key, const char* value) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oss)", static_cast<PyObject*>(sym),
                                 key, value);
  PyObject* res = CallRt("symbol_set_attr", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTSymbolSetAttr");
}

// JSON object {node: {key: value}} — one call instead of the
// reference's paired size/array outputs.
int MXTSymbolListAttr(void* sym, const char** out_json) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_attr_json", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolListAttr");
  const char* c = PyUnicode_AsUTF8(res);
  if (c == nullptr) {
    Py_DECREF(res);
    return PyFail("MXTSymbolListAttr");
  }
  ret_store.str = c;
  *out_json = ret_store.str.c_str();
  Py_DECREF(res);
  return 0;
}

int MXTSymbolGetInternals(void* sym, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_get_internals", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolGetInternals");
}

int MXTSymbolGetOutput(void* sym, uint32_t index, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OI)", static_cast<PyObject*>(sym),
                                 index);
  PyObject* res = CallRt("symbol_get_output", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolGetOutput");
}

int MXTSymbolCopy(void* sym, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_copy", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCopy");
}

// Device-side value copy dst <- src (no host round trip; ref:
// MXNDArraySyncCopyFromNDArray).
int MXTNDArrayCopyFrom(void* dst, void* src) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OO)", static_cast<PyObject*>(dst),
                                 static_cast<PyObject*>(src));
  PyObject* res = CallRt("set_data", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTNDArrayCopyFrom");
}

int MXTNDArrayGetDType(void* handle, int* out_dtype) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("dtype_of", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArrayGetDType");
  *out_dtype = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

}  // extern "C"

// ===== round-4 ABI long tail (VERDICT r3 item 3) ===========================
// Mechanical completions of reference families whose functionality already
// exists in the runtime: per-array waits, context/storage queries, symbol
// introspection, executor bind/reshape/print, KVStore role/row-sparse/
// compression, the MXProfile* object family, engine/bulk, libinfo,
// numpy-shape toggles, device queries, PS env, and autograd symbol
// extraction. Ref lines: include/mxnet/c_api.h for each MX name minus the
// leading T.

namespace {

int ReturnInt(PyObject* res, int* out, const char* who) {
  if (res == nullptr) return PyFail(who);
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  if (PyErr_Occurred()) return PyFail(who);
  return 0;
}

int ReturnStr(PyObject* res, const char** out, const char* who) {
  if (res == nullptr) return PyFail(who);
  const char* c = PyUnicode_AsUTF8(res);
  if (c == nullptr) {
    Py_DECREF(res);
    return PyFail(who);
  }
  ret_store.str = c;
  *out = ret_store.str.c_str();
  Py_DECREF(res);
  return 0;
}

}  // namespace

extern "C" {

// -- NDArray ----------------------------------------------------------------

int MXTNDArrayWaitToRead(void* handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("nd_wait", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTNDArrayWaitToRead");
}

int MXTNDArrayWaitToWrite(void* handle) {
  return MXTNDArrayWaitToRead(handle);
}

int MXTNDArrayDetach(void* handle, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("nd_detach", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTNDArrayDetach");
}

int MXTNDArrayGetContext(void* handle, int* out_dev_type, int* out_dev_id) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("nd_context", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArrayGetContext");
  if (!PyArg_ParseTuple(res, "ii", out_dev_type, out_dev_id)) {
    Py_DECREF(res);
    return PyFail("MXTNDArrayGetContext");
  }
  Py_DECREF(res);
  return 0;
}

int MXTNDArrayGetStorageType(void* handle, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("nd_storage_type", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTNDArrayGetStorageType");
}

int MXTNDArrayCreateNone(void** out) {
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("nd_none", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTNDArrayCreateNone");
}

int MXTShallowCopyNDArray(void* handle, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("nd_shallow_copy", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTShallowCopyNDArray");
}

int MXTNDArrayLoadFromBuffer(const void* buf, size_t size,
                             uint32_t* out_size, void*** out_arr,
                             uint32_t* out_name_size,
                             const char*** out_names) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(y#)", static_cast<const char*>(buf),
      static_cast<Py_ssize_t>(size));
  PyObject* res = CallRt("nd_load_from_buffer", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArrayLoadFromBuffer");
  PyObject* names = PyTuple_GetItem(res, 0);
  PyObject* arrs = PyTuple_GetItem(res, 1);
  if (names == nullptr || arrs == nullptr) {
    Py_DECREF(res);
    return PyFail("MXTNDArrayLoadFromBuffer");
  }
  ret_store.strings.clear();
  ret_store.charp.clear();
  ret_store.handles.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(names); ++i)
    ret_store.strings.emplace_back(
        PyUnicode_AsUTF8(PyList_GET_ITEM(names, i)));
  for (auto& s : ret_store.strings) ret_store.charp.push_back(s.c_str());
  for (Py_ssize_t i = 0; i < PyList_Size(arrs); ++i) {
    PyObject* a = PyList_GET_ITEM(arrs, i);
    Py_INCREF(a);
    ret_store.handles.push_back(a);
  }
  *out_name_size = static_cast<uint32_t>(ret_store.charp.size());
  *out_names = ret_store.charp.data();
  *out_size = static_cast<uint32_t>(ret_store.handles.size());
  *out_arr = ret_store.handles.data();
  Py_DECREF(res);
  return 0;
}

// -- Symbol -----------------------------------------------------------------

int MXTSymbolCreateGroup(uint32_t num_symbols, void** symbols, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(N)", HandleList(symbols, num_symbols));
  PyObject* res = CallRt("symbol_group", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCreateGroup");
}

int MXTSymbolGetNumOutputs(void* sym, uint32_t* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_num_outputs", args);
  Py_DECREF(args);
  int v = 0;
  int rc = ReturnInt(res, &v, "MXTSymbolGetNumOutputs");
  *out = static_cast<uint32_t>(v);
  return rc;
}

int MXTSymbolPrint(void* sym, const char** out_str) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_print", args);
  Py_DECREF(args);
  return ReturnStr(res, out_str, "MXTSymbolPrint");
}

int MXTSymbolGetChildren(void* sym, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_get_children", args);
  Py_DECREF(args);
  if (res == Py_None) {
    Py_DECREF(res);
    *out = nullptr;
    return 0;
  }
  return ReturnHandle(res, out, "MXTSymbolGetChildren");
}

int MXTSymbolGetInputSymbols(void* sym, void** out_handles,
                             uint32_t max_inputs, int* out_size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_get_inputs", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolGetInputSymbols");
  Py_ssize_t n = PyList_Size(res);
  if (static_cast<uint32_t>(n) > max_inputs) {
    Py_DECREF(res);
    return FailWith("MXTSymbolGetInputSymbols: too many inputs");
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(res, i);
    Py_INCREF(o);
    out_handles[i] = o;
  }
  *out_size = static_cast<int>(n);
  Py_DECREF(res);
  return 0;
}

int MXTSymbolGetAtomicSymbolName(void* sym, const char** out_name) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_atomic_name", args);
  Py_DECREF(args);
  return ReturnStr(res, out_name, "MXTSymbolGetAtomicSymbolName");
}

int MXTSymbolListAttrShallow(void* sym, uint32_t* out_size,
                             const char*** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_attrs_shallow", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolListAttrShallow");
  return ReturnStrList(res, out_size, out, "MXTSymbolListAttrShallow");
}

int MXTShallowCopySymbol(void* sym, void** out) {
  if (sym == nullptr) return FailWith("null symbol");
  Gil gil;
  Py_INCREF(static_cast<PyObject*>(sym));
  *out = sym;
  return 0;
}

int MXTSymbolInferShapePartial(void* sym, uint32_t num_provided,
                               const char** names, const uint32_t* ndims,
                               const int64_t* shapes_flat,
                               uint32_t* arg_count, uint32_t* out_count,
                               uint32_t* aux_count,
                               const uint32_t** all_ndims,
                               const int64_t** all_dims) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(sym),
                                 StrList(names, num_provided),
                                 ShapeList(num_provided, ndims, shapes_flat));
  PyObject* res = CallRt("symbol_infer_shape_partial", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolInferShapePartial");
  ret_store.shape_ndim.clear();
  ret_store.shape_data.clear();
  uint32_t counts[3] = {0, 0, 0};
  for (int part = 0; part < 3; ++part) {
    PyObject* lst = PyTuple_GET_ITEM(res, part);
    Py_ssize_t cnt = PyList_Size(lst);
    counts[part] = static_cast<uint32_t>(cnt);
    for (Py_ssize_t i = 0; i < cnt; ++i) {
      PyObject* shp = PyList_GET_ITEM(lst, i);
      Py_ssize_t nd = PyTuple_Size(shp);
      ret_store.shape_ndim.push_back(static_cast<uint32_t>(nd));
      for (Py_ssize_t d = 0; d < nd; ++d)
        ret_store.shape_data.push_back(
            PyLong_AsLongLong(PyTuple_GET_ITEM(shp, d)));
    }
  }
  Py_DECREF(res);
  *arg_count = counts[0];
  *out_count = counts[1];
  *aux_count = counts[2];
  *all_ndims = ret_store.shape_ndim.data();
  *all_dims = ret_store.shape_data.data();
  return 0;
}

int MXTSymbolInferType(void* sym, uint32_t num_provided, const char** names,
                       const int* dtypes, uint32_t* arg_count,
                       const int** arg_types, uint32_t* out_count,
                       const int** out_types, uint32_t* aux_count,
                       const int** aux_types) {
  Gil gil;
  PyObject* dt = PyList_New(num_provided);
  for (uint32_t i = 0; i < num_provided; ++i)
    PyList_SET_ITEM(dt, i, PyLong_FromLong(dtypes[i]));
  PyObject* args = Py_BuildValue("(ONNi)", static_cast<PyObject*>(sym),
                                 StrList(names, num_provided), dt, 0);
  PyObject* res = CallRt("symbol_infer_type", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolInferType");
  static thread_local std::vector<int> arg_v, out_v, aux_v;
  arg_v.clear(); out_v.clear(); aux_v.clear();
  std::vector<int>* dsts[3] = {&arg_v, &out_v, &aux_v};
  for (int part = 0; part < 3; ++part) {
    PyObject* lst = PyTuple_GetItem(res, part);
    for (Py_ssize_t i = 0; i < PyList_Size(lst); ++i)
      dsts[part]->push_back(
          static_cast<int>(PyLong_AsLong(PyList_GET_ITEM(lst, i))));
  }
  Py_DECREF(res);
  *arg_count = static_cast<uint32_t>(arg_v.size());
  *arg_types = arg_v.data();
  *out_count = static_cast<uint32_t>(out_v.size());
  *out_types = out_v.data();
  *aux_count = static_cast<uint32_t>(aux_v.size());
  *aux_types = aux_v.data();
  return 0;
}

// -- Executor ---------------------------------------------------------------

int MXTExecutorPrint(void* exec, const char** out_str) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(exec));
  PyObject* res = CallRt("executor_print", args);
  Py_DECREF(args);
  return ReturnStr(res, out_str, "MXTExecutorPrint");
}

int MXTExecutorReshape(void* exec, uint32_t num_provided,
                       const char** names, const uint32_t* ndims,
                       const int64_t* shapes_flat, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(exec),
                                 StrList(names, num_provided),
                                 ShapeList(num_provided, ndims, shapes_flat));
  PyObject* res = CallRt("executor_reshape", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTExecutorReshape");
}

int MXTExecutorBind(void* sym, uint32_t num_args, const char** names,
                    void** arg_handles, const char* grad_req, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ONNs)", static_cast<PyObject*>(sym),
                                 StrList(names, num_args),
                                 HandleList(arg_handles, num_args),
                                 grad_req);
  PyObject* res = CallRt("executor_bind", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTExecutorBind");
}

// -- KVStore ----------------------------------------------------------------

int MXTKVStoreIsWorkerNode(int* out) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", "worker");
  PyObject* res = CallRt("kv_role", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTKVStoreIsWorkerNode");
}

int MXTKVStoreIsServerNode(int* out) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", "server");
  PyObject* res = CallRt("kv_role", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTKVStoreIsServerNode");
}

int MXTKVStoreIsSchedulerNode(int* out) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", "scheduler");
  PyObject* res = CallRt("kv_role", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTKVStoreIsSchedulerNode");
}

int MXTKVStoreGetNumDeadNode(void* kv, int node_id, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", static_cast<PyObject*>(kv),
                                 node_id);
  PyObject* res = CallRt("kv_num_dead", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTKVStoreGetNumDeadNode");
}

int MXTKVStoreSetGradientCompression(void* kv, uint32_t num_params,
                                     const char** keys,
                                     const char** vals) {
  Gil gil;
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(kv),
                                 StrList(keys, num_params),
                                 StrList(vals, num_params));
  PyObject* res = CallRt("kv_set_gradient_compression", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStoreSetGradientCompression");
}

int MXTKVStorePullRowSparse(void* kv, const char* key, void* row_ids,
                            void* out_arr) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OsOO)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(row_ids),
                                 static_cast<PyObject*>(out_arr));
  PyObject* res = CallRt("kv_pull_row_sparse", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStorePullRowSparse");
}

int MXTNotifyShutdown(void) {
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("notify_shutdown", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTNotifyShutdown");
}

int MXTInitPSEnv(uint32_t num_vars, const char** keys, const char** vals) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(NN)", StrList(keys, num_vars),
                                 StrList(vals, num_vars));
  PyObject* res = CallRt("init_ps_env", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTInitPSEnv");
}

// -- Profiler object family -------------------------------------------------

static int ProfileCreate(const char* kind, void* domain, const char* name,
                         void** out, const char* who) {
  EnsurePython();
  Gil gil;
  PyObject* dom = domain ? static_cast<PyObject*>(domain) : Py_None;
  PyObject* args = Py_BuildValue("(sOs)", kind, dom, name);
  PyObject* res = CallRt("profile_create", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, who);
}

int MXTProfileCreateDomain(const char* name, void** out) {
  return ProfileCreate("domain", nullptr, name, out,
                       "MXTProfileCreateDomain");
}

int MXTProfileCreateTask(void* domain, const char* name, void** out) {
  return ProfileCreate("task", domain, name, out, "MXTProfileCreateTask");
}

int MXTProfileCreateFrame(void* domain, const char* name, void** out) {
  return ProfileCreate("frame", domain, name, out,
                       "MXTProfileCreateFrame");
}

int MXTProfileCreateEvent(const char* name, void** out) {
  return ProfileCreate("event", nullptr, name, out,
                       "MXTProfileCreateEvent");
}

int MXTProfileCreateCounter(void* domain, const char* name, void** out) {
  return ProfileCreate("counter", domain, name, out,
                       "MXTProfileCreateCounter");
}

int MXTProfileDestroyHandle(void* handle) {
  if (handle == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
}

int MXTProfileDurationStart(void* handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", static_cast<PyObject*>(handle), 1);
  PyObject* res = CallRt("profile_duration", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileDurationStart");
}

int MXTProfileDurationStop(void* handle) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", static_cast<PyObject*>(handle), 0);
  PyObject* res = CallRt("profile_duration", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileDurationStop");
}

int MXTProfileSetCounter(void* handle, uint64_t value) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OK)", static_cast<PyObject*>(handle),
                                 static_cast<unsigned long long>(value));
  PyObject* res = CallRt("profile_counter_set", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileSetCounter");
}

int MXTProfileAdjustCounter(void* handle, int64_t delta) {
  Gil gil;
  PyObject* args = Py_BuildValue("(OL)", static_cast<PyObject*>(handle),
                                 static_cast<long long>(delta));
  PyObject* res = CallRt("profile_counter_adjust", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileAdjustCounter");
}

int MXTProfileSetMarker(void* domain, const char* name,
                        const char* scope) {
  Gil gil;
  PyObject* args = Py_BuildValue("(Oss)", static_cast<PyObject*>(domain),
                                 name, scope ? scope : "process");
  PyObject* res = CallRt("profile_set_marker", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileSetMarker");
}

int MXTProfilePause(int paused) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", paused);
  PyObject* res = CallRt("profile_pause", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfilePause");
}

int MXTAggregateProfileStatsPrint(const char** out_str, int reset,
                                  const char* format, const char* sort_by,
                                  int ascending) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(issi)", reset, format ? format : "table",
                                 sort_by ? sort_by : "total", ascending);
  PyObject* res = CallRt("profile_aggregate_stats", args);
  Py_DECREF(args);
  return ReturnStr(res, out_str, "MXTAggregateProfileStatsPrint");
}

// -- misc -------------------------------------------------------------------

int MXTEngineSetBulkSize(int bulk_size, int* prev_bulk_size) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", bulk_size);
  PyObject* res = CallRt("engine_set_bulk_size", args);
  Py_DECREF(args);
  return ReturnInt(res, prev_bulk_size, "MXTEngineSetBulkSize");
}

int MXTLibInfoFeatures(uint32_t* out_size, const char*** out_pairs) {
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("lib_info_features", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTLibInfoFeatures");
  return ReturnStrList(res, out_size, out_pairs, "MXTLibInfoFeatures");
}

int MXTRandomSeedContext(int seed, int dev_type, int dev_id) {
  (void)dev_type;
  (void)dev_id;
  return MXTRandomSeed(seed);
}

int MXTIsNumpyShape(int* out) {
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("np_shape_is", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTIsNumpyShape");
}

int MXTSetIsNumpyShape(int is_np_shape, int* prev) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", is_np_shape);
  PyObject* res = CallRt("np_shape_set", args);
  Py_DECREF(args);
  return ReturnInt(res, prev, "MXTSetIsNumpyShape");
}

// "GPU" in the reference ABI = the accelerator; here that is the TPU
// fleet PJRT exposes (ref: MXGetGPUCount / MXGetGPUMemoryInformation64).
int MXTGetGPUCount(int* out) {
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("device_count", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTGetGPUCount");
}

int MXTGetGPUMemoryInformation(int dev_id, uint64_t* free_mem,
                               uint64_t* total_mem) {
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", dev_id);
  PyObject* res = CallRt("device_memory_info", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTGetGPUMemoryInformation");
  unsigned long long f = 0, t = 0;
  if (!PyArg_ParseTuple(res, "KK", &f, &t)) {
    Py_DECREF(res);
    return PyFail("MXTGetGPUMemoryInformation");
  }
  Py_DECREF(res);
  *free_mem = f;
  *total_mem = t;
  return 0;
}

int MXTDataIterGetPadNum(void* iter, int* out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(iter));
  PyObject* res = CallRt("dataiter_pad", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTDataIterGetPadNum");
}

int MXTDataIterGetIndex(void* iter, uint64_t** out_index,
                        uint64_t* out_size) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(iter));
  PyObject* res = CallRt("dataiter_index", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTDataIterGetIndex");
  static thread_local std::vector<uint64_t> idx;
  idx.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i)
    idx.push_back(static_cast<uint64_t>(
        PyLong_AsUnsignedLongLong(PyList_GET_ITEM(res, i))));
  Py_DECREF(res);
  *out_size = idx.size();
  *out_index = idx.data();
  return 0;
}

int MXTAutogradComputeGradient(uint32_t num_output, void** output_handles) {
  Gil gil;
  PyObject* args = Py_BuildValue("(N)",
                                 HandleList(output_handles, num_output));
  PyObject* res = CallRt("backward", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTAutogradComputeGradient");
}

int MXTStorageEmptyCache(int dev_type, int dev_id) {
  (void)dev_type;
  (void)dev_id;
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("storage_empty_cache", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTStorageEmptyCache");
}

}  // extern "C"
