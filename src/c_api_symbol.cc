// Frontier C ABI: Symbol, Executor, KVStore, DataIter, NDArray save/load.
//
// Widens the training surface of c_api_runtime.cc to the full set of
// families every reference language frontend is built on
// (ref: include/mxnet/c_api.h — MXSymbolCreateFromJSON/Compose family,
// MXExecutorSimpleBindEx, MXKVStoreInit/Push/Pull/PushPullEx,
// MXDataIterCreateIter/Next/GetData/GetLabel, MXNDArraySave/Load
// :638-672). Same architecture as c_api_runtime.cc: entry points
// marshal C types, dispatch to mxnet_tpu.c_runtime (embedded CPython),
// which shares the registry/tape/XLA cache with the Python frontend.
//
// Handle model: every handle is a PyObject* (NDArray, Symbol, Executor,
// KVStore, or iterator cursor). The per-family *Free functions all
// Py_DECREF — they exist because the reference ABI names them per
// family and frontends call them by those names.
//
// String/list lifetime: one thread_local return store backs ALL
// string/array-returning entry points, so a returned const char* /
// array stays valid only until the NEXT such ABI call on the same
// thread — copy out before making another call (the reference's
// MXAPIThreadLocalEntry has the same contract,
// ref: src/c_api/c_api_common.h).
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "c_error.h"
#include "py_embed.h"

// Exception->errno translation on every entry point (mxlint MX006):
// a C++ exception crossing the C ABI is UB; the macros turn it
// into the -1/MXTGetLastError() contract (see c_error.h).
#define API_BEGIN MXT_API_BEGIN
#define API_END MXT_API_END

namespace {

using mxnet_tpu::FailWith;
using mxnet_tpu::pyembed::EnsurePython;
using mxnet_tpu::pyembed::Gil;
using mxnet_tpu::pyembed::PyFail;

PyObject* Runtime() {
  static PyObject* mod = nullptr;  // borrowed forever (module is cached)
  if (mod == nullptr) mod = PyImport_ImportModule("mxnet_tpu.c_runtime");
  return mod;
}

PyObject* CallRt(const char* fn, PyObject* args) {
  PyObject* mod = Runtime();
  if (mod == nullptr) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (f == nullptr) return nullptr;
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return out;
}

PyObject* StrList(const char** strs, uint32_t n) {
  PyObject* lst = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i)
    PyList_SET_ITEM(lst, i, PyUnicode_DecodeLatin1(
        strs[i], strlen(strs[i]), "replace"));
  return lst;
}

PyObject* HandleList(void** handles, uint32_t n) {
  PyObject* lst = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i) {
    PyObject* h = static_cast<PyObject*>(handles[i]);
    Py_INCREF(h);
    PyList_SET_ITEM(lst, i, h);
  }
  return lst;
}

// Thread-local string-list return store (MXAPIThreadLocalEntry analog).
struct RetStore {
  std::vector<std::string> strings;
  std::vector<const char*> charp;
  std::vector<void*> handles;
  std::string str;
  std::vector<int64_t> shape_data;
  std::vector<uint32_t> shape_ndim;
  std::vector<const int64_t*> shape_ptr;
};
thread_local RetStore ret_store;

// Copy a Python list of str into the thread-local store; set *n/*out.
int ReturnStrList(PyObject* res, uint32_t* n, const char*** out,
                  const char* who) {
  if (!PyList_Check(res)) {
    Py_DECREF(res);
    return FailWith(std::string(who) + ": runtime returned non-list");
  }
  Py_ssize_t cnt = PyList_Size(res);
  ret_store.strings.clear();
  ret_store.charp.clear();
  for (Py_ssize_t i = 0; i < cnt; ++i) {
    PyObject* s = PyList_GET_ITEM(res, i);
    Py_ssize_t len = 0;
    const char* c = PyUnicode_AsUTF8AndSize(s, &len);
    if (c == nullptr) {
      Py_DECREF(res);
      return PyFail(who);
    }
    ret_store.strings.emplace_back(c, static_cast<size_t>(len));
  }
  for (auto& s : ret_store.strings) ret_store.charp.push_back(s.c_str());
  *n = static_cast<uint32_t>(cnt);
  *out = ret_store.charp.data();
  Py_DECREF(res);
  return 0;
}

// Build [[d0,d1,...], ...] from flat shape data.
PyObject* ShapeList(uint32_t num, const uint32_t* ndims,
                    const int64_t* flat) {
  PyObject* lst = PyList_New(num);
  size_t off = 0;
  for (uint32_t i = 0; i < num; ++i) {
    PyObject* shp = PyTuple_New(ndims[i]);
    for (uint32_t d = 0; d < ndims[i]; ++d)
      PyTuple_SET_ITEM(shp, d, PyLong_FromLongLong(flat[off + d]));
    off += ndims[i];
    PyList_SET_ITEM(lst, i, shp);
  }
  return lst;
}

// Common tail: return a single new-reference handle.
int ReturnHandle(PyObject* res, void** out, const char* who) {
  if (res == nullptr) return PyFail(who);
  *out = res;
  return 0;
}

// Common tail: ok/None result.
int ReturnOk(PyObject* res, const char* who) {
  if (res == nullptr) return PyFail(who);
  Py_DECREF(res);
  return 0;
}

}  // namespace

extern "C" {

// -- generic + misc ---------------------------------------------------------

int MXTGetVersion(int* out) {
  API_BEGIN()
  *out = 10600;
  return 0;
  API_END()
}

int MXTRandomSeed(int seed) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", seed);
  PyObject* res = CallRt("random_seed", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTRandomSeed");
  API_END()
}

int MXTListAllOpNames(uint32_t* out_size, const char*** out_array) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("list_all_ops", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTListAllOpNames");
  return ReturnStrList(res, out_size, out_array, "MXTListAllOpNames");
  API_END()
}

// Load an external operator library (ref: MXLoadLib c_api.cc:96).
int MXTLoadLib(const char* path) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", path);
  PyObject* res = CallRt("load_lib", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTLoadLib");
  API_END()
}

// -- Symbol -----------------------------------------------------------------

int MXTSymbolCreateFromJSON(const char* json, void** out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", json);
  PyObject* res = CallRt("symbol_from_json", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCreateFromJSON");
  API_END()
}

int MXTSymbolCreateFromFile(const char* path, void** out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", path);
  PyObject* res = CallRt("load_symbol_json", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCreateFromFile");
  API_END()
}

int MXTSymbolSaveToJSON(void* sym, const char** out_json) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_to_json", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolSaveToJSON");
  const char* c = PyUnicode_AsUTF8(res);
  if (c == nullptr) {
    Py_DECREF(res);
    return PyFail("MXTSymbolSaveToJSON");
  }
  ret_store.str = c;
  *out_json = ret_store.str.c_str();
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTSymbolSaveToFile(void* sym, const char* path) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(sym), path);
  PyObject* res = CallRt("symbol_save", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTSymbolSaveToFile");
  API_END()
}

int MXTSymbolCreateVariable(const char* name, void** out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", name);
  PyObject* res = CallRt("symbol_var", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCreateVariable");
  API_END()
}

int MXTSymbolCreateAtomicSymbol(const char* op_name, uint32_t num_params,
                                const char** keys, const char** vals,
                                void** out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(sNN)", op_name,
                                 StrList(keys, num_params),
                                 StrList(vals, num_params));
  PyObject* res = CallRt("symbol_create_atomic", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCreateAtomicSymbol");
  API_END()
}

// keys may be NULL => positional composition (reference semantics).
int MXTSymbolCompose(void* atomic, const char* name, uint32_t num_args,
                     const char** keys, void** args_handles, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* keylist = keys ? StrList(keys, num_args) : PyList_New(0);
  PyObject* args = Py_BuildValue("(OsNN)", static_cast<PyObject*>(atomic),
                                 name ? name : "", keylist,
                                 HandleList(args_handles, num_args));
  PyObject* res = CallRt("symbol_compose", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCompose");
  API_END()
}

int MXTSymbolListArguments(void* sym, uint32_t* out_size,
                           const char*** out_array) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_list_arguments", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolListArguments");
  return ReturnStrList(res, out_size, out_array, "MXTSymbolListArguments");
  API_END()
}

int MXTSymbolListOutputs(void* sym, uint32_t* out_size,
                         const char*** out_array) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_list_outputs", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolListOutputs");
  return ReturnStrList(res, out_size, out_array, "MXTSymbolListOutputs");
  API_END()
}

int MXTSymbolListAuxiliaryStates(void* sym, uint32_t* out_size,
                                 const char*** out_array) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_list_aux", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolListAuxiliaryStates");
  return ReturnStrList(res, out_size, out_array,
                       "MXTSymbolListAuxiliaryStates");
  API_END()
}

int MXTSymbolGetName(void* sym, const char** out_name) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_name", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolGetName");
  const char* c = PyUnicode_AsUTF8(res);
  ret_store.str = c ? c : "";
  *out_name = ret_store.str.c_str();
  Py_DECREF(res);
  return 0;
  API_END()
}

// Infer shapes from provided named input shapes.
// Outputs (valid until next call on this thread): three parallel arrays
// flattened — counts, per-entry ndim, and flat dims — for args, outputs
// and aux in sequence (ref: MXSymbolInferShape's triple return).
int MXTSymbolInferShape(void* sym, uint32_t num_provided,
                        const char** names, const uint32_t* ndims,
                        const int64_t* shapes_flat,
                        uint32_t* arg_count, uint32_t* out_count,
                        uint32_t* aux_count,
                        const uint32_t** all_ndims,
                        const int64_t** all_dims) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(sym),
                                 StrList(names, num_provided),
                                 ShapeList(num_provided, ndims, shapes_flat));
  PyObject* res = CallRt("symbol_infer_shape", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolInferShape");
  // res = ([argshapes], [outshapes], [auxshapes])
  ret_store.shape_ndim.clear();
  ret_store.shape_data.clear();
  uint32_t counts[3] = {0, 0, 0};
  for (int part = 0; part < 3; ++part) {
    PyObject* lst = PyTuple_GET_ITEM(res, part);
    Py_ssize_t cnt = PyList_Size(lst);
    counts[part] = static_cast<uint32_t>(cnt);
    for (Py_ssize_t i = 0; i < cnt; ++i) {
      PyObject* shp = PyList_GET_ITEM(lst, i);
      Py_ssize_t nd = PyTuple_Size(shp);
      ret_store.shape_ndim.push_back(static_cast<uint32_t>(nd));
      for (Py_ssize_t d = 0; d < nd; ++d)
        ret_store.shape_data.push_back(
            PyLong_AsLongLong(PyTuple_GET_ITEM(shp, d)));
    }
  }
  Py_DECREF(res);
  *arg_count = counts[0];
  *out_count = counts[1];
  *aux_count = counts[2];
  *all_ndims = ret_store.shape_ndim.data();
  *all_dims = ret_store.shape_data.data();
  return 0;
  API_END()
}

int MXTSymbolFree(void* sym) {
  API_BEGIN()
  if (sym == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(sym));
  return 0;
  API_END()
}

// -- Executor ---------------------------------------------------------------

int MXTExecutorSimpleBind(void* sym, uint32_t num_provided,
                          const char** names, const uint32_t* ndims,
                          const int64_t* shapes_flat,
                          const char* grad_req, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(ONNs)", static_cast<PyObject*>(sym),
                                 StrList(names, num_provided),
                                 ShapeList(num_provided, ndims, shapes_flat),
                                 grad_req);
  PyObject* res = CallRt("executor_simple_bind", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTExecutorSimpleBind");
  API_END()
}

int MXTExecutorForward(void* exec, int is_train) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", static_cast<PyObject*>(exec),
                                 is_train);
  PyObject* res = CallRt("executor_forward", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTExecutorForward");
  API_END()
}

int MXTExecutorOutputs(void* exec, uint32_t* num_outputs,
                       void** out_handles, uint32_t max_outputs) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(exec));
  PyObject* res = CallRt("executor_outputs", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTExecutorOutputs");
  Py_ssize_t n = PyList_Size(res);
  if (static_cast<uint32_t>(n) > max_outputs) {
    Py_DECREF(res);
    return FailWith("MXTExecutorOutputs: " + std::to_string(n) +
                    " outputs, caller provided " +
                    std::to_string(max_outputs) + " slots");
  }
  *num_outputs = static_cast<uint32_t>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(res, i);
    Py_INCREF(o);
    out_handles[i] = o;
  }
  Py_DECREF(res);
  return 0;
  API_END()
}

// num_head_grads == 0 => implicit ones (reference backward() semantics).
int MXTExecutorBackward(void* exec, uint32_t num_head_grads,
                        void** head_grads) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(exec),
                                 HandleList(head_grads, num_head_grads));
  PyObject* res = CallRt("executor_backward", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTExecutorBackward");
  API_END()
}

int MXTExecutorArgArray(void* exec, const char* name, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(exec), name);
  PyObject* res = CallRt("executor_arg", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTExecutorArgArray");
  API_END()
}

int MXTExecutorGradArray(void* exec, const char* name, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(exec), name);
  PyObject* res = CallRt("executor_grad", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTExecutorGradArray");
  API_END()
}

int MXTExecutorAuxArray(void* exec, const char* name, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(exec), name);
  PyObject* res = CallRt("executor_aux", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTExecutorAuxArray");
  API_END()
}

int MXTExecutorFree(void* exec) {
  API_BEGIN()
  if (exec == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(exec));
  return 0;
  API_END()
}

// -- CachedOp ---------------------------------------------------------------
// The jit seam as a C surface (ref: include/mxnet/c_api.h:1241
// MXCreateCachedOp / :1257 MXInvokeCachedOp / :1252 MXFreeCachedOp):
// a Symbol compiles once per input signature; repeat invocations with
// the same shapes/dtypes reuse the XLA executable. GetStats exposes the
// (calls, compiles) counters so callers can assert cache behavior.

int MXTCachedOpCreate(void* sym, uint32_t num_flags, const char** keys,
                      const char** vals, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(sym),
                                 StrList(keys, num_flags),
                                 StrList(vals, num_flags));
  PyObject* res = CallRt("cachedop_create", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTCachedOpCreate");
  API_END()
}

int MXTCachedOpInvoke(void* op, uint32_t num_inputs, void** inputs,
                      uint32_t* num_outputs, void** out_handles,
                      uint32_t max_outputs) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(op),
                                 HandleList(inputs, num_inputs));
  PyObject* res = CallRt("cachedop_invoke", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTCachedOpInvoke");
  Py_ssize_t n = PyList_Size(res);
  if (static_cast<uint32_t>(n) > max_outputs) {
    Py_DECREF(res);
    return FailWith("MXTCachedOpInvoke: " + std::to_string(n) +
                    " outputs, caller provided " +
                    std::to_string(max_outputs) + " slots");
  }
  *num_outputs = static_cast<uint32_t>(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(res, i);
    Py_INCREF(o);
    out_handles[i] = o;
  }
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTCachedOpGetStats(void* op, uint64_t* calls, uint64_t* compiles) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(op));
  PyObject* res = CallRt("cachedop_stats", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTCachedOpGetStats");
  unsigned long long c = 0, m = 0;
  if (!PyArg_ParseTuple(res, "KK", &c, &m)) {
    Py_DECREF(res);
    return PyFail("MXTCachedOpGetStats");
  }
  Py_DECREF(res);
  *calls = c;
  *compiles = m;
  return 0;
  API_END()
}

int MXTCachedOpFree(void* op) {
  API_BEGIN()
  if (op == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(op));
  return 0;
  API_END()
}

// -- KVStore ----------------------------------------------------------------

int MXTKVStoreCreate(const char* type, void** out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", type);
  PyObject* res = CallRt("kv_create", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTKVStoreCreate");
  API_END()
}

int MXTKVStoreInit(void* kv, int key, void* nd) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OiO)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(nd));
  PyObject* res = CallRt("kv_init", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStoreInit");
  API_END()
}

int MXTKVStoreInitEx(void* kv, const char* key, void* nd) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OsO)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(nd));
  PyObject* res = CallRt("kv_init", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStoreInitEx");
  API_END()
}

int MXTKVStorePush(void* kv, int key, void* nd, int priority) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OiOi)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(nd), priority);
  PyObject* res = CallRt("kv_push", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStorePush");
  API_END()
}

int MXTKVStorePushEx(void* kv, const char* key, void* nd, int priority) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OsOi)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(nd), priority);
  PyObject* res = CallRt("kv_push", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStorePushEx");
  API_END()
}

int MXTKVStorePull(void* kv, int key, void* out_nd, int priority) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OiOi)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(out_nd), priority);
  PyObject* res = CallRt("kv_pull", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStorePull");
  API_END()
}

int MXTKVStorePullEx(void* kv, const char* key, void* out_nd, int priority) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OsOi)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(out_nd), priority);
  PyObject* res = CallRt("kv_pull", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStorePullEx");
  API_END()
}

// Fused push+pull (ref: MXKVStorePushPullEx) — in/out may alias.
int MXTKVStorePushPull(void* kv, int key, void* in_nd, void* out_nd,
                       int priority) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OiOOi)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(in_nd),
                                 static_cast<PyObject*>(out_nd), priority);
  PyObject* res = CallRt("kv_pushpull", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStorePushPull");
  API_END()
}

int MXTKVStoreGetRank(void* kv, int* out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(kv));
  PyObject* res = CallRt("kv_rank", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTKVStoreGetRank");
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTKVStoreGetGroupSize(void* kv, int* out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(kv));
  PyObject* res = CallRt("kv_size", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTKVStoreGetGroupSize");
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTKVStoreGetType(void* kv, const char** out_type) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(kv));
  PyObject* res = CallRt("kv_type", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTKVStoreGetType");
  const char* c = PyUnicode_AsUTF8(res);
  ret_store.str = c ? c : "";
  *out_type = ret_store.str.c_str();
  Py_DECREF(res);
  return 0;
  API_END()
}

// Build the optimizer server-side from name+params — the C-frontend
// analog of the pickled-optimizer UX (ref: MXKVStoreSetOptimizer /
// kvstore_server.py _controller).
int MXTKVStoreSetOptimizer(void* kv, const char* opt_name,
                           uint32_t num_params, const char** keys,
                           const char** vals) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OsNN)", static_cast<PyObject*>(kv),
                                 opt_name, StrList(keys, num_params),
                                 StrList(vals, num_params));
  PyObject* res = CallRt("kv_set_optimizer", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStoreSetOptimizer");
  API_END()
}

// Global barrier across workers (ref: MXKVStoreBarrier /
// ps::Postoffice::Barrier).
int MXTKVStoreBarrier(void* kv) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(kv));
  PyObject* res = CallRt("kv_barrier", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStoreBarrier");
  API_END()
}

int MXTKVStoreFree(void* kv) {
  API_BEGIN()
  if (kv == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(kv));
  return 0;
  API_END()
}

// -- DataIter ---------------------------------------------------------------

int MXTListDataIters(uint32_t* out_size, const char*** out_array) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("list_data_iters", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTListDataIters");
  return ReturnStrList(res, out_size, out_array, "MXTListDataIters");
  API_END()
}

int MXTDataIterCreate(const char* name, uint32_t num_params,
                      const char** keys, const char** vals, void** out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(sNN)", name, StrList(keys, num_params),
                                 StrList(vals, num_params));
  PyObject* res = CallRt("data_iter_create", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTDataIterCreate");
  API_END()
}

int MXTDataIterNext(void* iter, int* out_more) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(iter));
  PyObject* res = CallRt("data_iter_next", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTDataIterNext");
  *out_more = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTDataIterGetData(void* iter, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(iter));
  PyObject* res = CallRt("data_iter_data", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTDataIterGetData");
  API_END()
}

int MXTDataIterGetLabel(void* iter, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(iter));
  PyObject* res = CallRt("data_iter_label", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTDataIterGetLabel");
  API_END()
}

int MXTDataIterBeforeFirst(void* iter) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(iter));
  PyObject* res = CallRt("data_iter_reset", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTDataIterBeforeFirst");
  API_END()
}

int MXTDataIterFree(void* iter) {
  API_BEGIN()
  if (iter == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(iter));
  return 0;
  API_END()
}

// -- NDArray save/load + in-place copy --------------------------------------

// names may be NULL => unnamed records (ref: MXNDArraySave c_api.h:659).
int MXTNDArraySave(const char* fname, uint32_t num, void** handles,
                   const char** names) {
  API_BEGIN()
  Gil gil;
  PyObject* namelist = names ? StrList(names, num) : PyList_New(0);
  PyObject* args = Py_BuildValue("(sNN)", fname, HandleList(handles, num),
                                 namelist);
  PyObject* res = CallRt("nd_save", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTNDArraySave");
  API_END()
}

// Returned handle/name arrays stay valid until the next Load on this
// thread; handles are owned by the caller (free each with
// MXTNDArrayFree). (ref: MXNDArrayLoad c_api.h:672)
int MXTNDArrayLoad(const char* fname, uint32_t* out_size, void*** out_arr,
                   uint32_t* out_name_size, const char*** out_names) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", fname);
  PyObject* res = CallRt("nd_load", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArrayLoad");
  PyObject* names = PyTuple_GET_ITEM(res, 0);
  PyObject* arrays = PyTuple_GET_ITEM(res, 1);
  Py_ssize_t nn = PyList_Size(names);
  Py_ssize_t na = PyList_Size(arrays);
  ret_store.strings.clear();
  ret_store.charp.clear();
  ret_store.handles.clear();
  for (Py_ssize_t i = 0; i < nn; ++i) {
    const char* c = PyUnicode_AsUTF8(PyList_GET_ITEM(names, i));
    ret_store.strings.emplace_back(c ? c : "");
  }
  for (auto& s : ret_store.strings) ret_store.charp.push_back(s.c_str());
  for (Py_ssize_t i = 0; i < na; ++i) {
    PyObject* a = PyList_GET_ITEM(arrays, i);
    Py_INCREF(a);
    ret_store.handles.push_back(a);
  }
  Py_DECREF(res);
  *out_size = static_cast<uint32_t>(na);
  *out_arr = ret_store.handles.data();
  *out_name_size = static_cast<uint32_t>(nn);
  *out_names = ret_store.charp.data();
  return 0;
  API_END()
}

int MXTNDArraySyncCopyFromCPU(void* handle, const void* data,
                              size_t nbytes) {
  API_BEGIN()
  Gil gil;
  PyObject* raw = PyBytes_FromStringAndSize(
      static_cast<const char*>(data), static_cast<Py_ssize_t>(nbytes));
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(handle),
                                 raw);
  PyObject* res = CallRt("copy_from_bytes", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTNDArraySyncCopyFromCPU");
  API_END()
}

// -- NDArray views (ref: MXNDArrayReshape/Slice/At c_api.h) -----------------

int MXTNDArrayReshape(void* handle, uint32_t ndim, const int64_t* dims,
                      void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* shp = PyList_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i)
    PyList_SET_ITEM(shp, i, PyLong_FromLongLong(dims[i]));
  PyObject* args = Py_BuildValue("(ON)", static_cast<PyObject*>(handle),
                                 shp);
  PyObject* res = CallRt("nd_reshape", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTNDArrayReshape");
  API_END()
}

int MXTNDArraySlice(void* handle, int64_t begin, int64_t end, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OLL)", static_cast<PyObject*>(handle),
                                 static_cast<long long>(begin),
                                 static_cast<long long>(end));
  PyObject* res = CallRt("nd_slice", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTNDArraySlice");
  API_END()
}

int MXTNDArrayAt(void* handle, int64_t idx, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OL)", static_cast<PyObject*>(handle),
                                 static_cast<long long>(idx));
  PyObject* res = CallRt("nd_at", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTNDArrayAt");
  API_END()
}

// -- autograd flags (ref: MXAutogradIsRecording/IsTraining/SetIsTraining) ---

int MXTAutogradIsRecording(int* out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("autograd_is_recording", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTAutogradIsRecording");
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTAutogradIsTraining(int* out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("autograd_is_training", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTAutogradIsTraining");
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTAutogradSetIsTraining(int train_mode) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", train_mode);
  PyObject* res = CallRt("autograd_set_training", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTAutogradSetIsTraining");
  API_END()
}

// -- profiler (ref: MXSetProcessProfilerConfig/State, MXDumpProfile) --------

int MXTProfileSetConfig(uint32_t num_params, const char** keys,
                        const char** vals) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(NN)", StrList(keys, num_params),
                                 StrList(vals, num_params));
  PyObject* res = CallRt("profiler_set_config", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileSetConfig");
  API_END()
}

int MXTProfileSetState(int state) {  // 0 = stop, 1 = run
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", state);
  PyObject* res = CallRt("profiler_set_state", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileSetState");
  API_END()
}

int MXTProfileDump() {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("profiler_dump", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileDump");
  API_END()
}

// -- Symbol attrs / views (ref: MXSymbolGetAttr/SetAttr/ListAttr,
//    MXSymbolGetInternals/GetOutput, MXSymbolCopy) --------------------------

int MXTSymbolGetAttr(void* sym, const char* key, const char** out,
                     int* success) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(sym), key);
  PyObject* res = CallRt("symbol_attr", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolGetAttr");
  if (res == Py_None) {  // attr missing (an empty string is PRESENT)
    Py_DECREF(res);
    ret_store.str.clear();
    *out = ret_store.str.c_str();
    *success = 0;
    return 0;
  }
  const char* c = PyUnicode_AsUTF8(res);
  if (c == nullptr) {
    Py_DECREF(res);
    return PyFail("MXTSymbolGetAttr");
  }
  ret_store.str = c;
  Py_DECREF(res);
  *success = 1;
  *out = ret_store.str.c_str();
  return 0;
  API_END()
}

int MXTSymbolSetAttr(void* sym, const char* key, const char* value) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(Oss)", static_cast<PyObject*>(sym),
                                 key, value);
  PyObject* res = CallRt("symbol_set_attr", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTSymbolSetAttr");
  API_END()
}

// JSON object {node: {key: value}} — one call instead of the
// reference's paired size/array outputs.
int MXTSymbolListAttr(void* sym, const char** out_json) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_attr_json", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolListAttr");
  const char* c = PyUnicode_AsUTF8(res);
  if (c == nullptr) {
    Py_DECREF(res);
    return PyFail("MXTSymbolListAttr");
  }
  ret_store.str = c;
  *out_json = ret_store.str.c_str();
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTSymbolGetInternals(void* sym, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_get_internals", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolGetInternals");
  API_END()
}

int MXTSymbolGetOutput(void* sym, uint32_t index, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OI)", static_cast<PyObject*>(sym),
                                 index);
  PyObject* res = CallRt("symbol_get_output", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolGetOutput");
  API_END()
}

int MXTSymbolCopy(void* sym, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_copy", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCopy");
  API_END()
}

// Device-side value copy dst <- src (no host round trip; ref:
// MXNDArraySyncCopyFromNDArray).
int MXTNDArrayCopyFrom(void* dst, void* src) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OO)", static_cast<PyObject*>(dst),
                                 static_cast<PyObject*>(src));
  PyObject* res = CallRt("set_data", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTNDArrayCopyFrom");
  API_END()
}

int MXTNDArrayGetDType(void* handle, int* out_dtype) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("dtype_of", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArrayGetDType");
  *out_dtype = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
  API_END()
}

}  // extern "C"

// ===== round-4 ABI long tail (VERDICT r3 item 3) ===========================
// Mechanical completions of reference families whose functionality already
// exists in the runtime: per-array waits, context/storage queries, symbol
// introspection, executor bind/reshape/print, KVStore role/row-sparse/
// compression, the MXProfile* object family, engine/bulk, libinfo,
// numpy-shape toggles, device queries, PS env, and autograd symbol
// extraction. Ref lines: include/mxnet/c_api.h for each MX name minus the
// leading T.

namespace {

int ReturnInt(PyObject* res, int* out, const char* who) {
  if (res == nullptr) return PyFail(who);
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  if (PyErr_Occurred()) return PyFail(who);
  return 0;
}

int ReturnStr(PyObject* res, const char** out, const char* who) {
  if (res == nullptr) return PyFail(who);
  const char* c = PyUnicode_AsUTF8(res);
  if (c == nullptr) {
    Py_DECREF(res);
    return PyFail(who);
  }
  ret_store.str = c;
  *out = ret_store.str.c_str();
  Py_DECREF(res);
  return 0;
}

}  // namespace

extern "C" {

// -- NDArray ----------------------------------------------------------------

int MXTNDArrayWaitToRead(void* handle) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("nd_wait", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTNDArrayWaitToRead");
  API_END()
}

int MXTNDArrayWaitToWrite(void* handle) {
  API_BEGIN()
  return MXTNDArrayWaitToRead(handle);
  API_END()
}

int MXTNDArrayDetach(void* handle, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("nd_detach", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTNDArrayDetach");
  API_END()
}

int MXTNDArrayGetContext(void* handle, int* out_dev_type, int* out_dev_id) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("nd_context", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArrayGetContext");
  if (!PyArg_ParseTuple(res, "ii", out_dev_type, out_dev_id)) {
    Py_DECREF(res);
    return PyFail("MXTNDArrayGetContext");
  }
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTNDArrayGetStorageType(void* handle, int* out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("nd_storage_type", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTNDArrayGetStorageType");
  API_END()
}

int MXTNDArrayCreateNone(void** out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("nd_none", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTNDArrayCreateNone");
  API_END()
}

int MXTShallowCopyNDArray(void* handle, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* res = CallRt("nd_shallow_copy", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTShallowCopyNDArray");
  API_END()
}

int MXTNDArrayLoadFromBuffer(const void* buf, size_t size,
                             uint32_t* out_size, void*** out_arr,
                             uint32_t* out_name_size,
                             const char*** out_names) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(y#)", static_cast<const char*>(buf),
      static_cast<Py_ssize_t>(size));
  PyObject* res = CallRt("nd_load_from_buffer", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTNDArrayLoadFromBuffer");
  PyObject* names = PyTuple_GetItem(res, 0);
  PyObject* arrs = PyTuple_GetItem(res, 1);
  if (names == nullptr || arrs == nullptr) {
    Py_DECREF(res);
    return PyFail("MXTNDArrayLoadFromBuffer");
  }
  ret_store.strings.clear();
  ret_store.charp.clear();
  ret_store.handles.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(names); ++i)
    ret_store.strings.emplace_back(
        PyUnicode_AsUTF8(PyList_GET_ITEM(names, i)));
  for (auto& s : ret_store.strings) ret_store.charp.push_back(s.c_str());
  for (Py_ssize_t i = 0; i < PyList_Size(arrs); ++i) {
    PyObject* a = PyList_GET_ITEM(arrs, i);
    Py_INCREF(a);
    ret_store.handles.push_back(a);
  }
  *out_name_size = static_cast<uint32_t>(ret_store.charp.size());
  *out_names = ret_store.charp.data();
  *out_size = static_cast<uint32_t>(ret_store.handles.size());
  *out_arr = ret_store.handles.data();
  Py_DECREF(res);
  return 0;
  API_END()
}

// -- Symbol -----------------------------------------------------------------

int MXTSymbolCreateGroup(uint32_t num_symbols, void** symbols, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(N)", HandleList(symbols, num_symbols));
  PyObject* res = CallRt("symbol_group", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTSymbolCreateGroup");
  API_END()
}

int MXTSymbolGetNumOutputs(void* sym, uint32_t* out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_num_outputs", args);
  Py_DECREF(args);
  int v = 0;
  int rc = ReturnInt(res, &v, "MXTSymbolGetNumOutputs");
  *out = static_cast<uint32_t>(v);
  return rc;
  API_END()
}

int MXTSymbolPrint(void* sym, const char** out_str) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_print", args);
  Py_DECREF(args);
  return ReturnStr(res, out_str, "MXTSymbolPrint");
  API_END()
}

int MXTSymbolGetChildren(void* sym, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_get_children", args);
  Py_DECREF(args);
  if (res == Py_None) {
    Py_DECREF(res);
    *out = nullptr;
    return 0;
  }
  return ReturnHandle(res, out, "MXTSymbolGetChildren");
  API_END()
}

int MXTSymbolGetInputSymbols(void* sym, void** out_handles,
                             uint32_t max_inputs, int* out_size) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_get_inputs", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolGetInputSymbols");
  Py_ssize_t n = PyList_Size(res);
  if (static_cast<uint32_t>(n) > max_inputs) {
    Py_DECREF(res);
    return FailWith("MXTSymbolGetInputSymbols: too many inputs");
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(res, i);
    Py_INCREF(o);
    out_handles[i] = o;
  }
  *out_size = static_cast<int>(n);
  Py_DECREF(res);
  return 0;
  API_END()
}

int MXTSymbolGetAtomicSymbolName(void* sym, const char** out_name) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_atomic_name", args);
  Py_DECREF(args);
  return ReturnStr(res, out_name, "MXTSymbolGetAtomicSymbolName");
  API_END()
}

int MXTSymbolListAttrShallow(void* sym, uint32_t* out_size,
                             const char*** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(sym));
  PyObject* res = CallRt("symbol_attrs_shallow", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolListAttrShallow");
  return ReturnStrList(res, out_size, out, "MXTSymbolListAttrShallow");
  API_END()
}

int MXTShallowCopySymbol(void* sym, void** out) {
  API_BEGIN()
  if (sym == nullptr) return FailWith("null symbol");
  Gil gil;
  Py_INCREF(static_cast<PyObject*>(sym));
  *out = sym;
  return 0;
  API_END()
}

int MXTSymbolInferShapePartial(void* sym, uint32_t num_provided,
                               const char** names, const uint32_t* ndims,
                               const int64_t* shapes_flat,
                               uint32_t* arg_count, uint32_t* out_count,
                               uint32_t* aux_count,
                               const uint32_t** all_ndims,
                               const int64_t** all_dims) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(sym),
                                 StrList(names, num_provided),
                                 ShapeList(num_provided, ndims, shapes_flat));
  PyObject* res = CallRt("symbol_infer_shape_partial", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolInferShapePartial");
  ret_store.shape_ndim.clear();
  ret_store.shape_data.clear();
  uint32_t counts[3] = {0, 0, 0};
  for (int part = 0; part < 3; ++part) {
    PyObject* lst = PyTuple_GET_ITEM(res, part);
    Py_ssize_t cnt = PyList_Size(lst);
    counts[part] = static_cast<uint32_t>(cnt);
    for (Py_ssize_t i = 0; i < cnt; ++i) {
      PyObject* shp = PyList_GET_ITEM(lst, i);
      Py_ssize_t nd = PyTuple_Size(shp);
      ret_store.shape_ndim.push_back(static_cast<uint32_t>(nd));
      for (Py_ssize_t d = 0; d < nd; ++d)
        ret_store.shape_data.push_back(
            PyLong_AsLongLong(PyTuple_GET_ITEM(shp, d)));
    }
  }
  Py_DECREF(res);
  *arg_count = counts[0];
  *out_count = counts[1];
  *aux_count = counts[2];
  *all_ndims = ret_store.shape_ndim.data();
  *all_dims = ret_store.shape_data.data();
  return 0;
  API_END()
}

int MXTSymbolInferType(void* sym, uint32_t num_provided, const char** names,
                       const int* dtypes, uint32_t* arg_count,
                       const int** arg_types, uint32_t* out_count,
                       const int** out_types, uint32_t* aux_count,
                       const int** aux_types) {
  API_BEGIN()
  Gil gil;
  PyObject* dt = PyList_New(num_provided);
  for (uint32_t i = 0; i < num_provided; ++i)
    PyList_SET_ITEM(dt, i, PyLong_FromLong(dtypes[i]));
  PyObject* args = Py_BuildValue("(ONNi)", static_cast<PyObject*>(sym),
                                 StrList(names, num_provided), dt, 0);
  PyObject* res = CallRt("symbol_infer_type", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTSymbolInferType");
  static thread_local std::vector<int> arg_v, out_v, aux_v;
  arg_v.clear(); out_v.clear(); aux_v.clear();
  std::vector<int>* dsts[3] = {&arg_v, &out_v, &aux_v};
  for (int part = 0; part < 3; ++part) {
    PyObject* lst = PyTuple_GetItem(res, part);
    for (Py_ssize_t i = 0; i < PyList_Size(lst); ++i)
      dsts[part]->push_back(
          static_cast<int>(PyLong_AsLong(PyList_GET_ITEM(lst, i))));
  }
  Py_DECREF(res);
  *arg_count = static_cast<uint32_t>(arg_v.size());
  *arg_types = arg_v.data();
  *out_count = static_cast<uint32_t>(out_v.size());
  *out_types = out_v.data();
  *aux_count = static_cast<uint32_t>(aux_v.size());
  *aux_types = aux_v.data();
  return 0;
  API_END()
}

// -- Executor ---------------------------------------------------------------

int MXTExecutorPrint(void* exec, const char** out_str) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(exec));
  PyObject* res = CallRt("executor_print", args);
  Py_DECREF(args);
  return ReturnStr(res, out_str, "MXTExecutorPrint");
  API_END()
}

int MXTExecutorReshape(void* exec, uint32_t num_provided,
                       const char** names, const uint32_t* ndims,
                       const int64_t* shapes_flat, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(exec),
                                 StrList(names, num_provided),
                                 ShapeList(num_provided, ndims, shapes_flat));
  PyObject* res = CallRt("executor_reshape", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTExecutorReshape");
  API_END()
}

int MXTExecutorBind(void* sym, uint32_t num_args, const char** names,
                    void** arg_handles, const char* grad_req, void** out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(ONNs)", static_cast<PyObject*>(sym),
                                 StrList(names, num_args),
                                 HandleList(arg_handles, num_args),
                                 grad_req);
  PyObject* res = CallRt("executor_bind", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, "MXTExecutorBind");
  API_END()
}

// -- KVStore ----------------------------------------------------------------

int MXTKVStoreIsWorkerNode(int* out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", "worker");
  PyObject* res = CallRt("kv_role", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTKVStoreIsWorkerNode");
  API_END()
}

int MXTKVStoreIsServerNode(int* out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", "server");
  PyObject* res = CallRt("kv_role", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTKVStoreIsServerNode");
  API_END()
}

int MXTKVStoreIsSchedulerNode(int* out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", "scheduler");
  PyObject* res = CallRt("kv_role", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTKVStoreIsSchedulerNode");
  API_END()
}

int MXTKVStoreGetNumDeadNode(void* kv, int node_id, int* out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", static_cast<PyObject*>(kv),
                                 node_id);
  PyObject* res = CallRt("kv_num_dead", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTKVStoreGetNumDeadNode");
  API_END()
}

int MXTKVStoreSetGradientCompression(void* kv, uint32_t num_params,
                                     const char** keys,
                                     const char** vals) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(ONN)", static_cast<PyObject*>(kv),
                                 StrList(keys, num_params),
                                 StrList(vals, num_params));
  PyObject* res = CallRt("kv_set_gradient_compression", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStoreSetGradientCompression");
  API_END()
}

int MXTKVStorePullRowSparse(void* kv, const char* key, void* row_ids,
                            void* out_arr) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OsOO)", static_cast<PyObject*>(kv), key,
                                 static_cast<PyObject*>(row_ids),
                                 static_cast<PyObject*>(out_arr));
  PyObject* res = CallRt("kv_pull_row_sparse", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTKVStorePullRowSparse");
  API_END()
}

int MXTNotifyShutdown(void) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("notify_shutdown", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTNotifyShutdown");
  API_END()
}

int MXTInitPSEnv(uint32_t num_vars, const char** keys, const char** vals) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(NN)", StrList(keys, num_vars),
                                 StrList(vals, num_vars));
  PyObject* res = CallRt("init_ps_env", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTInitPSEnv");
  API_END()
}

// -- Profiler object family -------------------------------------------------

static int ProfileCreate(const char* kind, void* domain, const char* name,
                         void** out, const char* who) {
  EnsurePython();
  Gil gil;
  PyObject* dom = domain ? static_cast<PyObject*>(domain) : Py_None;
  PyObject* args = Py_BuildValue("(sOs)", kind, dom, name);
  PyObject* res = CallRt("profile_create", args);
  Py_DECREF(args);
  return ReturnHandle(res, out, who);
}

int MXTProfileCreateDomain(const char* name, void** out) {
  API_BEGIN()
  return ProfileCreate("domain", nullptr, name, out,
                       "MXTProfileCreateDomain");
  API_END()
}

int MXTProfileCreateTask(void* domain, const char* name, void** out) {
  API_BEGIN()
  return ProfileCreate("task", domain, name, out, "MXTProfileCreateTask");
  API_END()
}

int MXTProfileCreateFrame(void* domain, const char* name, void** out) {
  API_BEGIN()
  return ProfileCreate("frame", domain, name, out,
                       "MXTProfileCreateFrame");
  API_END()
}

int MXTProfileCreateEvent(const char* name, void** out) {
  API_BEGIN()
  return ProfileCreate("event", nullptr, name, out,
                       "MXTProfileCreateEvent");
  API_END()
}

int MXTProfileCreateCounter(void* domain, const char* name, void** out) {
  API_BEGIN()
  return ProfileCreate("counter", domain, name, out,
                       "MXTProfileCreateCounter");
  API_END()
}

int MXTProfileDestroyHandle(void* handle) {
  API_BEGIN()
  if (handle == nullptr) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(handle));
  return 0;
  API_END()
}

int MXTProfileDurationStart(void* handle) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", static_cast<PyObject*>(handle), 1);
  PyObject* res = CallRt("profile_duration", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileDurationStart");
  API_END()
}

int MXTProfileDurationStop(void* handle) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(Oi)", static_cast<PyObject*>(handle), 0);
  PyObject* res = CallRt("profile_duration", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileDurationStop");
  API_END()
}

int MXTProfileSetCounter(void* handle, uint64_t value) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OK)", static_cast<PyObject*>(handle),
                                 static_cast<unsigned long long>(value));
  PyObject* res = CallRt("profile_counter_set", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileSetCounter");
  API_END()
}

int MXTProfileAdjustCounter(void* handle, int64_t delta) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(OL)", static_cast<PyObject*>(handle),
                                 static_cast<long long>(delta));
  PyObject* res = CallRt("profile_counter_adjust", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileAdjustCounter");
  API_END()
}

int MXTProfileSetMarker(void* domain, const char* name,
                        const char* scope) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(Oss)", static_cast<PyObject*>(domain),
                                 name, scope ? scope : "process");
  PyObject* res = CallRt("profile_set_marker", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfileSetMarker");
  API_END()
}

int MXTProfilePause(int paused) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", paused);
  PyObject* res = CallRt("profile_pause", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTProfilePause");
  API_END()
}

int MXTAggregateProfileStatsPrint(const char** out_str, int reset,
                                  const char* format, const char* sort_by,
                                  int ascending) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(issi)", reset, format ? format : "table",
                                 sort_by ? sort_by : "total", ascending);
  PyObject* res = CallRt("profile_aggregate_stats", args);
  Py_DECREF(args);
  return ReturnStr(res, out_str, "MXTAggregateProfileStatsPrint");
  API_END()
}

// -- misc -------------------------------------------------------------------

int MXTEngineSetBulkSize(int bulk_size, int* prev_bulk_size) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", bulk_size);
  PyObject* res = CallRt("engine_set_bulk_size", args);
  Py_DECREF(args);
  return ReturnInt(res, prev_bulk_size, "MXTEngineSetBulkSize");
  API_END()
}

int MXTLibInfoFeatures(uint32_t* out_size, const char*** out_pairs) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("lib_info_features", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTLibInfoFeatures");
  return ReturnStrList(res, out_size, out_pairs, "MXTLibInfoFeatures");
  API_END()
}

int MXTRandomSeedContext(int seed, int dev_type, int dev_id) {
  API_BEGIN()
  (void)dev_type;
  (void)dev_id;
  return MXTRandomSeed(seed);
  API_END()
}

int MXTIsNumpyShape(int* out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("np_shape_is", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTIsNumpyShape");
  API_END()
}

int MXTSetIsNumpyShape(int is_np_shape, int* prev) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", is_np_shape);
  PyObject* res = CallRt("np_shape_set", args);
  Py_DECREF(args);
  return ReturnInt(res, prev, "MXTSetIsNumpyShape");
  API_END()
}

// "GPU" in the reference ABI = the accelerator; here that is the TPU
// fleet PJRT exposes (ref: MXGetGPUCount / MXGetGPUMemoryInformation64).
int MXTGetGPUCount(int* out) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("device_count", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTGetGPUCount");
  API_END()
}

int MXTGetGPUMemoryInformation(int dev_id, uint64_t* free_mem,
                               uint64_t* total_mem) {
  API_BEGIN()
  EnsurePython();
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", dev_id);
  PyObject* res = CallRt("device_memory_info", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTGetGPUMemoryInformation");
  unsigned long long f = 0, t = 0;
  if (!PyArg_ParseTuple(res, "KK", &f, &t)) {
    Py_DECREF(res);
    return PyFail("MXTGetGPUMemoryInformation");
  }
  Py_DECREF(res);
  *free_mem = f;
  *total_mem = t;
  return 0;
  API_END()
}

int MXTDataIterGetPadNum(void* iter, int* out) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(iter));
  PyObject* res = CallRt("dataiter_pad", args);
  Py_DECREF(args);
  return ReturnInt(res, out, "MXTDataIterGetPadNum");
  API_END()
}

int MXTDataIterGetIndex(void* iter, uint64_t** out_index,
                        uint64_t* out_size) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(iter));
  PyObject* res = CallRt("dataiter_index", args);
  Py_DECREF(args);
  if (res == nullptr) return PyFail("MXTDataIterGetIndex");
  static thread_local std::vector<uint64_t> idx;
  idx.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i)
    idx.push_back(static_cast<uint64_t>(
        PyLong_AsUnsignedLongLong(PyList_GET_ITEM(res, i))));
  Py_DECREF(res);
  *out_size = idx.size();
  *out_index = idx.data();
  return 0;
  API_END()
}

int MXTAutogradComputeGradient(uint32_t num_output, void** output_handles) {
  API_BEGIN()
  Gil gil;
  PyObject* args = Py_BuildValue("(N)",
                                 HandleList(output_handles, num_output));
  PyObject* res = CallRt("backward", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTAutogradComputeGradient");
  API_END()
}

int MXTStorageEmptyCache(int dev_type, int dev_id) {
  API_BEGIN()
  (void)dev_type;
  (void)dev_id;
  EnsurePython();
  Gil gil;
  PyObject* args = PyTuple_New(0);
  PyObject* res = CallRt("storage_empty_cache", args);
  Py_DECREF(args);
  return ReturnOk(res, "MXTStorageEmptyCache");
  API_END()
}

}  // extern "C"
