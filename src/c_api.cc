// C ABI for the native runtime pieces of mxnet_tpu.
//
// Mirrors the reference's C API conventions (ref: include/mxnet/c_api.h,
// src/c_api/c_api_error.cc): every entry point returns 0 on success / -1
// on failure, with the message retrievable from MXTGetLastError()
// (thread-local, like the reference's error ring). Handles are opaque
// pointers owned by the caller until the matching *Free call.
#include <cstring>
#include <exception>
#include <string>
#include <vector>

#include "c_error.h"
#include "recordio.h"
#include "threaded_reader.h"

namespace mxnet_tpu {
std::string& LastError() {
  thread_local std::string last_error;
  return last_error;
}

int FailWith(const std::string& msg) {
  LastError() = msg;
  return -1;
}
}  // namespace mxnet_tpu

namespace {
int Fail(const std::string& msg) { return mxnet_tpu::FailWith(msg); }

#define API_BEGIN MXT_API_BEGIN
#define API_END MXT_API_END
}  // namespace

extern "C" {

const char* MXTGetLastError() { return mxnet_tpu::LastError().c_str(); }

// -- RecordWriter -----------------------------------------------------------
int MXTRecordWriterCreate(const char* path, void** out) {
  API_BEGIN()
  auto* w = new mxnet_tpu::RecordWriter(path);
  if (!w->ok()) {
    delete w;
    return Fail(std::string("cannot open for write: ") + path);
  }
  *out = w;
  API_END()
}

int MXTRecordWriterWrite(void* handle, const char* data, uint64_t size) {
  API_BEGIN()
  static_cast<mxnet_tpu::RecordWriter*>(handle)->Write(data, size);
  API_END()
}

int MXTRecordWriterTell(void* handle, uint64_t* out) {
  API_BEGIN()
  *out = static_cast<mxnet_tpu::RecordWriter*>(handle)->Tell();
  API_END()
}

int MXTRecordWriterFree(void* handle) {
  API_BEGIN()
  delete static_cast<mxnet_tpu::RecordWriter*>(handle);
  API_END()
}

// -- RecordReader -----------------------------------------------------------
int MXTRecordReaderCreate(const char* path, void** out) {
  API_BEGIN()
  auto* r = new mxnet_tpu::RecordReader(path);
  if (!r->ok()) {
    delete r;
    return Fail(std::string("cannot open for read: ") + path);
  }
  *out = r;
  API_END()
}

// *out_data points into an internal buffer valid until the next call on
// this handle; *out_size==0 with rc==0 and *eof==1 signals end of stream.
int MXTRecordReaderNext(void* handle, const char** out_data,
                        uint64_t* out_size, int* eof) {
  API_BEGIN()
  thread_local std::vector<char> buf;
  auto* r = static_cast<mxnet_tpu::RecordReader*>(handle);
  uint64_t at = r->Tell();
  switch (r->Next(&buf)) {
    case mxnet_tpu::ReadStatus::kRecord:
      *out_data = buf.data();
      *out_size = buf.size();
      *eof = 0;
      break;
    case mxnet_tpu::ReadStatus::kEOF:
      *out_data = nullptr;
      *out_size = 0;
      *eof = 1;
      break;
    case mxnet_tpu::ReadStatus::kCorrupt:
      return Fail("invalid RecordIO stream at offset " + std::to_string(at));
  }
  API_END()
}

int MXTRecordReaderSeek(void* handle, uint64_t pos) {
  API_BEGIN()
  static_cast<mxnet_tpu::RecordReader*>(handle)->Seek(pos);
  API_END()
}

int MXTRecordReaderTell(void* handle, uint64_t* out) {
  API_BEGIN()
  *out = static_cast<mxnet_tpu::RecordReader*>(handle)->Tell();
  API_END()
}

int MXTRecordReaderFree(void* handle) {
  API_BEGIN()
  delete static_cast<mxnet_tpu::RecordReader*>(handle);
  API_END()
}

// -- ThreadedRecordReader ---------------------------------------------------
int MXTThreadedReaderCreate(const char* path, uint64_t capacity, int shuffle,
                            uint64_t seed, void** out) {
  API_BEGIN()
  auto* r = new mxnet_tpu::ThreadedRecordReader(path, capacity, shuffle != 0,
                                                seed);
  if (!r->ok()) {
    delete r;
    return Fail(std::string("cannot open for read: ") + path);
  }
  *out = r;
  API_END()
}

int MXTThreadedReaderNext(void* handle, const char** out_data,
                          uint64_t* out_size, int* eof) {
  API_BEGIN()
  thread_local std::vector<char> buf;
  auto* r = static_cast<mxnet_tpu::ThreadedRecordReader*>(handle);
  if (r->Next(&buf)) {
    *out_data = buf.data();
    *out_size = buf.size();
    *eof = 0;
  } else {
    if (!r->error().empty()) return Fail(r->error());
    *out_data = nullptr;
    *out_size = 0;
    *eof = 1;
  }
  API_END()
}

int MXTThreadedReaderReset(void* handle) {
  API_BEGIN()
  static_cast<mxnet_tpu::ThreadedRecordReader*>(handle)->Reset();
  API_END()
}

int MXTThreadedReaderFree(void* handle) {
  API_BEGIN()
  delete static_cast<mxnet_tpu::ThreadedRecordReader*>(handle);
  API_END()
}

}  // extern "C"
