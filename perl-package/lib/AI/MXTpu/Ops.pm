package AI::MXTpu::Ops;

# GENERATED FILE - do not edit; run perl-package/scripts/gen_op_pm.py.
#
# One sub per operator in the live registry (389 ops), each a
# thin funnel into AI::MXTpu::op("<name>", @inputs, %params) - the
# imperative-invoke path of the C ABI. Names shadowing Perl builtins
# carry a trailing underscore (relu is relu, but abs is abs_).
#
# ref: perl-package/AI-MXNet/lib/AI/MXNet/NDArray.pm autogenerates the
# same surface at runtime from MXListAllOpNames.

use strict;
use warnings;

use AI::MXTpu;

# Activation(x, act_type='relu')
sub Activation { AI::MXTpu::op('Activation', @_) }

# AdaptiveAvgPooling2D(data, output_size=(1, 1))
sub AdaptiveAvgPooling2D { AI::MXTpu::op('AdaptiveAvgPooling2D', @_) }

# BatchNorm(x, gamma, beta, moving_mean, moving_var, eps=0.001, momentum=0.9, fix_gamma=True, use_global_stats=False, output_mean_var=False, axis=1, cudnn_off=False, min_calib_range=None, max_calib_range=None, _training=True)
sub BatchNorm { AI::MXTpu::op('BatchNorm', @_) }

# BatchNorm_v1(x, gamma, beta, moving_mean, moving_var, eps=0.001, momentum=0.9, fix_gamma=True, use_global_stats=False, output_mean_var=False, axis=1, cudnn_off=False, min_calib_range=None, max_calib_range=None, _training=True)
sub BatchNorm_v1 { AI::MXTpu::op('BatchNorm_v1', @_) }

# BilinearResize2D(data, height=1, width=1, scale_height=None, scale_width=None, mode='size')
sub BilinearResize2D { AI::MXTpu::op('BilinearResize2D', @_) }

# BilinearSampler(data, grid, cudnn_off=False)
sub BilinearSampler { AI::MXTpu::op('BilinearSampler', @_) }

# BlockGrad(x)
sub BlockGrad { AI::MXTpu::op('BlockGrad', @_) }

# BlockGrad_inner(x)
sub BlockGrad_inner { AI::MXTpu::op('BlockGrad_inner', @_) }

# CTCLoss(pred, label, pred_lengths=None, label_lengths=None, layout='NTC', label_layout='NT')
sub CTCLoss { AI::MXTpu::op('CTCLoss', @_) }

# Cast(x, dtype='float32')
sub Cast { AI::MXTpu::op('Cast', @_) }

# Concat(*xs, dim=1)
sub Concat { AI::MXTpu::op('Concat', @_) }

# Convolution(x, weight, bias=None, kernel=None, stride=None, dilate=None, pad=None, num_filter=None, num_group=1, no_bias=False, layout='NCHW', cudnn_tune=None, cudnn_off=False, workspace=1024, precision=None)
sub Convolution { AI::MXTpu::op('Convolution', @_) }

# Convolution_v1(x, weight, bias=None, kernel=None, stride=None, dilate=None, pad=None, num_filter=None, num_group=1, no_bias=False, layout='NCHW', cudnn_tune=None, cudnn_off=False, workspace=1024, precision=None)
sub Convolution_v1 { AI::MXTpu::op('Convolution_v1', @_) }

# Correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1, stride2=1, pad_size=0, is_multiply=True)
sub Correlation { AI::MXTpu::op('Correlation', @_) }

# Crop(data, *crop_like, num_args=1, offset=(0, 0), h_w=(0, 0), center_crop=False)
sub Crop { AI::MXTpu::op('Crop', @_) }

# CuDNNBatchNorm(x, gamma, beta, moving_mean, moving_var, eps=0.001, momentum=0.9, fix_gamma=True, use_global_stats=False, output_mean_var=False, axis=1, cudnn_off=False, min_calib_range=None, max_calib_range=None, _training=True)
sub CuDNNBatchNorm { AI::MXTpu::op('CuDNNBatchNorm', @_) }

# Deconvolution(x, weight, bias=None, kernel=None, stride=None, dilate=None, pad=None, adj=None, target_shape=None, num_filter=None, num_group=1, no_bias=True, layout='NCHW', cudnn_tune=None, cudnn_off=False, workspace=512, precision=None)
sub Deconvolution { AI::MXTpu::op('Deconvolution', @_) }

# DeformableConvolution(data, offset, weight, bias=None, kernel=(3, 3), stride=(1, 1), dilate=(1, 1), pad=(0, 0), num_filter=1, num_group=1, num_deformable_group=1, no_bias=False, workspace=1024, layout=None)
sub DeformableConvolution { AI::MXTpu::op('DeformableConvolution', @_) }

# DeformablePSROIPooling(data, rois, trans=None, spatial_scale=1.0, output_dim=1, group_size=1, pooled_size=1, part_size=0, sample_per_part=1, trans_std=0.0, no_trans=False)
sub DeformablePSROIPooling { AI::MXTpu::op('DeformablePSROIPooling', @_) }

# Dropout(x, key=None, p=0.5, mode='training', axes=(), _training=True, cudnn_off=False)
sub Dropout { AI::MXTpu::op('Dropout', @_) }

# ElementWiseSum(*xs)
sub ElementWiseSum { AI::MXTpu::op('ElementWiseSum', @_) }

# Embedding(data, weight, input_dim=None, output_dim=None, dtype=None, sparse_grad=False)
sub Embedding { AI::MXTpu::op('Embedding', @_) }

# Flatten(x)
sub Flatten { AI::MXTpu::op('Flatten', @_) }

# FullyConnected(x, weight, bias=None, num_hidden=None, no_bias=False, flatten=True, precision=None)
sub FullyConnected { AI::MXTpu::op('FullyConnected', @_) }

# GridGenerator(data, transform_type='affine', target_shape=(0, 0))
sub GridGenerator { AI::MXTpu::op('GridGenerator', @_) }

# GroupNorm(x, gamma, beta, num_groups=1, eps=1e-05)
sub GroupNorm { AI::MXTpu::op('GroupNorm', @_) }

# IdentityAttachKLSparseReg(data, sparseness_target=0.1, penalty=0.001, momentum=0.9)
sub IdentityAttachKLSparseReg { AI::MXTpu::op('IdentityAttachKLSparseReg', @_) }

# InstanceNorm(x, gamma, beta, eps=0.001)
sub InstanceNorm { AI::MXTpu::op('InstanceNorm', @_) }

# L2Normalization(x, eps=1e-10, mode='instance')
sub L2Normalization { AI::MXTpu::op('L2Normalization', @_) }

# LRN(x, alpha=0.0001, beta=0.75, knorm=2.0, nsize=5)
sub LRN { AI::MXTpu::op('LRN', @_) }

# LayerNorm(x, gamma, beta, axis=-1, eps=1e-05, output_mean_var=False)
sub LayerNorm { AI::MXTpu::op('LayerNorm', @_) }

# LeakyReLU(x, gamma=None, act_type='leaky', slope=0.25, lower_bound=0.125, upper_bound=0.334)
sub LeakyReLU { AI::MXTpu::op('LeakyReLU', @_) }

# LinearRegressionOutput(data, label, grad_scale=1.0)
sub LinearRegressionOutput { AI::MXTpu::op('LinearRegressionOutput', @_) }

# LogisticRegressionOutput(data, label, grad_scale=1.0)
sub LogisticRegressionOutput { AI::MXTpu::op('LogisticRegressionOutput', @_) }

# MAERegressionOutput(data, label, grad_scale=1.0)
sub MAERegressionOutput { AI::MXTpu::op('MAERegressionOutput', @_) }

# MakeLoss(x, grad_scale=1.0, valid_thresh=0.0, normalization='null')
sub MakeLoss { AI::MXTpu::op('MakeLoss', @_) }

# MultiBoxDetection(cls_pred, loc_pred, anchors, clip=True, threshold=0.01, background_id=0, nms_threshold=0.5, force_suppress=False, variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1)
sub MultiBoxDetection { AI::MXTpu::op('MultiBoxDetection', @_) }

# MultiBoxPrior(data, sizes=(1.0,), ratios=(1.0,), clip=False, steps=(-1.0, -1.0), offsets=(0.5, 0.5))
sub MultiBoxPrior { AI::MXTpu::op('MultiBoxPrior', @_) }

# MultiBoxTarget(anchor, label, cls_pred, overlap_threshold=0.5, ignore_label=-1.0, negative_mining_ratio=-1.0, negative_mining_thresh=0.5, minimum_negative_samples=0, variances=(0.1, 0.1, 0.2, 0.2))
sub MultiBoxTarget { AI::MXTpu::op('MultiBoxTarget', @_) }

# MultiProposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000, rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16, scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16, output_score=False, iou_loss=False)
sub MultiProposal { AI::MXTpu::op('MultiProposal', @_) }

# PSROIPooling(data, rois, spatial_scale=1.0, output_dim=1, pooled_size=1, group_size=0)
sub PSROIPooling { AI::MXTpu::op('PSROIPooling', @_) }

# Pad(x, mode='constant', pad_width=(), constant_value=0.0)
sub Pad { AI::MXTpu::op('Pad', @_) }

# Pooling(x, kernel=None, pool_type='max', stride=None, pad=None, global_pool=False, pooling_convention='valid', cudnn_off=False, p_value=2, count_include_pad=True, layout=None)
sub Pooling { AI::MXTpu::op('Pooling', @_) }

# Pooling_v1(x, kernel=None, pool_type='max', stride=None, pad=None, global_pool=False, pooling_convention='valid', cudnn_off=False, p_value=2, count_include_pad=True, layout=None)
sub Pooling_v1 { AI::MXTpu::op('Pooling_v1', @_) }

# Proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000, rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16, scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16, output_score=False, iou_loss=False)
sub Proposal { AI::MXTpu::op('Proposal', @_) }

# RNN(data, parameters, state, state_cell=None, sequence_length=None, key=None, *, mode='lstm', state_size=None, num_layers=1, bidirectional=False, p=0.0, state_outputs=False, projection_size=None, lstm_state_clip_min=None, lstm_state_clip_max=None, lstm_state_clip_nan=False, use_sequence_length=False, _training=True)
sub RNN { AI::MXTpu::op('RNN', @_) }

# ROIAlign(data, rois, pooled_size=(7, 7), spatial_scale=1.0, sample_ratio=-1, position_sensitive=False, aligned=False)
sub ROIAlign { AI::MXTpu::op('ROIAlign', @_) }

# ROIPooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0)
sub ROIPooling { AI::MXTpu::op('ROIPooling', @_) }

# RROIAlign(data, rois, pooled_size=(1, 1), spatial_scale=1.0, sampling_ratio=-1)
sub RROIAlign { AI::MXTpu::op('RROIAlign', @_) }

# Reshape(x, shape=None, reverse=False)
sub Reshape { AI::MXTpu::op('Reshape', @_) }

# SVMOutput(data, label, margin=1.0, regularization_coefficient=1.0, use_linear=False)
sub SVMOutput { AI::MXTpu::op('SVMOutput', @_) }

# SequenceLast(data, sequence_length=None, use_sequence_length=True, axis=0)
sub SequenceLast { AI::MXTpu::op('SequenceLast', @_) }

# SequenceMask(data, sequence_length=None, use_sequence_length=True, value=0.0, axis=0)
sub SequenceMask { AI::MXTpu::op('SequenceMask', @_) }

# SequenceReverse(data, sequence_length=None, use_sequence_length=True, axis=0)
sub SequenceReverse { AI::MXTpu::op('SequenceReverse', @_) }

# SliceChannel(x, num_outputs=1, axis=1, squeeze_axis=False)
sub SliceChannel { AI::MXTpu::op('SliceChannel', @_) }

# SoftmaxOutput(data, label, grad_scale=1.0, ignore_label=-1.0, multi_output=False, use_ignore=False, preserve_shape=False, normalization='null', out_grad=False, smooth_alpha=0.0)
sub SoftmaxOutput { AI::MXTpu::op('SoftmaxOutput', @_) }

# SpatialTransformer(data, loc, target_shape=(0, 0), transform_type='affine', sampler_type='bilinear', cudnn_off=None)
sub SpatialTransformer { AI::MXTpu::op('SpatialTransformer', @_) }

# SwapAxis(x, dim1=0, dim2=0)
sub SwapAxis { AI::MXTpu::op('SwapAxis', @_) }

# SyncBatchNorm(x, gamma, beta, moving_mean, moving_var, eps=0.001, momentum=0.9, fix_gamma=True, use_global_stats=False, output_mean_var=False, axis=1, cudnn_off=False, min_calib_range=None, max_calib_range=None, _training=True)
sub SyncBatchNorm { AI::MXTpu::op('SyncBatchNorm', @_) }

# UpSampling(*data, scale=1, sample_type='nearest', num_args=1, num_filter=0, multi_input_mode='concat', workspace=512)
sub UpSampling { AI::MXTpu::op('UpSampling', @_) }

# abs(x: 'ArrayLike', /) -> 'Array'
sub abs_ { AI::MXTpu::op('abs', @_) }

# activation(x, act_type='relu')
sub activation { AI::MXTpu::op('activation', @_) }

# adam_update(weight, grad, mean, var, lr=None, beta1=0.9, beta2=0.999, epsilon=1e-08, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True)
sub adam_update { AI::MXTpu::op('adam_update', @_) }

# adamw_update(weight, grad, mean, var, rescale_grad=1.0, lr=None, eta=None, beta1=0.9, beta2=0.999, epsilon=1e-08, wd=0.0, clip_gradient=-1.0)
sub adamw_update { AI::MXTpu::op('adamw_update', @_) }

# adaptive_avg_pooling_2d(data, output_size=(1, 1))
sub adaptive_avg_pooling_2d { AI::MXTpu::op('adaptive_avg_pooling_2d', @_) }

# add(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub add { AI::MXTpu::op('add', @_) }

# add_n(*xs)
sub add_n { AI::MXTpu::op('add_n', @_) }

# all_finite(data, init_output=True)
sub all_finite { AI::MXTpu::op('all_finite', @_) }

# amp_cast(x, dtype='bfloat16')
sub amp_cast { AI::MXTpu::op('amp_cast', @_) }

# amp_multicast(*arrays, num_outputs=1, cast_narrow=False)
sub amp_multicast { AI::MXTpu::op('amp_multicast', @_) }

# arange(start=0.0, stop=None, step=1.0, repeat=1, infer_range=False, dtype='float32')
sub arange { AI::MXTpu::op('arange', @_) }

# arccos(x: 'ArrayLike', /) -> 'Array'
sub arccos { AI::MXTpu::op('arccos', @_) }

# arccosh(x: 'ArrayLike', /) -> 'Array'
sub arccosh { AI::MXTpu::op('arccosh', @_) }

# arcsin(x: 'ArrayLike', /) -> 'Array'
sub arcsin { AI::MXTpu::op('arcsin', @_) }

# arcsinh(x: 'ArrayLike', /) -> 'Array'
sub arcsinh { AI::MXTpu::op('arcsinh', @_) }

# arctan(x: 'ArrayLike', /) -> 'Array'
sub arctan { AI::MXTpu::op('arctan', @_) }

# arctan2(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub arctan2 { AI::MXTpu::op('arctan2', @_) }

# arctanh(x: 'ArrayLike', /) -> 'Array'
sub arctanh { AI::MXTpu::op('arctanh', @_) }

# argmax(x, axis=None, keepdims=False)
sub argmax { AI::MXTpu::op('argmax', @_) }

# argmax_channel(x)
sub argmax_channel { AI::MXTpu::op('argmax_channel', @_) }

# argmin(x, axis=None, keepdims=False)
sub argmin { AI::MXTpu::op('argmin', @_) }

# argsort(x, axis=-1, is_ascend=True, dtype='float32')
sub argsort { AI::MXTpu::op('argsort', @_) }

# batch_dot(a, b, transpose_a=False, transpose_b=False, precision=None)
sub batch_dot { AI::MXTpu::op('batch_dot', @_) }

# batch_norm(x, gamma, beta, moving_mean, moving_var, eps=0.001, momentum=0.9, fix_gamma=True, use_global_stats=False, output_mean_var=False, axis=1, cudnn_off=False, min_calib_range=None, max_calib_range=None, _training=True)
sub batch_norm { AI::MXTpu::op('batch_norm', @_) }

# batch_take(a, indices)
sub batch_take { AI::MXTpu::op('batch_take', @_) }

# bernoulli(p, key=None, dtype='float32')
sub bernoulli { AI::MXTpu::op('bernoulli', @_) }

# bilinear_resize_2d(data, height=1, width=1, scale_height=None, scale_width=None, mode='size')
sub bilinear_resize_2d { AI::MXTpu::op('bilinear_resize_2d', @_) }

# bilinear_sampler(data, grid, cudnn_off=False)
sub bilinear_sampler { AI::MXTpu::op('bilinear_sampler', @_) }

# bipartite_matching(data, threshold=1e-12, is_ascend=False, topk=-1)
sub bipartite_matching { AI::MXTpu::op('bipartite_matching', @_) }

# blackman(M=1, dtype='float32', ctx=None)
sub blackman { AI::MXTpu::op('blackman', @_) }

# box_iou(lhs, rhs, format='corner')
sub box_iou { AI::MXTpu::op('box_iou', @_) }

# box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2, score_index=1, id_index=-1, background_id=-1, force_suppress=False, in_format='corner', out_format='corner')
sub box_nms { AI::MXTpu::op('box_nms', @_) }

# box_non_maximum_suppression(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2, score_index=1, id_index=-1, background_id=-1, force_suppress=False, in_format='corner', out_format='corner')
sub box_non_maximum_suppression { AI::MXTpu::op('box_non_maximum_suppression', @_) }

# broadcast_add(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub broadcast_add { AI::MXTpu::op('broadcast_add', @_) }

# broadcast_arctan2(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub broadcast_arctan2 { AI::MXTpu::op('broadcast_arctan2', @_) }

# broadcast_axes(x, axis=(), size=())
sub broadcast_axes { AI::MXTpu::op('broadcast_axes', @_) }

# broadcast_axis(x, axis=(), size=())
sub broadcast_axis { AI::MXTpu::op('broadcast_axis', @_) }

# broadcast_div(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub broadcast_div { AI::MXTpu::op('broadcast_div', @_) }

# broadcast_divide(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub broadcast_divide { AI::MXTpu::op('broadcast_divide', @_) }

# broadcast_equal(a, b)
sub broadcast_equal { AI::MXTpu::op('broadcast_equal', @_) }

# broadcast_greater(a, b)
sub broadcast_greater { AI::MXTpu::op('broadcast_greater', @_) }

# broadcast_greater_equal(a, b)
sub broadcast_greater_equal { AI::MXTpu::op('broadcast_greater_equal', @_) }

# broadcast_hypot(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub broadcast_hypot { AI::MXTpu::op('broadcast_hypot', @_) }

# broadcast_lesser(a, b)
sub broadcast_lesser { AI::MXTpu::op('broadcast_lesser', @_) }

# broadcast_lesser_equal(a, b)
sub broadcast_lesser_equal { AI::MXTpu::op('broadcast_lesser_equal', @_) }

# broadcast_like(x, like, lhs_axes=None, rhs_axes=None)
sub broadcast_like { AI::MXTpu::op('broadcast_like', @_) }

# broadcast_logical_and(a, b)
sub broadcast_logical_and { AI::MXTpu::op('broadcast_logical_and', @_) }

# broadcast_logical_or(a, b)
sub broadcast_logical_or { AI::MXTpu::op('broadcast_logical_or', @_) }

# broadcast_logical_xor(a, b)
sub broadcast_logical_xor { AI::MXTpu::op('broadcast_logical_xor', @_) }

# broadcast_maximum(x: 'ArrayLike', y: 'ArrayLike', /) -> 'Array'
sub broadcast_maximum { AI::MXTpu::op('broadcast_maximum', @_) }

# broadcast_minimum(x: 'ArrayLike', y: 'ArrayLike', /) -> 'Array'
sub broadcast_minimum { AI::MXTpu::op('broadcast_minimum', @_) }

# broadcast_mod(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub broadcast_mod { AI::MXTpu::op('broadcast_mod', @_) }

# broadcast_mul(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub broadcast_mul { AI::MXTpu::op('broadcast_mul', @_) }

# broadcast_multiply(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub broadcast_multiply { AI::MXTpu::op('broadcast_multiply', @_) }

# broadcast_not_equal(a, b)
sub broadcast_not_equal { AI::MXTpu::op('broadcast_not_equal', @_) }

# broadcast_pow(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub broadcast_pow { AI::MXTpu::op('broadcast_pow', @_) }

# broadcast_power(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub broadcast_power { AI::MXTpu::op('broadcast_power', @_) }

# broadcast_sub(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub broadcast_sub { AI::MXTpu::op('broadcast_sub', @_) }

# broadcast_subtract(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub broadcast_subtract { AI::MXTpu::op('broadcast_subtract', @_) }

# broadcast_to(x, shape=None)
sub broadcast_to { AI::MXTpu::op('broadcast_to', @_) }

# calibrate_entropy(hist, hist_edges, num_quantized_bins=255)
sub calibrate_entropy { AI::MXTpu::op('calibrate_entropy', @_) }

# cast(x, dtype='float32')
sub cast { AI::MXTpu::op('cast', @_) }

# cast_storage(data, stype='default')
sub cast_storage { AI::MXTpu::op('cast_storage', @_) }

# cbrt(x: 'ArrayLike', /) -> 'Array'
sub cbrt { AI::MXTpu::op('cbrt', @_) }

# ceil(x: 'ArrayLike', /) -> 'Array'
sub ceil { AI::MXTpu::op('ceil', @_) }

# choose_element_0index(lhs, rhs)
sub choose_element_0index { AI::MXTpu::op('choose_element_0index', @_) }

# clip(x, a_min=None, a_max=None)
sub clip { AI::MXTpu::op('clip', @_) }

# concat(*xs, dim=1)
sub concat { AI::MXTpu::op('concat', @_) }

# concatenate(*xs, dim=1)
sub concatenate { AI::MXTpu::op('concatenate', @_) }

# contrib_ctc_loss(pred, label, pred_lengths=None, label_lengths=None, layout='NTC', label_layout='NT')
sub contrib_ctc_loss { AI::MXTpu::op('contrib_ctc_loss', @_) }

# convolution(x, weight, bias=None, kernel=None, stride=None, dilate=None, pad=None, num_filter=None, num_group=1, no_bias=False, layout='NCHW', cudnn_tune=None, cudnn_off=False, workspace=1024, precision=None)
sub convolution { AI::MXTpu::op('convolution', @_) }

# correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1, stride2=1, pad_size=0, is_multiply=True)
sub correlation { AI::MXTpu::op('correlation', @_) }

# cos(x: 'ArrayLike', /) -> 'Array'
sub cos_ { AI::MXTpu::op('cos', @_) }

# cosh(x: 'ArrayLike', /) -> 'Array'
sub cosh { AI::MXTpu::op('cosh', @_) }

# count_sketch(data, h, s, out_dim=1, processing_batch_size=32)
sub count_sketch { AI::MXTpu::op('count_sketch', @_) }

# crop(x, begin=(), end=(), step=())
sub crop { AI::MXTpu::op('crop', @_) }

# crop_like(data, *crop_like, num_args=1, offset=(0, 0), h_w=(0, 0), center_crop=False)
sub crop_like { AI::MXTpu::op('crop_like', @_) }

# ctc_loss(pred, label, pred_lengths=None, label_lengths=None, layout='NTC', label_layout='NT')
sub ctc_loss { AI::MXTpu::op('ctc_loss', @_) }

# cumsum(x, axis=None, dtype=None)
sub cumsum { AI::MXTpu::op('cumsum', @_) }

# deconvolution(x, weight, bias=None, kernel=None, stride=None, dilate=None, pad=None, adj=None, target_shape=None, num_filter=None, num_group=1, no_bias=True, layout='NCHW', cudnn_tune=None, cudnn_off=False, workspace=512, precision=None)
sub deconvolution { AI::MXTpu::op('deconvolution', @_) }

# degrees(x: 'ArrayLike', /) -> 'Array'
sub degrees { AI::MXTpu::op('degrees', @_) }

# depth_to_space(x, block_size=1)
sub depth_to_space { AI::MXTpu::op('depth_to_space', @_) }

# diag(x, k=0, axis1=0, axis2=1)
sub diag { AI::MXTpu::op('diag', @_) }

# divide(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub divide { AI::MXTpu::op('divide', @_) }

# dot(a, b, transpose_a=False, transpose_b=False, precision=None)
sub dot_ { AI::MXTpu::op('dot', @_) }

# dropout(x, key=None, p=0.5, mode='training', axes=(), _training=True, cudnn_off=False)
sub dropout { AI::MXTpu::op('dropout', @_) }

# elemwise_add(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub elemwise_add { AI::MXTpu::op('elemwise_add', @_) }

# elemwise_div(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub elemwise_div { AI::MXTpu::op('elemwise_div', @_) }

# elemwise_divide(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub elemwise_divide { AI::MXTpu::op('elemwise_divide', @_) }

# elemwise_mul(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub elemwise_mul { AI::MXTpu::op('elemwise_mul', @_) }

# elemwise_multiply(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub elemwise_multiply { AI::MXTpu::op('elemwise_multiply', @_) }

# elemwise_sub(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub elemwise_sub { AI::MXTpu::op('elemwise_sub', @_) }

# elemwise_subtract(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub elemwise_subtract { AI::MXTpu::op('elemwise_subtract', @_) }

# elemwise_sum(*xs)
sub elemwise_sum { AI::MXTpu::op('elemwise_sum', @_) }

# embedding(data, weight, input_dim=None, output_dim=None, dtype=None, sparse_grad=False)
sub embedding { AI::MXTpu::op('embedding', @_) }

# equal(a, b)
sub equal { AI::MXTpu::op('equal', @_) }

# erf(x: 'ArrayLike') -> 'Array'
sub erf { AI::MXTpu::op('erf', @_) }

# erfinv(x: 'ArrayLike') -> 'Array'
sub erfinv { AI::MXTpu::op('erfinv', @_) }

# exp(x: 'ArrayLike', /) -> 'Array'
sub exp_ { AI::MXTpu::op('exp', @_) }

# expand_dims(x, axis=0)
sub expand_dims { AI::MXTpu::op('expand_dims', @_) }

# expm1(x: 'ArrayLike', /) -> 'Array'
sub expm1 { AI::MXTpu::op('expm1', @_) }

# extracttrian(a, offset=0, lower=True)
sub extracttrian { AI::MXTpu::op('extracttrian', @_) }

# eye(N=1, M=0, k=0, dtype='float32')
sub eye { AI::MXTpu::op('eye', @_) }

# fft(data, compute_size=128)
sub fft { AI::MXTpu::op('fft', @_) }

# fill_element_0index(lhs, mhs, rhs)
sub fill_element_0index { AI::MXTpu::op('fill_element_0index', @_) }

# fix(x: 'ArrayLike') -> 'Array'
sub fix { AI::MXTpu::op('fix', @_) }

# flatten(x)
sub flatten { AI::MXTpu::op('flatten', @_) }

# flip(x, axis=())
sub flip_ { AI::MXTpu::op('flip', @_) }

# floor(x: 'ArrayLike', /) -> 'Array'
sub floor { AI::MXTpu::op('floor', @_) }

# ftml_update(weight, grad, d, v, z, lr=None, t=1, beta1=0.6, beta2=0.999, epsilon=1e-08, wd=0.0, rescale_grad=1.0, clip_grad=-1.0)
sub ftml_update { AI::MXTpu::op('ftml_update', @_) }

# ftrl_update(weight, grad, z, n, lr=None, lamda1=0.01, beta=1.0, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0)
sub ftrl_update { AI::MXTpu::op('ftrl_update', @_) }

# full(shape=(), value=0.0, dtype='float32')
sub full { AI::MXTpu::op('full', @_) }

# fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False, flatten=True, precision=None)
sub fully_connected { AI::MXTpu::op('fully_connected', @_) }

# gamma(x: 'ArrayLike') -> 'Array'
sub gamma { AI::MXTpu::op('gamma', @_) }

# gammaln(x: 'ArrayLike') -> 'Array'
sub gammaln { AI::MXTpu::op('gammaln', @_) }

# gather_nd(data, indices)
sub gather_nd { AI::MXTpu::op('gather_nd', @_) }

# greater(a, b)
sub greater { AI::MXTpu::op('greater', @_) }

# greater_equal(a, b)
sub greater_equal { AI::MXTpu::op('greater_equal', @_) }

# grid_generator(data, transform_type='affine', target_shape=(0, 0))
sub grid_generator { AI::MXTpu::op('grid_generator', @_) }

# group_adagrad_update(weight, grad, history, lr=None, epsilon=1e-07, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0)
sub group_adagrad_update { AI::MXTpu::op('group_adagrad_update', @_) }

# group_norm(x, gamma, beta, num_groups=1, eps=1e-05)
sub group_norm { AI::MXTpu::op('group_norm', @_) }

# hamming(M=1, dtype='float32', ctx=None)
sub hamming { AI::MXTpu::op('hamming', @_) }

# hanning(M=1, dtype='float32', ctx=None)
sub hanning { AI::MXTpu::op('hanning', @_) }

# hard_sigmoid(x, alpha=0.2, beta=0.5)
sub hard_sigmoid { AI::MXTpu::op('hard_sigmoid', @_) }

# hawkesll(mu, alpha, beta, state, lags, marks, valid_length, max_time)
sub hawkesll { AI::MXTpu::op('hawkesll', @_) }

# histogram(data, bin_cnt=10, range=None)
sub histogram { AI::MXTpu::op('histogram', @_) }

# hypot(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub hypot { AI::MXTpu::op('hypot', @_) }

# identity(x)
sub identity { AI::MXTpu::op('identity', @_) }

# identity_attach_kl_sparse_reg(data, sparseness_target=0.1, penalty=0.001, momentum=0.9)
sub identity_attach_kl_sparse_reg { AI::MXTpu::op('identity_attach_kl_sparse_reg', @_) }

# ifft(data, compute_size=128)
sub ifft { AI::MXTpu::op('ifft', @_) }

# image_crop(data, x=0, y=0, width=1, height=1)
sub image_crop { AI::MXTpu::op('image_crop', @_) }

# image_flip_left_right(data)
sub image_flip_left_right { AI::MXTpu::op('image_flip_left_right', @_) }

# image_flip_top_bottom(data)
sub image_flip_top_bottom { AI::MXTpu::op('image_flip_top_bottom', @_) }

# image_normalize(data, mean=0.0, std=1.0)
sub image_normalize { AI::MXTpu::op('image_normalize', @_) }

# image_random_brightness(data, key=None, min_factor=0.0, max_factor=1.0)
sub image_random_brightness { AI::MXTpu::op('image_random_brightness', @_) }

# image_random_color_jitter(data, key=None, brightness=0.0, contrast=0.0, saturation=0.0, hue=0.0)
sub image_random_color_jitter { AI::MXTpu::op('image_random_color_jitter', @_) }

# image_random_contrast(data, key=None, min_factor=0.0, max_factor=1.0)
sub image_random_contrast { AI::MXTpu::op('image_random_contrast', @_) }

# image_random_flip_left_right(data, key=None, p=0.5)
sub image_random_flip_left_right { AI::MXTpu::op('image_random_flip_left_right', @_) }

# image_random_flip_top_bottom(data, key=None, p=0.5)
sub image_random_flip_top_bottom { AI::MXTpu::op('image_random_flip_top_bottom', @_) }

# image_random_hue(data, key=None, min_factor=0.0, max_factor=1.0)
sub image_random_hue { AI::MXTpu::op('image_random_hue', @_) }

# image_random_lighting(data, key=None, alpha_std=0.05)
sub image_random_lighting { AI::MXTpu::op('image_random_lighting', @_) }

# image_random_saturation(data, key=None, min_factor=0.0, max_factor=1.0)
sub image_random_saturation { AI::MXTpu::op('image_random_saturation', @_) }

# image_resize(data, size=(0, 0), keep_ratio=False, interp=1)
sub image_resize { AI::MXTpu::op('image_resize', @_) }

# image_to_tensor(data)
sub image_to_tensor { AI::MXTpu::op('image_to_tensor', @_) }

# index_array(data, axes=None)
sub index_array { AI::MXTpu::op('index_array', @_) }

# index_copy(data, index, new_tensor)
sub index_copy { AI::MXTpu::op('index_copy', @_) }

# instance_norm(x, gamma, beta, eps=0.001)
sub instance_norm { AI::MXTpu::op('instance_norm', @_) }

# khatri_rao(*mats)
sub khatri_rao { AI::MXTpu::op('khatri_rao', @_) }

# l2_normalization(x, eps=1e-10, mode='instance')
sub l2_normalization { AI::MXTpu::op('l2_normalization', @_) }

# lamb_update_phase1(weight, grad, mean, var, lr=None, beta1=0.9, beta2=0.999, epsilon=1e-06, t=1, bias_correction=True, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0)
sub lamb_update_phase1 { AI::MXTpu::op('lamb_update_phase1', @_) }

# lamb_update_phase2(weight, g, r1, r2, lr=None, lower_bound=-1.0, upper_bound=-1.0)
sub lamb_update_phase2 { AI::MXTpu::op('lamb_update_phase2', @_) }

# layer_norm(x, gamma, beta, axis=-1, eps=1e-05, output_mean_var=False)
sub layer_norm { AI::MXTpu::op('layer_norm', @_) }

# leaky_relu(x, gamma=None, act_type='leaky', slope=0.25, lower_bound=0.125, upper_bound=0.334)
sub leaky_relu { AI::MXTpu::op('leaky_relu', @_) }

# lesser(a, b)
sub lesser { AI::MXTpu::op('lesser', @_) }

# lesser_equal(a, b)
sub lesser_equal { AI::MXTpu::op('lesser_equal', @_) }

# linalg_det(A)
sub linalg_det { AI::MXTpu::op('linalg_det', @_) }

# linalg_extractdiag(A, offset=0)
sub linalg_extractdiag { AI::MXTpu::op('linalg_extractdiag', @_) }

# linalg_extracttrian(a, offset=0, lower=True)
sub linalg_extracttrian { AI::MXTpu::op('linalg_extracttrian', @_) }

# linalg_gelqf(A)
sub linalg_gelqf { AI::MXTpu::op('linalg_gelqf', @_) }

# linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, axis=-2, precision=None)
sub linalg_gemm { AI::MXTpu::op('linalg_gemm', @_) }

# linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2, precision=None)
sub linalg_gemm2 { AI::MXTpu::op('linalg_gemm2', @_) }

# linalg_inverse(A)
sub linalg_inverse { AI::MXTpu::op('linalg_inverse', @_) }

# linalg_makediag(d, offset=0)
sub linalg_makediag { AI::MXTpu::op('linalg_makediag', @_) }

# linalg_maketrian(a, offset=0, lower=True)
sub linalg_maketrian { AI::MXTpu::op('linalg_maketrian', @_) }

# linalg_potrf(A, lower=True)
sub linalg_potrf { AI::MXTpu::op('linalg_potrf', @_) }

# linalg_potri(A, lower=True)
sub linalg_potri { AI::MXTpu::op('linalg_potri', @_) }

# linalg_slogdet(A)
sub linalg_slogdet { AI::MXTpu::op('linalg_slogdet', @_) }

# linalg_sumlogdiag(A)
sub linalg_sumlogdiag { AI::MXTpu::op('linalg_sumlogdiag', @_) }

# linalg_syevd(a)
sub linalg_syevd { AI::MXTpu::op('linalg_syevd', @_) }

# linalg_syrk(A, transpose=False, alpha=1.0, precision=None)
sub linalg_syrk { AI::MXTpu::op('linalg_syrk', @_) }

# linalg_trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0, precision=None)
sub linalg_trmm { AI::MXTpu::op('linalg_trmm', @_) }

# linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0)
sub linalg_trsm { AI::MXTpu::op('linalg_trsm', @_) }

# linear_regression_output(data, label, grad_scale=1.0)
sub linear_regression_output { AI::MXTpu::op('linear_regression_output', @_) }

# linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype='float32')
sub linspace { AI::MXTpu::op('linspace', @_) }

# log(x)
sub log_ { AI::MXTpu::op('log', @_) }

# log10(x: 'ArrayLike', /) -> 'Array'
sub log10 { AI::MXTpu::op('log10', @_) }

# log1p(x: 'ArrayLike', /) -> 'Array'
sub log1p { AI::MXTpu::op('log1p', @_) }

# log2(x: 'ArrayLike', /) -> 'Array'
sub log2 { AI::MXTpu::op('log2', @_) }

# log_softmax(x, axis=-1, temperature=None, dtype=None)
sub log_softmax { AI::MXTpu::op('log_softmax', @_) }

# logical_and(a, b)
sub logical_and { AI::MXTpu::op('logical_and', @_) }

# logical_not(x)
sub logical_not { AI::MXTpu::op('logical_not', @_) }

# logical_or(a, b)
sub logical_or { AI::MXTpu::op('logical_or', @_) }

# logical_xor(a, b)
sub logical_xor { AI::MXTpu::op('logical_xor', @_) }

# logistic_regression_output(data, label, grad_scale=1.0)
sub logistic_regression_output { AI::MXTpu::op('logistic_regression_output', @_) }

# lrn(x, alpha=0.0001, beta=0.75, knorm=2.0, nsize=5)
sub lrn { AI::MXTpu::op('lrn', @_) }

# mae_regression_output(data, label, grad_scale=1.0)
sub mae_regression_output { AI::MXTpu::op('mae_regression_output', @_) }

# make_loss(x, grad_scale=1.0, valid_thresh=0.0, normalization='null')
sub make_loss { AI::MXTpu::op('make_loss', @_) }

# maketrian(a, offset=0, lower=True)
sub maketrian { AI::MXTpu::op('maketrian', @_) }

# max(x, axis=None, keepdims=False, exclude=False)
sub max_ { AI::MXTpu::op('max', @_) }

# max_axis(x, axis=None, keepdims=False, exclude=False)
sub max_axis { AI::MXTpu::op('max_axis', @_) }

# maximum(x: 'ArrayLike', y: 'ArrayLike', /) -> 'Array'
sub maximum { AI::MXTpu::op('maximum', @_) }

# mean(x, axis=None, keepdims=False, exclude=False)
sub mean { AI::MXTpu::op('mean', @_) }

# min(x, axis=None, keepdims=False, exclude=False)
sub min_ { AI::MXTpu::op('min', @_) }

# min_axis(x, axis=None, keepdims=False, exclude=False)
sub min_axis { AI::MXTpu::op('min_axis', @_) }

# minimum(x: 'ArrayLike', y: 'ArrayLike', /) -> 'Array'
sub minimum { AI::MXTpu::op('minimum', @_) }

# mod(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub mod { AI::MXTpu::op('mod', @_) }

# moments(data, axes=None, keepdims=False)
sub moments { AI::MXTpu::op('moments', @_) }

# mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad=1.0, lr=None, eta=None, beta1=0.9, beta2=0.999, epsilon=1e-08, wd=0.0, clip_gradient=-1.0)
sub mp_adamw_update { AI::MXTpu::op('mp_adamw_update', @_) }

# mp_nag_mom_update(weight, grad, mom, weight32, lr=None, momentum=0.0, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0)
sub mp_nag_mom_update { AI::MXTpu::op('mp_nag_mom_update', @_) }

# mp_sgd_mom_update(weight, grad, mom, weight32, lr=None, momentum=0.0, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True)
sub mp_sgd_mom_update { AI::MXTpu::op('mp_sgd_mom_update', @_) }

# mp_sgd_update(weight, grad, weight32, lr=None, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True)
sub mp_sgd_update { AI::MXTpu::op('mp_sgd_update', @_) }

# multi_all_finite(*arrays, num_arrays=1, init_output=True)
sub multi_all_finite { AI::MXTpu::op('multi_all_finite', @_) }

# multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001, eps=1e-08, rescale_grad=1.0)
sub multi_lars { AI::MXTpu::op('multi_lars', @_) }

# multi_mp_sgd_mom_update(*data, lrs=None, wds=None, num_weights=1, momentum=0.0, rescale_grad=1.0, clip_gradient=-1.0)
sub multi_mp_sgd_mom_update { AI::MXTpu::op('multi_mp_sgd_mom_update', @_) }

# multi_mp_sgd_update(*data, lrs=None, wds=None, num_weights=1, rescale_grad=1.0, clip_gradient=-1.0)
sub multi_mp_sgd_update { AI::MXTpu::op('multi_mp_sgd_update', @_) }

# multi_sgd_mom_update(*data, lrs=None, wds=None, num_weights=1, momentum=0.0, rescale_grad=1.0, clip_gradient=-1.0)
sub multi_sgd_mom_update { AI::MXTpu::op('multi_sgd_mom_update', @_) }

# multi_sgd_update(*data, lrs=None, wds=None, num_weights=1, rescale_grad=1.0, clip_gradient=-1.0)
sub multi_sgd_update { AI::MXTpu::op('multi_sgd_update', @_) }

# multi_sum_sq(*arrays, num_arrays=1)
sub multi_sum_sq { AI::MXTpu::op('multi_sum_sq', @_) }

# multibox_detection(cls_pred, loc_pred, anchors, clip=True, threshold=0.01, background_id=0, nms_threshold=0.5, force_suppress=False, variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1)
sub multibox_detection { AI::MXTpu::op('multibox_detection', @_) }

# multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False, steps=(-1.0, -1.0), offsets=(0.5, 0.5))
sub multibox_prior { AI::MXTpu::op('multibox_prior', @_) }

# multinomial(data, key=None, shape=(), get_prob=False, dtype='int32')
sub multinomial { AI::MXTpu::op('multinomial', @_) }

# multiply(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub multiply { AI::MXTpu::op('multiply', @_) }

# nag_mom_update(weight, grad, mom, lr=None, momentum=0.0, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0)
sub nag_mom_update { AI::MXTpu::op('nag_mom_update', @_) }

# nanprod(x, axis=None, keepdims=False, exclude=False)
sub nanprod { AI::MXTpu::op('nanprod', @_) }

# nansum(x, axis=None, keepdims=False, exclude=False)
sub nansum { AI::MXTpu::op('nansum', @_) }

# negative(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub negative { AI::MXTpu::op('negative', @_) }

# norm(x, ord=2, axis=None, keepdims=False)
sub norm { AI::MXTpu::op('norm', @_) }

# norm_fro(A)
sub norm_fro { AI::MXTpu::op('norm_fro', @_) }

# normal(key=None, loc=0.0, scale=1.0, shape=(), dtype='float32', ctx=None)
sub normal { AI::MXTpu::op('normal', @_) }

# not_equal(a, b)
sub not_equal { AI::MXTpu::op('not_equal', @_) }

# one_hot(indices, depth=0, on_value=1.0, off_value=0.0, dtype='float32')
sub one_hot { AI::MXTpu::op('one_hot', @_) }

# ones_like(x)
sub ones_like { AI::MXTpu::op('ones_like', @_) }

# pad(x, mode='constant', pad_width=(), constant_value=0.0)
sub pad { AI::MXTpu::op('pad', @_) }

# pick(x, index, axis=-1, keepdims=False, mode='clip')
sub pick { AI::MXTpu::op('pick', @_) }

# pooling(x, kernel=None, pool_type='max', stride=None, pad=None, global_pool=False, pooling_convention='valid', cudnn_off=False, p_value=2, count_include_pad=True, layout=None)
sub pooling { AI::MXTpu::op('pooling', @_) }

# power(x1: 'ArrayLike', x2: 'ArrayLike', /) -> 'Array'
sub power { AI::MXTpu::op('power', @_) }

# preloaded_multi_mp_sgd_mom_update(*data, num_weights=1, momentum=0.0, rescale_grad=1.0, clip_gradient=-1.0)
sub preloaded_multi_mp_sgd_mom_update { AI::MXTpu::op('preloaded_multi_mp_sgd_mom_update', @_) }

# preloaded_multi_mp_sgd_update(*data, num_weights=1, rescale_grad=1.0, clip_gradient=-1.0)
sub preloaded_multi_mp_sgd_update { AI::MXTpu::op('preloaded_multi_mp_sgd_update', @_) }

# preloaded_multi_sgd_mom_update(*data, num_weights=1, momentum=0.0, rescale_grad=1.0, clip_gradient=-1.0)
sub preloaded_multi_sgd_mom_update { AI::MXTpu::op('preloaded_multi_sgd_mom_update', @_) }

# preloaded_multi_sgd_update(*data, num_weights=1, rescale_grad=1.0, clip_gradient=-1.0)
sub preloaded_multi_sgd_update { AI::MXTpu::op('preloaded_multi_sgd_update', @_) }

# prod(x, axis=None, keepdims=False, exclude=False)
sub prod { AI::MXTpu::op('prod', @_) }

# quadratic(data, a=0.0, b=0.0, c=0.0)
sub quadratic { AI::MXTpu::op('quadratic', @_) }

# quantize_v1(data, min_range, max_range, out_type='int8')
sub quantize_v1 { AI::MXTpu::op('quantize_v1', @_) }

# quantize_v2(data, out_type='int8', min_calib_range=None, max_calib_range=None)
sub quantize_v2 { AI::MXTpu::op('quantize_v2', @_) }

# quantized_act(data, min_data, max_data, act_type='relu')
sub quantized_act { AI::MXTpu::op('quantized_act', @_) }

# quantized_batch_norm(data, gamma, beta, moving_mean, moving_var, min_data, max_data, eps=0.001, min_calib_range=None, max_calib_range=None)
sub quantized_batch_norm { AI::MXTpu::op('quantized_batch_norm', @_) }

# quantized_concat(*args, dim=1, num_args=None)
sub quantized_concat { AI::MXTpu::op('quantized_concat', @_) }

# quantized_conv(data, weight, bias, min_data, max_data, min_weight, max_weight, min_bias, max_bias, kernel=(1, 1), stride=(1, 1), pad=(0, 0), dilate=(1, 1), num_filter=1, num_group=1, no_bias=False, layout='NCHW')
sub quantized_conv { AI::MXTpu::op('quantized_conv', @_) }

# quantized_elemwise_add(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max)
sub quantized_elemwise_add { AI::MXTpu::op('quantized_elemwise_add', @_) }

# quantized_flatten(data, min_data, max_data)
sub quantized_flatten { AI::MXTpu::op('quantized_flatten', @_) }

# quantized_fully_connected(data, weight, bias, min_data, max_data, min_weight, max_weight, min_bias, max_bias, num_hidden=1, no_bias=False, flatten=True)
sub quantized_fully_connected { AI::MXTpu::op('quantized_fully_connected', @_) }

# quantized_pooling(data, min_data, max_data, kernel=(2, 2), pool_type='max', stride=(1, 1), pad=(0, 0), global_pool=False)
sub quantized_pooling { AI::MXTpu::op('quantized_pooling', @_) }

# radians(x: 'ArrayLike', /) -> 'Array'
sub radians { AI::MXTpu::op('radians', @_) }

# randint(key=None, low=0, high=1, shape=(), dtype='int32', ctx=None)
sub randint { AI::MXTpu::op('randint', @_) }

# randn(key=None, loc=0.0, scale=1.0, shape=(), dtype='float32', ctx=None)
sub randn { AI::MXTpu::op('randn', @_) }

# random_exponential(key=None, lam=1.0, shape=(), dtype='float32', ctx=None)
sub random_exponential { AI::MXTpu::op('random_exponential', @_) }

# random_gamma(key=None, alpha=1.0, beta=1.0, shape=(), dtype='float32', ctx=None)
sub random_gamma { AI::MXTpu::op('random_gamma', @_) }

# random_generalized_negative_binomial(key=None, mu=1.0, alpha=1.0, shape=(), dtype='float32', ctx=None)
sub random_generalized_negative_binomial { AI::MXTpu::op('random_generalized_negative_binomial', @_) }

# random_negative_binomial(key=None, k=1, p=1.0, shape=(), dtype='float32', ctx=None)
sub random_negative_binomial { AI::MXTpu::op('random_negative_binomial', @_) }

# random_normal(key=None, loc=0.0, scale=1.0, shape=(), dtype='float32', ctx=None)
sub random_normal { AI::MXTpu::op('random_normal', @_) }

# random_poisson(key=None, lam=1.0, shape=(), dtype='float32', ctx=None)
sub random_poisson { AI::MXTpu::op('random_poisson', @_) }

# random_randint(key=None, low=0, high=1, shape=(), dtype='int32', ctx=None)
sub random_randint { AI::MXTpu::op('random_randint', @_) }

# random_uniform(key=None, low=0.0, high=1.0, shape=(), dtype='float32', ctx=None)
sub random_uniform { AI::MXTpu::op('random_uniform', @_) }

# ravel_multi_index(data, shape=())
sub ravel_multi_index { AI::MXTpu::op('ravel_multi_index', @_) }

# rcbrt(x)
sub rcbrt { AI::MXTpu::op('rcbrt', @_) }

# reciprocal(x)
sub reciprocal { AI::MXTpu::op('reciprocal', @_) }

# relu(x)
sub relu { AI::MXTpu::op('relu', @_) }

# repeat(x, repeats=1, axis=None)
sub repeat { AI::MXTpu::op('repeat', @_) }

# requantize(data, min_range, max_range, min_calib_range=None, max_calib_range=None)
sub requantize { AI::MXTpu::op('requantize', @_) }

# reshape(x, shape=None, reverse=False)
sub reshape { AI::MXTpu::op('reshape', @_) }

# reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None, rhs_end=None)
sub reshape_like { AI::MXTpu::op('reshape_like', @_) }

# reverse(x, axis=())
sub reverse_ { AI::MXTpu::op('reverse', @_) }

# rint(x: 'ArrayLike', /) -> 'Array'
sub rint { AI::MXTpu::op('rint', @_) }

# rmsprop_update(weight, grad, n, lr=None, gamma1=0.95, epsilon=1e-08, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0)
sub rmsprop_update { AI::MXTpu::op('rmsprop_update', @_) }

# rmspropalex_update(weight, grad, n, g, delta, lr=None, gamma1=0.95, gamma2=0.9, epsilon=1e-08, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0)
sub rmspropalex_update { AI::MXTpu::op('rmspropalex_update', @_) }

# rnn(data, parameters, state, state_cell=None, sequence_length=None, key=None, *, mode='lstm', state_size=None, num_layers=1, bidirectional=False, p=0.0, state_outputs=False, projection_size=None, lstm_state_clip_min=None, lstm_state_clip_max=None, lstm_state_clip_nan=False, use_sequence_length=False, _training=True)
sub rnn { AI::MXTpu::op('rnn', @_) }

# roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0, sample_ratio=-1, position_sensitive=False, aligned=False)
sub roi_align { AI::MXTpu::op('roi_align', @_) }

# roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0)
sub roi_pooling { AI::MXTpu::op('roi_pooling', @_) }

# round(a: 'ArrayLike', decimals: 'int' = 0, out: 'None' = None) -> 'Array'
sub round { AI::MXTpu::op('round', @_) }

# rsqrt(x)
sub rsqrt { AI::MXTpu::op('rsqrt', @_) }

# sample_gamma(alpha, beta, key=None, shape=(), dtype='float32')
sub sample_gamma { AI::MXTpu::op('sample_gamma', @_) }

# sample_multinomial(data, key=None, shape=(), get_prob=False, dtype='int32')
sub sample_multinomial { AI::MXTpu::op('sample_multinomial', @_) }

# sample_normal(mu, sigma, key=None, shape=(), dtype='float32')
sub sample_normal { AI::MXTpu::op('sample_normal', @_) }

# sample_uniform(low, high, key=None, shape=(), dtype='float32')
sub sample_uniform { AI::MXTpu::op('sample_uniform', @_) }

# scatter_nd(data, indices, shape=None)
sub scatter_nd { AI::MXTpu::op('scatter_nd', @_) }

# sequence_last(data, sequence_length=None, use_sequence_length=True, axis=0)
sub sequence_last { AI::MXTpu::op('sequence_last', @_) }

# sequence_mask(data, sequence_length=None, use_sequence_length=True, value=0.0, axis=0)
sub sequence_mask { AI::MXTpu::op('sequence_mask', @_) }

# sequence_reverse(data, sequence_length=None, use_sequence_length=True, axis=0)
sub sequence_reverse { AI::MXTpu::op('sequence_reverse', @_) }

# sgd_mom_update(weight, grad, mom, lr=None, momentum=0.0, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True)
sub sgd_mom_update { AI::MXTpu::op('sgd_mom_update', @_) }

# sgd_update(weight, grad, lr=None, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True)
sub sgd_update { AI::MXTpu::op('sgd_update', @_) }

# shape_array(x)
sub shape_array { AI::MXTpu::op('shape_array', @_) }

# shuffle(data, key=None)
sub shuffle { AI::MXTpu::op('shuffle', @_) }

# sigmoid(x)
sub sigmoid { AI::MXTpu::op('sigmoid', @_) }

# sign(x: 'ArrayLike', /) -> 'Array'
sub sign_ { AI::MXTpu::op('sign', @_) }

# signsgd_update(weight, grad, lr=None, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0)
sub signsgd_update { AI::MXTpu::op('signsgd_update', @_) }

# signum_update(weight, grad, mom, lr=None, momentum=0.0, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0)
sub signum_update { AI::MXTpu::op('signum_update', @_) }

# sin(x: 'ArrayLike', /) -> 'Array'
sub sin_ { AI::MXTpu::op('sin', @_) }

# sinh(x: 'ArrayLike', /) -> 'Array'
sub sinh { AI::MXTpu::op('sinh', @_) }

# size_array(x)
sub size_array { AI::MXTpu::op('size_array', @_) }

# slice(x, begin=(), end=(), step=())
sub slice { AI::MXTpu::op('slice', @_) }

# slice_axis(x, axis=0, begin=0, end=None)
sub slice_axis { AI::MXTpu::op('slice_axis', @_) }

# slice_like(x, like, axes=())
sub slice_like { AI::MXTpu::op('slice_like', @_) }

# smooth_l1(x, scalar=1.0)
sub smooth_l1 { AI::MXTpu::op('smooth_l1', @_) }

# softmax(x, axis=-1, temperature=None, length=None, use_length=False, dtype=None)
sub softmax { AI::MXTpu::op('softmax', @_) }

# softmax_cross_entropy(data, label)
sub softmax_cross_entropy { AI::MXTpu::op('softmax_cross_entropy', @_) }

# softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0, multi_output=False, use_ignore=False, preserve_shape=False, normalization='null', out_grad=False, smooth_alpha=0.0)
sub softmax_output { AI::MXTpu::op('softmax_output', @_) }

# softmin(x, axis=-1)
sub softmin { AI::MXTpu::op('softmin', @_) }

# softrelu(x)
sub softrelu { AI::MXTpu::op('softrelu', @_) }

# softsign(x)
sub softsign { AI::MXTpu::op('softsign', @_) }

# sort(x, axis=-1, is_ascend=True)
sub sort_ { AI::MXTpu::op('sort', @_) }

# space_to_depth(x, block_size=1)
sub space_to_depth { AI::MXTpu::op('space_to_depth', @_) }

# sparse_adagrad_update(weight, grad, history, lr=None, epsilon=1e-07, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0)
sub sparse_adagrad_update { AI::MXTpu::op('sparse_adagrad_update', @_) }

# sparse_retain(data, indices)
sub sparse_retain { AI::MXTpu::op('sparse_retain', @_) }

# spatial_transformer(data, loc, target_shape=(0, 0), transform_type='affine', sampler_type='bilinear', cudnn_off=None)
sub spatial_transformer { AI::MXTpu::op('spatial_transformer', @_) }

# split(x, num_outputs=1, axis=1, squeeze_axis=False)
sub split_ { AI::MXTpu::op('split', @_) }

# split_v2(data, indices=(), axis=0, squeeze_axis=False, sections=0)
sub split_v2 { AI::MXTpu::op('split_v2', @_) }

# sqrt(x: 'ArrayLike', /) -> 'Array'
sub sqrt_ { AI::MXTpu::op('sqrt', @_) }

# square(x: 'ArrayLike', /) -> 'Array'
sub square { AI::MXTpu::op('square', @_) }

# squeeze(x, axis=None)
sub squeeze { AI::MXTpu::op('squeeze', @_) }

# stack(*xs, axis=0)
sub stack { AI::MXTpu::op('stack', @_) }

# stop_gradient(x)
sub stop_gradient { AI::MXTpu::op('stop_gradient', @_) }

# subtract(*args: 'ArrayLike', out: 'None' = None, where: 'None' = None) -> 'Any'
sub subtract { AI::MXTpu::op('subtract', @_) }

# sum(x, axis=None, keepdims=False, exclude=False)
sub sum_ { AI::MXTpu::op('sum', @_) }

# sum_axis(x, axis=None, keepdims=False, exclude=False)
sub sum_axis { AI::MXTpu::op('sum_axis', @_) }

# svm_output(data, label, margin=1.0, regularization_coefficient=1.0, use_linear=False)
sub svm_output { AI::MXTpu::op('svm_output', @_) }

# swapaxes(x, dim1=0, dim2=0)
sub swapaxes { AI::MXTpu::op('swapaxes', @_) }

# syevd(a)
sub syevd { AI::MXTpu::op('syevd', @_) }

# take(a, indices, axis=0, mode='clip')
sub take { AI::MXTpu::op('take', @_) }

# tan(x: 'ArrayLike', /) -> 'Array'
sub tan { AI::MXTpu::op('tan', @_) }

# tanh(x)
sub tanh { AI::MXTpu::op('tanh', @_) }

# tile(x, reps=())
sub tile { AI::MXTpu::op('tile', @_) }

# topk(x, axis=-1, k=1, ret_typ='indices', is_ascend=False, dtype='float32')
sub topk { AI::MXTpu::op('topk', @_) }

# transpose(x, axes=None)
sub transpose { AI::MXTpu::op('transpose', @_) }

# trunc(x: 'ArrayLike') -> 'Array'
sub trunc { AI::MXTpu::op('trunc', @_) }

# uniform(key=None, low=0.0, high=1.0, shape=(), dtype='float32', ctx=None)
sub uniform { AI::MXTpu::op('uniform', @_) }

# unravel_index(data, shape=())
sub unravel_index { AI::MXTpu::op('unravel_index', @_) }

# upsampling(*data, scale=1, sample_type='nearest', num_args=1, num_filter=0, multi_input_mode='concat', workspace=512)
sub upsampling { AI::MXTpu::op('upsampling', @_) }

# where(cond, x, y)
sub where { AI::MXTpu::op('where', @_) }

# zeros_like(x)
sub zeros_like { AI::MXTpu::op('zeros_like', @_) }

1;
