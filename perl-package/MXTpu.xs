/* XS bindings: Perl <-> the MXT* C ABI (src/c_api_runtime.cc).
 *
 * The second generated non-C++ frontend over the C ABI (the first is
 * cpp-package/), proving the attach seam generalizes — analog of the
 * reference's perl-package/ (ref: perl-package/AI-MXNetCAPI/mxnet.i,
 * which SWIG-wraps include/mxnet/c_api.h the same way).
 *
 * Handles cross the boundary as IVs (pointer-sized integers); the
 * Perl-side AI::MXTpu::NDArray class owns lifetime (DESTROY -> free).
 * Only f32 crosses in this frontend, matching example/capi/.
 */
#define PERL_NO_GET_CONTEXT
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

extern const char* MXTGetLastError(void);
extern int MXTNDArrayFromData(const int64_t* shape, uint32_t ndim,
                              int dtype, const void* data, size_t nbytes,
                              void** out);
extern int MXTNDArrayFree(void* h);
extern int MXTNDArrayGetShape(void* h, uint32_t* out_ndim,
                              int64_t* out_shape);
extern int MXTNDArraySyncCopyToCPU(void* h, void* data, size_t nbytes);
extern int MXTNDArrayWaitAll(void);
extern int MXTImperativeInvoke(const char* op, uint32_t nin, void** in,
                               uint32_t nparam, const char** keys,
                               const char** vals, uint32_t* nout,
                               void** out, uint32_t max_out);
extern int MXTAutogradMarkVariables(uint32_t n, void** h);
extern int MXTAutogradSetIsRecording(int rec);
extern int MXTAutogradBackward(uint32_t n, void** out);
extern int MXTNDArrayGetGrad(void* h, void** grad);

#define MAX_OUTS 8
#define MAX_DIMS 8

static void croak_abi(pTHX_ const char* where) {
    croak("AI::MXTpu: %s failed: %s", where, MXTGetLastError());
}

MODULE = AI::MXTpu    PACKAGE = AI::MXTpu

PROTOTYPES: DISABLE

const char*
_last_error()
CODE:
    RETVAL = MXTGetLastError();
OUTPUT:
    RETVAL

IV
_from_data(shape_ref, data)
    SV* shape_ref
    SV* data
CODE:
{
    AV* av = (AV*)SvRV(shape_ref);
    uint32_t ndim = (uint32_t)(av_len(av) + 1);
    int64_t shape[MAX_DIMS];
    uint32_t i;
    STRLEN nbytes;
    const char* buf;
    void* out = NULL;
    if (ndim > MAX_DIMS)
        croak("AI::MXTpu: ndim %u exceeds %d", ndim, MAX_DIMS);
    for (i = 0; i < ndim; ++i)
        shape[i] = (int64_t)SvIV(*av_fetch(av, i, 0));
    buf = SvPVbyte(data, nbytes);
    if (MXTNDArrayFromData(shape, ndim, /*f32*/0, buf, (size_t)nbytes,
                           &out) != 0)
        croak_abi(aTHX_ "NDArrayFromData");
    RETVAL = PTR2IV(out);
}
OUTPUT:
    RETVAL

void
_free(h)
    IV h
CODE:
    MXTNDArrayFree(INT2PTR(void*, h));

void
_shape(h)
    IV h
PPCODE:
{
    uint32_t ndim = 0, i;
    int64_t shape[MAX_DIMS];
    if (MXTNDArrayGetShape(INT2PTR(void*, h), &ndim, shape) != 0)
        croak_abi(aTHX_ "NDArrayGetShape");
    EXTEND(SP, ndim);
    for (i = 0; i < ndim; ++i)
        mPUSHi((IV)shape[i]);
}

SV*
_to_bytes(h, nbytes)
    IV h
    IV nbytes
CODE:
{
    SV* out = newSV((STRLEN)nbytes + 1);
    SvPOK_on(out);
    if (MXTNDArraySyncCopyToCPU(INT2PTR(void*, h), SvPVX(out),
                                (size_t)nbytes) != 0) {
        SvREFCNT_dec(out);
        croak_abi(aTHX_ "NDArraySyncCopyToCPU");
    }
    SvCUR_set(out, (STRLEN)nbytes);
    RETVAL = out;
}
OUTPUT:
    RETVAL

void
_waitall()
CODE:
    if (MXTNDArrayWaitAll() != 0)
        croak_abi(aTHX_ "NDArrayWaitAll");

void
_invoke(op, in_ref, keys_ref, vals_ref)
    const char* op
    SV* in_ref
    SV* keys_ref
    SV* vals_ref
PPCODE:
{
    AV* in_av = (AV*)SvRV(in_ref);
    AV* k_av = (AV*)SvRV(keys_ref);
    AV* v_av = (AV*)SvRV(vals_ref);
    uint32_t nin = (uint32_t)(av_len(in_av) + 1);
    uint32_t nparam = (uint32_t)(av_len(k_av) + 1);
    void** ins;
    const char** keys;
    const char** vals;
    void* outs[MAX_OUTS];
    uint32_t nout = 0, i;
    int rc;
    Newx(ins, nin ? nin : 1, void*);
    Newx(keys, nparam ? nparam : 1, const char*);
    Newx(vals, nparam ? nparam : 1, const char*);
    for (i = 0; i < nin; ++i)
        ins[i] = INT2PTR(void*, SvIV(*av_fetch(in_av, i, 0)));
    for (i = 0; i < nparam; ++i) {
        keys[i] = SvPV_nolen(*av_fetch(k_av, i, 0));
        vals[i] = SvPV_nolen(*av_fetch(v_av, i, 0));
    }
    rc = MXTImperativeInvoke(op, nin, ins, nparam, keys, vals, &nout,
                             outs, MAX_OUTS);
    Safefree(ins);
    Safefree(keys);
    Safefree(vals);
    if (rc != 0)
        croak_abi(aTHX_ op);
    EXTEND(SP, nout);
    for (i = 0; i < nout; ++i)
        mPUSHi(PTR2IV(outs[i]));
}

void
_mark_variables(in_ref)
    SV* in_ref
CODE:
{
    AV* av = (AV*)SvRV(in_ref);
    uint32_t n = (uint32_t)(av_len(av) + 1);
    void** hs;
    uint32_t i;
    int rc;
    Newx(hs, n ? n : 1, void*);
    for (i = 0; i < n; ++i)
        hs[i] = INT2PTR(void*, SvIV(*av_fetch(av, i, 0)));
    rc = MXTAutogradMarkVariables(n, hs);
    Safefree(hs);
    if (rc != 0)
        croak_abi(aTHX_ "AutogradMarkVariables");
}

void
_set_recording(rec)
    IV rec
CODE:
    if (MXTAutogradSetIsRecording((int)rec) != 0)
        croak_abi(aTHX_ "AutogradSetIsRecording");

void
_backward(h)
    IV h
CODE:
{
    void* out = INT2PTR(void*, h);
    if (MXTAutogradBackward(1, &out) != 0)
        croak_abi(aTHX_ "AutogradBackward");
}

IV
_get_grad(h)
    IV h
CODE:
{
    void* grad = NULL;
    if (MXTNDArrayGetGrad(INT2PTR(void*, h), &grad) != 0)
        croak_abi(aTHX_ "NDArrayGetGrad");
    RETVAL = PTR2IV(grad);
}
OUTPUT:
    RETVAL
