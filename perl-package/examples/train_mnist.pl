#!/usr/bin/env perl
# Train an MLP classifier from Perl — no Python in this file.
#
# The Perl twin of example/capi/train_mnist.c (same synthetic
# MNIST-shaped data, same 784-64-10 MLP, same loss-drops-5x pass
# criterion), built on the generated AI::MXTpu::Ops wrappers instead of
# hand-rolled MXTImperativeInvoke calls — the point being that the
# registry-generated surface carries a full training loop. Analog of
# the reference's perl-package/AI-MXNet/examples/mnist.pl.
#
# Run (tests/test_perl_frontend.py does this in CI):
#   cd perl-package && perl Makefile.PL && make
#   PYTHONPATH=$REPO JAX_PLATFORMS=cpu perl -Mblib examples/train_mnist.pl
use strict;
use warnings;

use AI::MXTpu;
use AI::MXTpu::Ops;

my ($N, $D, $H, $C, $EPOCHS, $LR) = (256, 784, 64, 10, 30, 0.5);

# synthetic separable blobs: class c means a one-hot-ish spread
srand(7);
my (@x, @y);
for my $i (0 .. $N - 1) {
    my $c = $i % $C;
    push @y, $c;
    for my $j (0 .. $D - 1) {
        push @x, (rand() - 0.5) * 0.5 + (($j % $C) == $c ? 1.0 : 0.0);
    }
}
my $xa = AI::MXTpu::NDArray->new([$N, $D], \@x);
my $ya = AI::MXTpu::NDArray->new([$N], \@y);

# parameters live as flat perl arrays between steps; FullyConnected
# weights are (num_hidden, input_dim) like the reference
my @w1 = map { (rand() - 0.5) * 0.05 } 1 .. $H * $D;
my @b1 = (0) x $H;
my @w2 = map { (rand() - 0.5) * 0.05 } 1 .. $C * $H;
my @b2 = (0) x $C;

my ($first, $last);
for my $ep (0 .. $EPOCHS - 1) {
    my $W1 = AI::MXTpu::NDArray->new([$H, $D], \@w1);
    my $B1 = AI::MXTpu::NDArray->new([$H], \@b1);
    my $W2 = AI::MXTpu::NDArray->new([$C, $H], \@w2);
    my $B2 = AI::MXTpu::NDArray->new([$C], \@b2);
    AI::MXTpu::mark_variables($W1, $B1, $W2, $B2);

    my $loss = AI::MXTpu::record(sub {
        my $h = AI::MXTpu::Ops::FullyConnected(
            $xa, $W1, $B1, num_hidden => $H);
        $h = AI::MXTpu::Ops::Activation($h, act_type => 'relu');
        my $logits = AI::MXTpu::Ops::FullyConnected(
            $h, $W2, $B2, num_hidden => $C);
        return AI::MXTpu::Ops::softmax_cross_entropy($logits, $ya);
    });
    AI::MXTpu::backward($loss);

    my $lval = $loss->asscalar / $N;
    $first = $lval if $ep == 0;
    $last = $lval;
    printf("epoch %d loss %.4f\n", $ep, $lval) if $ep % 10 == 0;

    # SGD on the host-side buffers (loss was summed over the batch)
    my $inv = $LR / $N;
    my @updates = ([\@w1, $W1], [\@b1, $B1], [\@w2, $W2], [\@b2, $B2]);
    for my $u (@updates) {
        my ($buf, $param) = @$u;
        my $g = $param->grad->aslist;
        $buf->[$_] -= $inv * $g->[$_] for 0 .. $#$buf;
    }
}

printf("first %.4f last %.4f\n", $first, $last);
die "FAIL: loss did not drop 5x\n" unless $last < $first / 5.0;
print "Perl-frontend MNIST training OK\n";
