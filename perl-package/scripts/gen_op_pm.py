#!/usr/bin/env python
"""Generate perl-package/lib/AI/MXTpu/Ops.pm from the op registry.

Analog of the reference's runtime op autogeneration in
perl-package/AI-MXNet/lib/AI/MXNet/NDArray.pm (_init_ns walking
MXListAllOpNames) and of cpp-package/scripts/gen_op_h.py here: one
named Perl sub per registered operator, funneling through
AI::MXTpu::op (imperative invoke over the C ABI). The generated file
is checked in, like the C++ op.h. Regenerate after adding ops:

    PYTHONPATH=. python perl-package/scripts/gen_op_pm.py
"""
import inspect
import keyword
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

# subs that would collide with Perl builtins/keywords get a trailing _
PERL_RESERVED = {
    "abs", "exp", "log", "sqrt", "sin", "cos", "sort", "reverse", "split",
    "join", "keys", "values", "each", "push", "pop", "shift", "unshift",
    "splice", "map", "grep", "print", "printf", "sprintf", "pack",
    "unpack", "length", "substr", "index", "rindex", "ord", "chr", "uc",
    "lc", "crypt", "eval", "exec", "sleep", "time", "localtime", "gmtime",
    "die", "warn", "ref", "bless", "tie", "untie", "local", "my", "our",
    "sub", "do", "if", "else", "elsif", "unless", "while", "until", "for",
    "foreach", "last", "next", "redo", "return", "and", "or", "not", "xor",
    "lt", "gt", "le", "ge", "eq", "ne", "cmp", "x", "q", "qq", "qw", "qr",
    "tr", "y", "s", "m", "no", "use", "package", "require", "wantarray",
    "defined", "delete", "exists", "scalar", "undef", "chomp", "chop",
    "lcfirst", "ucfirst", "int", "hex", "oct", "rand", "srand", "sum",
    "max", "min", "open", "close", "read", "write", "seek", "tell", "stat",
    "flip", "dot", "sign",
}

HEADER = '''\
package AI::MXTpu::Ops;

# GENERATED FILE - do not edit; run perl-package/scripts/gen_op_pm.py.
#
# One sub per operator in the live registry (%(count)d ops), each a
# thin funnel into AI::MXTpu::op("<name>", @inputs, %%params) - the
# imperative-invoke path of the C ABI. Names shadowing Perl builtins
# carry a trailing underscore (relu is relu, but abs is abs_).
#
# ref: perl-package/AI-MXNet/lib/AI/MXNet/NDArray.pm autogenerates the
# same surface at runtime from MXListAllOpNames.

use strict;
use warnings;

use AI::MXTpu;

'''

FOOTER = '''\
1;
'''


def perl_name(name):
    if not name.isidentifier() or keyword.iskeyword(name):
        return None
    if name.startswith("_"):
        return None
    return name + "_" if name.lower() in PERL_RESERVED else name


def main(out_path=None):
    from mxnet_tpu.ops import registry

    body = []
    emitted = set()
    for name in sorted(registry.list_ops()):
        pname = perl_name(name)
        if pname is None or pname in emitted:
            continue
        emitted.add(pname)
        opdef = registry.get_op(name)
        try:
            sig = str(inspect.signature(opdef.fn))
        except (TypeError, ValueError):
            sig = "(...)"
        body.append("# %s%s\n" % (name, sig))
        body.append("sub %s { AI::MXTpu::op('%s', @_) }\n\n"
                    % (pname, name))

    if out_path is None:
        out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "lib", "AI", "MXTpu", "Ops.pm")
    with open(out_path, "w") as f:
        f.write(HEADER % {"count": len(emitted)})
        f.writelines(body)
        f.write(FOOTER)
    print("wrote %s (%d ops)" % (os.path.normpath(out_path), len(emitted)))


if __name__ == "__main__":
    # optional explicit output path (CI generates to a temp file and
    # diffs against the checked-in copy without touching the tree)
    main(sys.argv[1] if len(sys.argv) > 1 else None)
