"""Headline benchmark: ResNet-50 training throughput (images/sec/chip),
plus the transformer-LM training MFU as a sub-benchmark.

Matches the reference's own headline (ref: docs perf.md — ResNet-50 training
batch 32: 298.51 img/s on V100 fp32; BASELINE.md). Runs the full Gluon
training step (forward + backward + SGD-momentum update + BN stat updates)
as ONE fused XLA program via ShardedTrainStep on whatever chip is attached.

Prints one JSON line:
  {"metric": "resnet50_train_imgs_per_sec_per_chip", "value": N,
   "unit": "images/sec", "vs_baseline": N / 298.51,
   "transformer": {"tokens_per_sec": N, "model_tflops_per_sec": N, ...}}

The transformer sub-benchmark is the modern capability headline the 2019
reference lacks: a 1.6B-param decoder LM (dim 4096, 5 layers, seq 2048,
batch 12, bf16, Pallas flash attention fwd+bwd, chunked CE, full
per-layer remat). Measured on one v5e chip: dim sweep 34/70/111 TF/s
model-flops at dim 1024/2048/4096 (r2 config) -> 123.3 with round-3
tuning (layer/batch sweep + chunked CE; selective remat via
BENCH_REMAT_SAVE=ffn_prod measures ~equal at batch 6).

The combined run also records an `inference` section (ResNet-50 eval
mode, the reference's benchmark_score headline — vs_baseline over the
published V100 fp16 b128 figure) and, on real devices, a `numerics`
section (TPU-vs-CPU-golden op sweep).

BENCH_MODEL=resnet50|transformer|resnet50_infer runs one section alone.
"""
import json
import os
import sys
import time

import numpy as np

# Baselines live in BASELINE.json (the machine-readable home; prose in
# BASELINE.md): ResNet = ref V100 fp32 training batch 32 (perf.md);
# transformer = PaLM 540B's published 46.2% MFU, the canonical large-LM
# training MFU figure (same published table: GPT-3 21.3%, Gopher 32.5%,
# MT-NLG 30.2%) — the 2019 reference has no transformer benchmark.
# Fallbacks keep bench.py runnable standalone.
def _published_baseline(*path, default):
    """One key from BASELINE.json's `published` block, falling back to
    the hardcoded default independently per key (a malformed entry must
    not discard the other valid ones)."""
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BASELINE.json")) as f:
            node = json.load(f).get("published", {})
        for p in path:
            node = node[p]
        return float(node)
    except (OSError, ValueError, TypeError, KeyError, AttributeError):
        return default


BASELINE_IMGS_PER_SEC = _published_baseline(
    "resnet50_train_imgs_per_sec_v100", default=298.51)
BASELINE_TRANSFORMER_MFU = _published_baseline(
    "transformer_mfu", "beat_target_mfu", default=0.462)


def _fused_mode():
    """Validated BENCH_FUSED value AND its model fuse= mapping — ONE
    parser so the train and inference sub-benches can't attribute
    results to different configs. Returns (raw_value, fuse_kwarg)."""
    fused = os.environ.get("BENCH_FUSED", "0")
    if fused not in ("0", "1", "pallas", "pallas_remat", "pallas_all"):
        raise ValueError("BENCH_FUSED must be one of 0|1|pallas|"
                         "pallas_remat|pallas_all, got %r" % fused)
    return fused, {"pallas": "auto", "pallas_remat": "auto",
                   "pallas_all": True}.get(fused, False)


def _transformer_mfu_run(B, S, dim, layers, loss_chunks, remat_save,
                         iters, big):
    """One measured transformer-LM training config; returns the metric
    dict (MFU only when the chip's bf16 peak is known)."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr
    import mxnet_tpu  # noqa: F401
    from mxnet_tpu.parallel import create_mesh
    from mxnet_tpu.parallel import transformer as T

    platform = jax.devices()[0].platform
    cfg = T.TransformerConfig(
        vocab_size=32000 if big else 256,
        dim=dim, n_layers=layers,
        n_heads=max(4, dim // 128), ffn_hidden=dim * 4,
        max_seq_len=S, dtype="bfloat16" if big else "float32",
        attn_mode="local",
        # chunked CE keeps the [B,S,32k] f32 logits off HBM (see
        # TransformerConfig.loss_chunks) — required for batch >= 8
        loss_chunks=loss_chunks,
        remat_save=remat_save)
    mesh = create_mesh(devices=jax.devices()[:1], dp=1)
    init_fn, step_fn = T.make_train_step(cfg, mesh)
    rs = np.random.RandomState(0)
    with mesh.mesh:
        state = init_fn(jr.PRNGKey(0))
        toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
        tgts = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
        state, loss = step_fn(state, toks, tgts)
        float(loss)  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            state, loss = step_fn(state, toks, tgts)
        loss = float(loss)
        dt = (time.perf_counter() - t0) / iters
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(state[0]))
    tflops = 6 * n_params * B * S / dt / 1e12
    # per-generation bf16 peak TF/s/chip; MFU only when the chip is
    # known. The v5e family resolves to the modeled ASSUMPTIONS table
    # (MX021: one home for hardware rates); the other generations are
    # public datasheet numbers with no comm model here.
    peaks = {"v4": 275.0, "v5p": 459.0, "v6e": 918.0}
    from mxnet_tpu.gluon.fused_step import _load_comm_model
    cm = _load_comm_model()
    if cm is not None:
        peaks["v5e"] = peaks["v5 lite"] = cm.peak_tflops("bf16")
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    peak = next((p for k, p in peaks.items() if k in kind), None)
    mfu = tflops / peak if (platform == "tpu" and peak) else None
    return {
        "metric": "transformer_train_tokens_per_sec_per_chip",
        "value": round(B * S / dt, 1),
        "unit": "tokens/sec",
        # vs the declared published bar (PaLM 46.2% MFU; BASELINE.md) —
        # MFU-based so it's only defined when the chip's peak is known
        "vs_baseline": (round(mfu / BASELINE_TRANSFORMER_MFU, 4)
                        if mfu is not None else None),
        "baseline_mfu": BASELINE_TRANSFORMER_MFU,
        "platform": platform,
        "params_m": round(n_params / 1e6, 1),
        "batch": B, "seq": S, "dim": dim, "layers": layers,
        "model_tflops_per_sec": round(tflops, 1),
        "mfu": round(mfu, 3) if mfu is not None else None,
        "final_loss": round(loss, 4),
    }


def bench_transformer():
    import jax
    platform = jax.devices()[0].platform
    big = platform != "cpu"
    # PEAK config — dim 4096 is the MFU sweet spot on one chip (111
    # TF/s model-flops at full remat vs 70 at dim 2048, 34 at 1024;
    # dim 5120 measured WORSE at 58.8%); params+momentum+grads are the
    # HBM floor. 5 layers (1.6B params) at batch 12 with FULL remat:
    # measured r3 best (123.3 TF/s, 62.6% MFU); bigger batches beat
    # selective remat once the saved buffers stop fitting
    # (BENCH_REMAT_SAVE=ffn_prod reproduces the selective config).
    out = _transformer_mfu_run(
        B=int(os.environ.get("BENCH_BATCH", 12 if big else 2)),
        S=int(os.environ.get("BENCH_SEQ", 2048 if big else 128)),
        dim=int(os.environ.get("BENCH_DIM", 4096 if big else 64)),
        layers=int(os.environ.get("BENCH_LAYERS", 5 if big else 2)),
        loss_chunks=int(os.environ.get("BENCH_LOSS_CHUNKS",
                                       8 if big else 1)),
        remat_save=tuple(n for n in os.environ.get(
            "BENCH_REMAT_SAVE", "").split(",") if n),
        iters=int(os.environ.get("BENCH_ITERS", 10 if big else 2)),
        big=big)
    # DEEP config (VERDICT r4 weak #4: a 5-layer MFU flatters vs
    # PaLM's 118-layer 46.2%): 24 layers x dim 2048 (1.74B params) at
    # the same seq 2048. Measured r5 on one v5e: b8 105.2 TF/s =
    # 53.4% MFU (run variance ±0.3 pp; the sweep — b12 53.0, b16 OOM,
    # dim-2304 49.4, attn_o-save@s1024 55.1, b16/s1024 55.8 — beats
    # 55% only by shortening seq, and the PaLM bar was measured at
    # 2048). The depth tax vs the 5-layer peak is activation
    # bandwidth: HBM bytes/FLOP scale with 1/dim.
    # Default-on only for the stock headline run: a BENCH_* sweep
    # point should not silently pay an extra 1.74B training run.
    swept = any(os.environ.get(k) for k in
                ("BENCH_BATCH", "BENCH_DIM", "BENCH_LAYERS",
                 "BENCH_SEQ", "BENCH_LOSS_CHUNKS", "BENCH_REMAT_SAVE"))
    if big and os.environ.get("BENCH_DEEP",
                              "0" if swept else "1") == "1":
        try:
            deep = _transformer_mfu_run(
                B=8, S=2048, dim=2048,
                layers=int(os.environ.get("BENCH_DEEP_LAYERS", 24)),
                loss_chunks=8, remat_save=(),
                iters=int(os.environ.get("BENCH_ITERS", 10)), big=big)
            out["deep"] = {k: deep[k] for k in
                           ("value", "params_m", "batch", "seq", "dim",
                            "layers", "model_tflops_per_sec", "mfu",
                            "vs_baseline", "final_loss")}
        except Exception as e:  # noqa: BLE001 - keep the peak figure
            out["deep"] = {"error": str(e)[:200]}
    return out


def bench_resnet():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    import mxnet_tpu.optimizer as opt
    from mxnet_tpu.parallel import create_mesh, data_parallel, \
        ShardedTrainStep

    platform = jax.devices()[0].platform
    batch = int(os.environ.get("BENCH_BATCH",
                               128 if platform != "cpu" else 8))
    dtype = os.environ.get("BENCH_DTYPE",
                           "bfloat16" if platform != "cpu" else "float32")
    # BENCH_LAYOUT=NHWC runs the channels-last variant (API stays NCHW;
    # one boundary transpose inside the model). Measured r3 on one v5e
    # chip: NCHW 2548/2577 img/s vs NHWC 2480/2564 (b128/b256) — parity
    # within noise, because the step is HBM-bandwidth-bound (XLA cost
    # analysis: 43.95 GB moved per b128 step at ~880 GB/s ≈ the chip's
    # peak), and XLA already picks its own internal conv layouts either
    # way. See docs/ROADMAP.md "ResNet perf ceiling".
    layout = os.environ.get("BENCH_LAYOUT", "NCHW")
    if layout not in ("NCHW", "NHWC"):
        raise ValueError("BENCH_LAYOUT must be NCHW or NHWC, got %r"
                         % layout)
    # BENCH_FUSED=1: NHWC + 1x1-convs-as-dots + save-only-conv-outs remat
    # so normalize/ReLU chains never persist in HBM (round-4 HBM work;
    # see ShardedTrainStep remat_policy + ops/nn.py _ckpt_name).
    # BENCH_FUSED=pallas: NHWC + the Pallas fused BN->ReLU->conv3x3
    # kernel (pallas_kernels/conv_fused.py) on the stages where it beats
    # XLA's native conv (fuse="auto"); pallas_all forces it everywhere;
    # pallas_remat combines auto with the conv-outs remat policy.
    fused, pallas_fuse = _fused_mode()
    if fused != "0":
        layout = "NHWC"

    net = resnet50_v1(layout=layout, fuse=pallas_fuse)
    net.initialize()
    net(mx.nd.array(np.zeros((1, 3, 224, 224), "float32")))  # deferred init
    if dtype != "float32":
        net.cast(dtype)

    mesh = create_mesh(devices=jax.devices()[:1], dp=1)
    step = ShardedTrainStep(net, SoftmaxCrossEntropyLoss(),
                            opt.create("sgd", learning_rate=0.01,
                                       momentum=0.9),
                            strategy=data_parallel(mesh),
                            remat_policy="conv_outs"
                            if fused in ("1", "pallas_remat") else None)

    rng = np.random.RandomState(0)
    x = rng.rand(batch, 3, 224, 224).astype(dtype)
    y = rng.randint(0, 1000, (batch,)).astype("float32")
    xd, yd = step.place_batch(x, y)  # compute-only: batch on device once

    float(step.step(xd, yd))  # compile + warm
    float(step.step(xd, yd))

    iters = int(os.environ.get("BENCH_ITERS", 30 if platform != "cpu" else 3))
    import contextlib
    xprof_dir = os.environ.get("BENCH_XPROF")
    trace_cm = jax.profiler.trace(xprof_dir) if xprof_dir \
        else contextlib.nullcontext()
    t0 = time.perf_counter()
    loss = None
    with trace_cm:
        for _ in range(iters):
            loss = step.step(xd, yd)
        loss = float(loss)  # sync once at the end
    dt = time.perf_counter() - t0

    imgs_per_sec = batch * iters / dt
    result = {
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 4),
        "platform": platform,
        "batch": batch,
        "dtype": dtype,
        "layout": layout,
        "fused": fused,
        "final_loss": round(float(loss), 4),
    }
    if os.environ.get("BENCH_INPUT_PIPELINE", "1") == "1":
        try:
            result["input_pipeline"] = bench_input_pipeline(
                step=step, batch=batch, dtype=dtype,
                compute_imgs_per_sec=imgs_per_sec)
        except Exception as e:  # noqa: BLE001 — a missing cv2 etc. must
            # not discard the compute result measured above
            result["input_pipeline"] = {"error": "%s: %s"
                                        % (type(e).__name__, e)}
    return result


def _synth_rec(n=2048, side=256, path="/tmp/mxtpu_bench_synth.rec",
               raw=False):
    """Synthetic .rec + .idx (written once, reused across runs). raw=True
    stores pre-decoded pixels (recordio.pack_raw_img) — the decode-free
    fast path; JPEG otherwise."""
    import cv2
    from mxnet_tpu.recordio import (MXIndexedRecordIO, pack, pack_raw_img,
                                    IRHeader)
    if raw:
        path = path.replace(".rec", "_raw.rec")
    idx = path.replace(".rec", ".idx")
    if os.path.exists(path) and os.path.exists(idx):
        return path, idx
    # write to temp names + atomic rename so an interrupted run can
    # never leave a truncated file that later runs silently reuse
    tmp_rec, tmp_idx = path + ".tmp", idx + ".tmp"
    w = MXIndexedRecordIO(tmp_idx, tmp_rec, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = rng.randint(0, 255, (side, side, 3), np.uint8)
        header = IRHeader(0, float(i % 1000), i, 0)
        if raw:
            w.write_idx(i, pack_raw_img(header, img))
        else:
            ok, enc = cv2.imencode(".jpg", img,
                                   [cv2.IMWRITE_JPEG_QUALITY, 90])
            assert ok
            w.write_idx(i, pack(header, enc.tobytes()))
    w.close()
    os.rename(tmp_rec, path)
    os.rename(tmp_idx, idx)
    return path, idx


def bench_input_pipeline(step=None, batch=128, dtype="bfloat16",
                         compute_imgs_per_sec=None):
    """End-to-end input pipeline: synthetic .rec -> ImageRecordIter
    (uint8 feed, on-device normalize) -> sustained img/s, and the same
    pipeline actually feeding the training step (VERDICT r2 item 5).

    The pipeline is host-CPU-bound: single-core cv2 JPEG decode of
    256px records measures ~1300 img/s, so a host needs
    ceil(compute_rate / per-core rate) cores to keep a chip fed — the
    reference's published numbers assume a 36-core C5 host
    (ref: perf.md), while CI/axon hosts may have 1."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx

    rec, idx = _synth_rec()
    raw_rec, raw_idx = _synth_rec(raw=True)

    n_threads = min(8, os.cpu_count() or 1)

    def make_iter(path_rec=rec, path_idx=idx):
        return mx.io.ImageRecordIter(
            path_imgrec=path_rec, path_imgidx=path_idx,
            data_shape=(3, 224, 224),
            batch_size=batch, shuffle=True, rand_crop=True,
            rand_mirror=True, dtype="uint8",
            preprocess_threads=n_threads)

    # 1) pipeline-only sustained rate (decode + augment + batch), for
    #    BOTH record formats: JPEG (decode-bound on small hosts) and
    #    the pre-decoded raw-pixel fast path (recordio.pack_raw_img —
    #    frombuffer+crop only, VERDICT r4 item 8)
    def sustained(path_rec, path_idx):
        it = make_iter(path_rec, path_idx)
        n = 0
        t0 = time.perf_counter()
        for _ in range(2):
            it.reset()
            for b in it:
                n += b.data[0].shape[0]
        return n / (time.perf_counter() - t0)

    pipeline_rate = sustained(rec, idx)
    raw_rate = sustained(raw_rec, raw_idx)

    # host->device bandwidth for one uint8 batch (on a real TPU host
    # this is PCIe/DMA at GB/s; over a remote-tunnel dev attach it can
    # be the train-through bottleneck, so report it for context)
    probe = np.zeros((batch, 3, 224, 224), np.uint8)
    jax.block_until_ready(jnp.asarray(probe))  # warm
    # best of 3: this figure becomes the feed_overlap_efficiency bound,
    # so one tunnel hiccup must not define it
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(jnp.asarray(probe))
        times.append(time.perf_counter() - t0)
    h2d_mbps = probe.nbytes / min(times) / 1e6

    out = {
        "sustained_imgs_per_sec": round(pipeline_rate, 1),
        "sustained_raw_imgs_per_sec": round(raw_rate, 1),
        "host_cpus": os.cpu_count(),
        "record_px": 256,
        "host_to_device_MBps": round(h2d_mbps, 1),
        # hard ceiling the transfer link imposes on ANY feed: a uint8
        # 3x224x224 image is 150,528 B, so train-through can never beat
        # h2d_bw / img_bytes. On a real TPU host (PCIe/DMA, GB/s) this
        # is tens of thousands img/s and irrelevant; over the remote
        # dev tunnel it can be the binding constraint — judge
        # train_through against it, not against the pipeline rate.
        "h2d_bound_imgs_per_sec": round(
            h2d_mbps * 1e6 / (3 * 224 * 224), 1),
    }
    if compute_imgs_per_sec:
        # per-core rate uses the thread count the pipeline actually ran
        # with, not the host's core count
        out["cores_to_feed_compute"] = int(
            np.ceil(compute_imgs_per_sec / (pipeline_rate / n_threads)))
        out["cores_to_feed_compute_raw"] = int(
            np.ceil(compute_imgs_per_sec / (raw_rate / n_threads)))

    # 2) the same pipeline feeding the real train step: uint8 batches are
    #    DOUBLE-BUFFERED to the device (DevicePrefetchIter issues the
    #    device_put of batch N+1 while N computes — SURVEY §7.5), then
    #    normalized on-chip (the TPU-idiomatic feed)
    if step is not None:
        from mxnet_tpu.io import DevicePrefetchIter

        mean = jnp.asarray([123.68, 116.78, 103.94], dtype
                           ).reshape(1, 3, 1, 1)
        scale = jnp.asarray(1.0 / 58.0, dtype)

        @jax.jit
        def normalize(u8):
            return (u8.astype(dtype) - mean) * scale

        def to_host(b):
            return (b.data[0].asnumpy(), b.label[0].asnumpy())

        class _HostBatches:
            def __init__(self, inner):
                self.inner = inner

            def __iter__(self):
                return (to_host(b) for b in self.inner)

            def reset(self):
                self.inner.reset()

        # the train-through feed uses the raw-pixel fast path — on a
        # decode-starved host that is the difference between feeding
        # ~1/3 of compute and feeding it fully
        it = make_iter(raw_rec, raw_idx)
        it.reset()
        # place straight onto the step's batch sharding so step() never
        # re-device_puts inside the timed loop
        pf = DevicePrefetchIter(_HostBatches(it), depth=2,
                                sharding=step._batch_sharding)
        xu8, yh = next(pf)
        xd, yd = step.place_batch(normalize(xu8), yh)
        float(step.step(xd, yd))  # warm the (possibly new) shapes
        n = 0
        t0 = time.perf_counter()
        loss = None
        pf.reset()
        for xu8, yh in pf:
            loss = step.step(normalize(xu8), yh)
            n += int(xu8.shape[0])
        float(loss)
        dt_through = time.perf_counter() - t0
        out["train_through_imgs_per_sec"] = round(n / dt_through, 1)
        out["train_through_feed"] = "raw"
        if compute_imgs_per_sec:
            # overlap quality: 1.0 = perfectly hidden feed
            # (train-through == min(raw pipeline, compute, transfer
            # link) — the raw rate because that is the feed used)
            bound = min(raw_rate, compute_imgs_per_sec,
                        out["h2d_bound_imgs_per_sec"])
            out["feed_overlap_efficiency"] = round(
                (n / dt_through) / bound, 3)
    return out


def _synth_raw_rec_io(n=384, side=64, path=None):
    """Synthetic raw-pixel .rec + .idx + .crc for the data-plane gate —
    cv2-free (pack_raw_img stores pre-decoded pixels), written once
    via temp+rename so an interrupted run never leaves truncated files
    a later run silently reuses. The cache is keyed on (n, side) in
    the filename, so a BENCH_IO_RECORDS override can never silently
    reuse a dataset of the wrong size."""
    from mxnet_tpu.io import build_crc_sidecar
    from mxnet_tpu.recordio import (MXIndexedRecordIO, pack_raw_img,
                                    IRHeader)
    if path is None:
        path = "/tmp/mxtpu_bench_io_plane_%dx%d.rec" % (n, side)
    idx = path.replace(".rec", ".idx")
    if not (os.path.exists(path) and os.path.exists(idx)
            and os.path.exists(path + ".crc")):
        tmp_rec, tmp_idx = path + ".tmp", idx + ".tmp"
        w = MXIndexedRecordIO(tmp_idx, tmp_rec, "w")
        rng = np.random.RandomState(0)
        for i in range(n):
            img = rng.randint(0, 255, (side, side, 3), np.uint8)
            w.write_idx(i, pack_raw_img(IRHeader(0, float(i % 10), i, 0),
                                        img))
        w.close()
        os.rename(tmp_rec, path)
        os.rename(tmp_idx, idx)
        build_crc_sidecar(path)
    return path, idx


def bench_input_pipeline_gate():
    """BENCH_MODEL=input_pipeline: the ISSUE 11 data-plane gate.

    The sharded streaming service (ShardService -> RecordIORangeReader
    -> DecodePool -> DevicePrefetchIter) must sustain **>= 2x the
    fused-step consumption rate** so the accelerator can never starve
    even if decode momentarily halves, with the
    ``io.prefetch_queue_depth`` gauge nonzero while stepping at full
    rate (depth 0 at the consumer = the pipeline IS the ceiling). The
    chaos variant re-runs the same plane under 15% injected decode
    faults (worker deaths + restarts) and 15% injected read faults
    (retried range fetches) and must still beat **1x** — degraded, not
    starving. Exits non-zero on breach (driven from __main__)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import profiler
    from mxnet_tpu._debug import faultpoint
    from mxnet_tpu._retry import RetryPolicy
    from mxnet_tpu.io import (ShardService, RecordIORangeReader,
                              DevicePrefetchIter)
    from mxnet_tpu.io import _stats as io_stats
    from mxnet_tpu.recordio import unpack_img

    side = 64
    n_rec = int(os.environ.get("BENCH_IO_RECORDS", "384"))
    batch = int(os.environ.get("BENCH_IO_BATCH", "32"))
    workers = int(os.environ.get("BENCH_IO_WORKERS", "2"))
    rec, idx = _synth_raw_rec_io(n=n_rec, side=side)

    crop = side - 8

    def decode(payload):
        _, img = unpack_img(payload)  # raw fast path: no JPEG decode
        return np.ascontiguousarray(
            img[4:4 + crop, 4:4 + crop].transpose(2, 0, 1))

    # the consumer this plane must outrun: a jitted multi-layer conv
    # step — an honest stand-in for a fused TRAIN step's per-batch
    # device time (a single tiny conv measures noise, not a workload,
    # and a noisy denominator makes the 2x/1x ratios flap run-to-run)
    key = jax.random.PRNGKey(0)
    ws = [jax.random.normal(key, (32, 3, 3, 3), jnp.float32) * 0.1] + \
        [jax.random.normal(key, (32, 32, 3, 3), jnp.float32) * 0.1
         for _ in range(3)]

    @jax.jit
    def step_fn(x):
        y = x.astype(jnp.float32) / 255.0
        for w in ws:
            y = jax.nn.relu(jax.lax.conv_general_dilated(
                y, w, (1, 1), "SAME",
                dimension_numbers=("NCHW", "OIHW", "NCHW")))
        return jnp.tanh(y).mean()

    probe = np.zeros((batch, 3, crop, crop), np.uint8)
    float(step_fn(probe))  # compile
    reps, times = 7, []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(step_fn(probe))
        times.append(time.perf_counter() - t0)
    step_s = sorted(times)[reps // 2]  # median: robust to one stall
    consume_rate = 1.0 / step_s  # batches/sec the device eats

    def make_plane():
        reader = RecordIORangeReader(
            rec, index=idx,
            # chaos injects transient read faults at 15%: keep the
            # backoff small so the gate prices the retry MACHINERY,
            # not a production-tuned sleep schedule
            retry_policy=RetryPolicy(base=0.0005, cap=0.01,
                                     deadline=30))
        svc = ShardService(n_rec, shard_size=batch, seed=0, world=(0,),
                           rank=0, reader=reader, decode_fn=decode)
        return svc

    def pipeline_rate(chaos):
        svc = make_plane()
        if chaos:
            faultpoint.configure(
                {"io.worker.decode": "raise:ValueError@p=0.15",
                 "io.shard.read": "raise:OSError@p=0.15"}, seed=11)
        else:
            faultpoint.reset()
        try:
            nb = 0
            t0 = time.perf_counter()
            for _, samples in svc.iter_batches(batch, workers=workers):
                np.stack(samples)
                nb += 1
            dt = time.perf_counter() - t0
            faults = dict(profiler.metrics().get("faults", {}))
        finally:
            faultpoint.reset()  # also zeroes the trigger counters
        return nb / dt, nb, faults

    plain_rate, plain_batches, _ = pipeline_rate(chaos=False)
    chaos_rate, chaos_batches, chaos_faults = pipeline_rate(chaos=True)

    # full-step-rate run: the plane feeds the jitted step through the
    # device double buffer; the queue-depth gauge must be nonzero while
    # the consumer is busy (i.e. the producer stays ahead)
    svc = make_plane()

    def host_batches():
        for _, samples in svc.iter_batches(batch, workers=workers):
            yield np.stack(samples)

    depth_samples = []
    pf = DevicePrefetchIter(host_batches(), depth=2)
    first = next(pf)
    float(step_fn(first))
    for x in pf:
        float(step_fn(x))
        depth_samples.append(
            io_stats.get("prefetch_queue_depth", 0))
    nonzero_frac = (sum(1 for d in depth_samples if d > 0)
                    / max(1, len(depth_samples)))

    gate = {
        "min_speedup": 2.0,
        "min_chaos_speedup": 1.0,
        "min_depth_nonzero_frac": 0.5,
        "plain_ok": plain_rate >= 2.0 * consume_rate,
        "chaos_ok": chaos_rate >= 1.0 * consume_rate,
        # chaos must actually have injected (a zero-fault chaos run
        # pricing at full speed would be a lie)
        "chaos_injected": (chaos_faults.get("io.worker.decode", 0) > 0
                           and chaos_faults.get("io.shard.read", 0)
                           > 0),
        "depth_ok": nonzero_frac >= 0.5,
    }
    gate["ok"] = (gate["plain_ok"] and gate["chaos_ok"]
                  and gate["chaos_injected"] and gate["depth_ok"])
    io_m = {k: v for k, v in profiler.metrics().get("io", {}).items()
            if not k.startswith("service_")}
    return {
        "metric": "input_pipeline_plane",
        "records": n_rec, "batch": batch, "workers": workers,
        "consume_batches_per_sec": round(consume_rate, 2),
        "plain_batches_per_sec": round(plain_rate, 2),
        "plain_speedup": round(plain_rate / consume_rate, 2),
        "chaos_batches_per_sec": round(chaos_rate, 2),
        "chaos_speedup": round(chaos_rate / consume_rate, 2),
        "chaos_faults": chaos_faults,
        "queue_depth_nonzero_frac": round(nonzero_frac, 3),
        "batches_streamed": {"plain": plain_batches,
                             "chaos": chaos_batches},
        "io_metrics": io_m,
        "gate": gate,
    }


def bench_resnet_inference(net=None, batch=None, dtype=None):
    """ResNet-50 inference throughput — the reference's benchmark_score
    headline (perf.md V100 fp16 batch 128: 2355.04 img/s, BASELINE.md
    inference tables). Whole-graph jit of the eval-mode forward, batch
    resident on device (compute-only, like the training number)."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    platform = jax.devices()[0].platform
    big = platform != "cpu"
    batch = batch or int(os.environ.get("BENCH_BATCH",
                                        256 if big else 8))
    dtype = dtype or os.environ.get("BENCH_DTYPE",
                                    "bfloat16" if big else "float32")
    # same BENCH_FUSED parsing+mapping as the training bench — inference
    # is forward-only, the regime where the kernel wins per-stage (it
    # still loses whole-model; docs/ROADMAP.md fused-conv study)
    fused, pallas_fuse = _fused_mode()
    layout = "NHWC" if big else "NCHW"
    if net is None:
        net = resnet50_v1(layout=layout,
                          fuse=pallas_fuse if big else False)
        net.initialize()
        net(mx.nd.array(np.zeros((1, 3, 224, 224), "float32")))
        if dtype != "float32":
            net.cast(dtype)

    # eager-built params are committed to the HOST (default ctx cpu) and
    # jit follows operand placement — without an explicit device_put the
    # whole graph compiles for and runs on the host CPU (measured: 26 s
    # per b32 forward). Place params and batch on the accelerator.
    dev = jax.devices()[0]
    params = [jax.device_put(p.data()._data, dev)
              for p in net._all_params_list()]
    from mxnet_tpu.ndarray import NDArray as _ND

    def fwd(param_datas, x):
        originals = [p.data()._data for p in net._all_params_list()]
        for p, d in zip(net._all_params_list(), param_datas):
            p.data()._data = d
        prev = autograd.set_training(False)
        try:
            out = net(_ND(x))
        finally:
            autograd.set_training(prev)
            for p, d in zip(net._all_params_list(), originals):
                p.data()._data = d
        return out._data

    iters = int(os.environ.get("BENCH_ITERS", 30 if big else 3))

    # the whole timing loop runs INSIDE one jit: per-call host dispatch
    # (hundreds of param buffers; seconds over a remote-tunnel attach)
    # must not pollute a throughput number. The carry perturbs the input
    # each iteration so XLA cannot hoist the loop-invariant forward.
    @jax.jit
    def run(param_datas, x):
        def body(i, acc):
            xi = x + jnp.full((), acc * 1e-24, x.dtype)
            out = fwd(param_datas, xi)
            return acc + jnp.sum(out.astype(jnp.float32)) * 1e-20
        return jax.lax.fori_loop(0, iters, body, jnp.float32(0))

    rng = np.random.RandomState(0)
    x = jax.device_put(
        jnp.asarray(rng.rand(batch, 3, 224, 224).astype(dtype)), dev)
    float(run(params, x))  # compile + warm
    t0 = time.perf_counter()
    float(run(params, x))  # scalar materialization = real device sync
    dt = time.perf_counter() - t0
    imgs_per_sec = batch * iters / dt
    baseline = _published_baseline(
        "resnet50_infer_imgs_per_sec_v100_fp16_b128", default=2355.04)
    return {
        "metric": "resnet50_infer_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(imgs_per_sec / baseline, 4),
        "platform": platform, "batch": batch, "dtype": dtype,
        "layout": layout, "fused": fused,
    }


def bench_eager_ops():
    """BENCH_MODEL=eager_ops: imperative dispatch overhead — a chain of
    small NDArray ops in ops/sec, fast path (MXNET_IMPERATIVE_JIT jitted
    dispatch cache) vs untraced eager, plus the engine.bulk() segment mode
    (whole chain fused into one XLA program per flush). Tracks the per-op
    Python+dispatch cost the reference's engine/CachedOp machinery exists
    to hide (SURVEY §3; include/mxnet/engine.h:117)."""
    import mxnet_tpu as mx
    from mxnet_tpu import engine
    from mxnet_tpu.ndarray import register as R

    n = int(os.environ.get("BENCH_EAGER_SIZE", 64))
    iters = int(os.environ.get("BENCH_EAGER_ITERS", 200))
    chain = int(os.environ.get("BENCH_EAGER_CHAIN", 16))
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.rand(n, n).astype("float32"))
    y = mx.nd.array((rs.rand(n, n) + 0.5).astype("float32"))

    reps = max(1, chain // 4)
    ops_per_iter = reps * 4

    def run_chain():
        # representative imperative mix: scalar arithmetic (the reference's
        # _plus_scalar/_mul_scalar traffic), an activation, a tensor op
        c = x
        for _ in range(reps):
            c = c * 0.5
            c = c + 1.0
            c = mx.nd.softmax(c)
            c = c + y
        return c

    def one_round(mode, n):
        prev = R.set_imperative_jit(mode != "off")
        try:
            t0 = time.perf_counter()
            for _ in range(n):
                if mode == "bulk":
                    with engine.bulk(ops_per_iter):
                        c = run_chain()
                else:
                    c = run_chain()
            c.wait_to_read()
            dt = time.perf_counter() - t0
        finally:
            R.set_imperative_jit(prev)
        return n * ops_per_iter / dt, c.asnumpy()

    # warm every mode first (dispatch cache compiles on repeat), then
    # measure in ALTERNATING rounds and keep the per-mode median — the
    # modes see the same machine-load drift instead of each other's noise
    outs = {}
    for mode in ("jit", "bulk", "off"):
        _r, outs[mode] = one_round(mode, 4)
    R.reset_dispatch_stats()
    _r, outs["jit"] = one_round("jit", 2)  # stats over a clean jit round
    stats = R.dispatch_stats()
    rates = {"jit": [], "bulk": [], "off": []}
    for _round in range(3):
        for mode in rates:
            rates[mode].append(one_round(mode, max(1, iters // 3))[0])
    med = {m: sorted(v)[len(v) // 2] for m, v in rates.items()}
    fast, bulk, slow = med["jit"], med["bulk"], med["off"]
    out_fast, out_bulk, out_slow = outs["jit"], outs["bulk"], outs["off"]
    return {
        "metric": "eager_ops_per_sec",
        "value": round(fast, 1),
        "unit": "ops/sec",
        "jit_ops_per_sec": round(fast, 1),
        "eager_ops_per_sec": round(slow, 1),
        "bulk_ops_per_sec": round(bulk, 1),
        "speedup_jit": round(fast / slow, 2),
        "speedup_bulk": round(bulk / slow, 2),
        "bitwise_parity": bool(np.array_equal(out_fast, out_slow)
                               and np.array_equal(out_bulk, out_slow)),
        "chain_len": ops_per_iter,
        "tensor_side": n,
        "dispatch": stats,
    }


def bench_train_step():
    """BENCH_MODEL=train_step: full Gluon training-step throughput — the
    fused donated program (gluon.train_step: forward + backward +
    optimizer for all params as ONE jitted call, ISSUE 4) vs the eager
    record/backward/Trainer.step loop on the same hybridized MLP.

    Median-of-3 ALTERNATING rounds of steps/sec per mode (both modes see
    the same machine-load drift), parity-checked bitwise after 3 steps,
    and replay-checked: after compiling once, an lr change and a new
    batch_size divisor must replay the same executable
    (fused_step.retraces == 0 — lr/wd/rescale are operands, not baked
    constants). Gate: fused >= 1.5x eager steps/sec, like the
    profiler_overhead gate this exits non-zero on breach."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import fused_step as FS

    hidden = int(os.environ.get("BENCH_STEP_HIDDEN", 64))
    batch = int(os.environ.get("BENCH_STEP_BATCH", 32))
    iters = int(os.environ.get("BENCH_STEP_ITERS", 60))
    loss_fn = gluon.loss.L2Loss()
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.rand(batch, hidden).astype("float32"))
    y = mx.nd.array(rs.rand(batch, 1).astype("float32"))

    def make_net(seed_from=None):
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Dense(hidden, in_units=hidden,
                                   activation="relu"))
            net.add(gluon.nn.Dense(hidden, in_units=hidden,
                                   activation="relu"))
            net.add(gluon.nn.Dense(1, in_units=hidden))
        net.initialize(mx.init.Uniform(0.1))
        net.hybridize()
        if seed_from is not None:
            for (_, p1), (_, p2) in zip(
                    sorted(seed_from.collect_params().items()),
                    sorted(net.collect_params().items())):
                p2.set_data(p1.data())
        return net

    def make_trainer(net):
        return gluon.Trainer(net.collect_params(), "sgd",
                             {"learning_rate": 0.05, "momentum": 0.9})

    def eager_step(net, trainer, bs=batch):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(bs)
        return loss

    # -- parity: 3 steps on identical nets, bitwise ----------------------
    net_a = make_net()
    net_b = make_net(net_a)
    tr_a, tr_b = make_trainer(net_a), make_trainer(net_b)
    step_b = gluon.train_step(net_b, loss_fn, tr_b)
    for _ in range(3):
        eager_step(net_a, tr_a)
        step_b(x, y, batch_size=batch)
    parity = all(
        np.array_equal(pa.data().asnumpy(), pb.data().asnumpy())
        for (_, pa), (_, pb) in zip(
            sorted(net_a.collect_params().items()),
            sorted(net_b.collect_params().items())))

    # -- replay: lr + batch_size changes must not retrace ----------------
    FS.reset_stats()
    tr_b.set_learning_rate(0.01)
    step_b(x, y, batch_size=batch)
    step_b(x, y, batch_size=2 * batch)
    replay_stats = FS.stats()
    replays_clean = replay_stats["retraces"] == 0 \
        and replay_stats["hits"] == 2

    # -- throughput: alternating rounds, median-of-3 per mode ------------
    net_e = make_net(net_a)
    net_f = make_net(net_a)
    tr_e, tr_f = make_trainer(net_e), make_trainer(net_f)
    step_f = gluon.train_step(net_f, loss_fn, tr_f)
    for _ in range(3):  # warm both paths (fused compiles on repeat)
        eager_step(net_e, tr_e)
        step_f(x, y, batch_size=batch)

    def eager_round(n):
        t0 = time.perf_counter()
        loss = None
        for _ in range(n):
            loss = eager_step(net_e, tr_e)
        loss.wait_to_read()
        return n / (time.perf_counter() - t0)

    def fused_round(n):
        t0 = time.perf_counter()
        loss = None
        for _ in range(n):
            loss = step_f(x, y, batch_size=batch)
        loss.wait_to_read()
        return n / (time.perf_counter() - t0)

    rates = {"eager": [], "fused": []}
    n = max(1, iters // 3)
    for _ in range(3):
        rates["eager"].append(eager_round(n))
        rates["fused"].append(fused_round(n))
    med = {m: sorted(v)[len(v) // 2] for m, v in rates.items()}
    speedup = med["fused"] / med["eager"]
    assert step_f.last_mode == "fused", step_f.last_mode

    return {
        "metric": "train_step_steps_per_sec",
        "value": round(med["fused"], 1),
        "unit": "steps/sec",
        "fused_steps_per_sec": round(med["fused"], 1),
        "eager_steps_per_sec": round(med["eager"], 1),
        "speedup": round(speedup, 2),
        "bitwise_parity": bool(parity),
        "replay": {"retraces": replay_stats["retraces"],
                   "hits": replay_stats["hits"],
                   "clean": bool(replays_clean)},
        "hidden": hidden,
        "batch": batch,
        "params": len(tr_f._params),
        "dispatch": FS.stats(),
        "gate": {"ok": bool(speedup >= 1.5 and parity and replays_clean),
                 "min_speedup": 1.5},
    }


def bench_profiler_overhead():
    """BENCH_MODEL=profiler_overhead: cost of the telemetry layer at the
    imperative dispatch choke point (ISSUE 2 hard constraint: zero-cost
    when profiling is off).

    The gate is computed from two noise-robust measurements rather than an
    end-to-end A/B (on a loaded box run-to-run wall-clock noise is 10-30%,
    while the signal — one guard conditional — is ~100ns against a ~50us
    dispatch, so a throughput diff would gate on noise):

    1. ``guard_ns``: the EXACT extra work the profiling-off hot path
       executes per op (`_HOOKS and _profiler._ACTIVE` + the two
       `is not None` return-site tests in register.invoke), timed in a
       tight loop with the empty-loop baseline subtracted.
    2. ``dispatch_us``: per-op eager dispatch latency, best-of-N rounds
       (min time ≙ the unloaded quantum both numbers share).

    Gate: guard_ns / dispatch_us < 2%. The eager_ops A/B rates (off vs
    full tracing ON) are reported for context — `on` is allowed to cost;
    it must be bought only by set_state('run')."""
    import tempfile
    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    from mxnet_tpu.ndarray import register as R

    n = int(os.environ.get("BENCH_EAGER_SIZE", 64))
    iters = int(os.environ.get("BENCH_EAGER_ITERS", 200))
    chain = int(os.environ.get("BENCH_EAGER_CHAIN", 16))
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.rand(n, n).astype("float32"))
    y = mx.nd.array((rs.rand(n, n) + 0.5).astype("float32"))
    reps = max(1, chain // 4)
    ops_per_iter = reps * 4

    def run_chain():
        c = x
        for _ in range(reps):
            c = c * 0.5
            c = c + 1.0
            c = mx.nd.softmax(c)
            c = c + y
        return c

    profiler.set_config(
        filename=os.path.join(tempfile.mkdtemp(), "profile.json"),
        xprof=False)

    # -- 1. the guard expression, in isolation (profiling off) -----------
    # ISSUE 8 made the guard _LIVE (profiler OR flight recorder); this
    # bench prices the profiler layer with EVERYTHING off, so the
    # always-on recorder is disabled for the whole run (its own price
    # is BENCH_MODEL=flightrec_overhead's job)
    from mxnet_tpu._debug import flightrec
    flightrec_was_on = flightrec.ENABLED
    flightrec.disable()
    _FREC = R._FREC

    def guard_loop(k):
        t0 = time.perf_counter()
        for _ in range(k):
            p = (time.perf_counter() if profiler._ACTIVE else _FREC) \
                if (R._HOOKS and profiler._LIVE) else None
            if p is not None:
                pass
            if p is not None:
                pass
        return time.perf_counter() - t0

    def empty_loop(k):
        t0 = time.perf_counter()
        for _ in range(k):
            p = None
            if p:
                pass
            if p:
                pass
        return time.perf_counter() - t0

    k = 200000
    guard_loop(k // 10), empty_loop(k // 10)  # warm
    guard_ns = max(0.0, (min(guard_loop(k) for _ in range(5))
                         - min(empty_loop(k) for _ in range(5)))
                   / k * 1e9)

    # -- 2. per-op dispatch latency, best-of (min-time) -------------------
    def one_round(mode, rounds):
        if mode == "on":
            profiler.set_state("run")
        try:
            t0 = time.perf_counter()
            for _ in range(rounds):
                c = run_chain()
            c.wait_to_read()
            dt = time.perf_counter() - t0
        finally:
            if mode == "on":
                profiler.set_state("stop")
                profiler.dumps(reset=True)  # don't grow _events unbounded
        return dt / (rounds * ops_per_iter)

    for mode in ("off", "on"):
        one_round(mode, 4)  # warm: dispatch cache compiles on repeat
    per_op = {"off": [], "on": []}
    for _ in range(5):
        for mode in per_op:
            per_op[mode].append(one_round(mode, max(1, iters // 5)))
    best = {m: min(v) for m, v in per_op.items()}
    dispatch_us = best["off"] * 1e6
    overhead_off = guard_ns / 1e3 / dispatch_us * 100.0
    overhead_on = (best["on"] / best["off"] - 1.0) * 100.0

    # -- 3. record_latency on the hot path (ISSUE 6 gate extension) -------
    # Off-path cost is the same inlined guard measured above; here we
    # also price the ACTIVE-path histogram update (frexp + dict bump
    # under the event lock) so regressions in the primitive itself show.
    profiler.set_state("run")
    def lat_loop(k):
        t0 = time.perf_counter()
        for _ in range(k):
            profiler.record_latency("bench.lat", 37.25)
        return time.perf_counter() - t0
    lat_loop(k // 10)  # warm
    record_latency_ns = min(lat_loop(k) for _ in range(5)) / k * 1e9
    profiler.set_state("stop")
    profiler.metrics(reset=True)

    # -- 4. wire trace-context: added RTT + off-path byte identity --------
    # Noise-robust like the guard: measure the EXACT extra work a
    # stamped request pays (client stamp build + server strip) in a
    # tight loop, divide by a measured loopback pull RTT. The 20 extra
    # bytes themselves are <0.01% of any real payload. Gate: <0.5% of
    # RTT, and with profiling OFF the frames on the wire must be
    # byte-identical to the v0 protocol (flag bit never set).
    import struct as _struct
    from mxnet_tpu import kvstore_async as KA
    srv = KA.AsyncPSServer()
    cli = KA.AsyncPSClient("127.0.0.1", srv.port)
    cli.init("w", np.zeros((64, 64), np.float32))
    sent_ops = []
    real_send = KA._send_frame
    def spy_send(sock, payload):
        sent_ops.append(payload[0])
        real_send(sock, payload)
    KA._send_frame = spy_send
    try:
        for _ in range(3):
            cli.pull("w")  # profiling is OFF here
    finally:
        KA._send_frame = real_send
    off_stamped = sum(1 for op in sent_ops if op & KA._TRACE_FLAG)

    def rtt_round(rounds):
        t0 = time.perf_counter()
        for _ in range(rounds):
            cli.pull("w")
        return (time.perf_counter() - t0) / rounds
    rtt_round(20)  # warm
    pull_rtt_us = min(rtt_round(50) for _ in range(5)) * 1e6

    pull_payload = bytes([KA._OP_PULL]) + KA._pack_key("w")
    def stamp_loop(k2):
        t0 = time.perf_counter()
        for i in range(k2):
            wire = bytes([pull_payload[0] | KA._TRACE_FLAG]) \
                + _struct.pack(KA._CTX_FMT, 0, i, 123.0) \
                + pull_payload[1:]
            # the server-side strip the same request pays
            _ = bytes([wire[0] & ~KA._TRACE_FLAG]) \
                + wire[1 + KA._CTX_SIZE:]
        return time.perf_counter() - t0
    def stamp_base(k2):
        t0 = time.perf_counter()
        for i in range(k2):
            wire = pull_payload
            _ = wire
        return time.perf_counter() - t0
    k2 = 100000
    stamp_loop(k2 // 10), stamp_base(k2 // 10)  # warm
    ctx_ns = max(0.0, (min(stamp_loop(k2) for _ in range(5))
                       - min(stamp_base(k2) for _ in range(5)))
                 / k2 * 1e9)
    cli.stop_server()
    srv.stop()
    ctx_pct = ctx_ns / 1e3 / pull_rtt_us * 100.0
    if flightrec_was_on:
        flightrec.enable()

    gate_ok = bool(overhead_off < 2.0 and ctx_pct < 0.5
                   and off_stamped == 0)
    return {
        "metric": "profiler_off_overhead_pct",
        "value": round(overhead_off, 4),
        "unit": "%",
        "guard_ns_per_op": round(guard_ns, 1),
        "dispatch_us_per_op": round(dispatch_us, 2),
        "ops_per_sec_off": round(1.0 / best["off"], 1),
        "ops_per_sec_on": round(1.0 / best["on"], 1),
        "overhead_on_pct": round(overhead_on, 2),
        "record_latency_ns_per_call": round(record_latency_ns, 1),
        "wire_ctx": {
            "bytes_per_request": KA._CTX_SIZE,
            "ctx_ns_per_request": round(ctx_ns, 1),
            "pull_rtt_us": round(pull_rtt_us, 2),
            "added_rtt_pct": round(ctx_pct, 4),
            "off_path_stamped_frames": off_stamped,
        },
        "gate": {"ok": gate_ok, "budget_pct": 2.0,
                 "wire_budget_pct": 0.5},
        "chain_len": ops_per_iter,
        "tensor_side": n,
    }


def bench_flightrec_overhead():
    """BENCH_MODEL=flightrec_overhead: price of the ALWAYS-ON flight
    recorder ring (ISSUE 8 hard constraint: the black box must be free
    enough to never turn off).

    Same noise-robust shape as profiler_overhead — tight-loop deltas
    against measured best-of latencies, not an end-to-end A/B:

    1. ``ring_ns``: the EXACT extra work the flightrec-only hot path
       executes per eager op (the shared ``_HOOKS and _LIVE`` guard
       yielding the ``_FREC`` sentinel — no clock read — + one
       bare-name ``RING.append`` at the return site of
       register.invoke), measured by toggling ``flightrec.ENABLED``
       around the literal code shape, baseline subtracted.
    2. ``dispatch_us``: per-op eager dispatch latency with the recorder
       ON (its production state), best-of-N.
       Gate: ring_ns / dispatch_us < 0.5%.
    3. ``step_ns``: the fused step's per-step recorder work — one
       helper-path ``record_span`` via ``profiler.record_op`` (plus the
       early-returning ``record_latency``) — against the measured fused
       step latency of the train_step bench net.
       Gate: step_ns / fused_step_us < 0.1%.

    Sanity: the ring must actually have recorded the benched ops (an
    accidentally-disabled recorder would price at zero and lie)."""
    import tempfile
    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    from mxnet_tpu.ndarray import register as R
    from mxnet_tpu._debug import flightrec, watchdog

    n = int(os.environ.get("BENCH_EAGER_SIZE", 64))
    iters = int(os.environ.get("BENCH_EAGER_ITERS", 200))
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.rand(n, n).astype("float32"))
    y = mx.nd.array((rs.rand(n, n) + 0.5).astype("float32"))
    reps = 4
    ops_per_iter = reps * 4

    def run_chain():
        c = x
        for _ in range(reps):
            c = c * 0.5
            c = c + 1.0
            c = mx.nd.softmax(c)
            c = c + y
        return c

    profiler.set_config(
        filename=os.path.join(tempfile.mkdtemp(), "profile.json"),
        xprof=False)

    # -- 1. the ring record path, in isolation (profiling off) -----------
    # the literal flightrec-only return-site shape of register.invoke
    class _OpDef:
        name = "bench.op"
    opdef = _OpDef()

    _FREC = R._FREC

    def rec_loop(k):
        t0 = time.perf_counter()
        for _ in range(k):
            p = (time.perf_counter() if profiler._ACTIVE else _FREC) \
                if (R._HOOKS and profiler._LIVE) else None
            if p is not None:
                if p is _FREC:
                    flightrec.RING.append(opdef.name)
                else:
                    pass
        return time.perf_counter() - t0

    k = 200000
    flightrec.enable()
    rec_loop(k // 10)
    on_ns = min(rec_loop(k) for _ in range(7)) / k * 1e9
    flightrec.disable()
    try:
        rec_loop(k // 10)
        off_ns = min(rec_loop(k) for _ in range(7)) / k * 1e9
    finally:
        flightrec.enable()
    ring_ns = max(0.0, on_ns - off_ns)

    # -- 2. eager dispatch latency, recorder ON (production state) -------
    def dispatch_round(rounds):
        t0 = time.perf_counter()
        for _ in range(rounds):
            c = run_chain()
        c.wait_to_read()
        return (time.perf_counter() - t0) / (rounds * ops_per_iter)

    flightrec.reset_ring()
    for _ in range(4):
        dispatch_round(4)  # warm: dispatch cache compiles on repeat
    dispatch_us = min(dispatch_round(max(1, iters // 5))
                      for _ in range(5)) * 1e6
    ring_recorded = len(flightrec.RING) > 0
    eager_pct = ring_ns / 1e3 / dispatch_us * 100.0

    # -- 3. fused-step: helper-path record cost vs measured step ---------
    def helper_loop(k2):
        t0 = time.perf_counter()
        for _ in range(k2):
            p = time.perf_counter() if profiler._LIVE else None
            if p is not None:
                dur = (time.perf_counter() - p) * 1e6
                profiler.record_op("bench.step", dur, category="gluon",
                                   lane="gluon")
                profiler.record_latency("bench.step", dur)
        return time.perf_counter() - t0

    helper_loop(k // 10)
    step_ns = min(helper_loop(k) for _ in range(7)) / k * 1e9

    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    watchdog.reset()
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(16))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    l2 = gluon.loss.L2Loss()
    step = gluon.train_step(net, lambda o, t: l2(o, t), trainer)
    bx = mx.nd.array(rs.rand(32, 32).astype("float32"))
    by = mx.nd.array(rs.rand(32, 16).astype("float32"))
    for _ in range(6):
        step(bx, by, batch_size=32)
    assert step.last_mode == "fused", step.last_mode

    def step_round(rounds):
        t0 = time.perf_counter()
        for _ in range(rounds):
            loss = step(bx, by, batch_size=32)
        loss.wait_to_read()
        return (time.perf_counter() - t0) / rounds

    step_round(5)
    fused_step_us = min(step_round(20) for _ in range(5)) * 1e6
    fused_pct = step_ns / 1e3 / fused_step_us * 100.0
    watchdog.reset()

    gate_ok = bool(eager_pct < 0.5 and fused_pct < 0.1 and ring_recorded)
    return {
        "metric": "flightrec_overhead_pct",
        "value": round(eager_pct, 4),
        "unit": "%",
        "ring_ns_per_op": round(ring_ns, 1),
        "dispatch_us_per_op": round(dispatch_us, 2),
        "eager_pct": round(eager_pct, 4),
        "step_record_ns": round(step_ns, 1),
        "fused_step_us": round(fused_step_us, 1),
        "fused_pct": round(fused_pct, 4),
        "ring_recorded_benched_ops": ring_recorded,
        "ring_capacity": flightrec.stats()["capacity"],
        "gate": {"ok": gate_ok, "eager_budget_pct": 0.5,
                 "fused_budget_pct": 0.1},
    }


def bench_memory_overhead():
    """BENCH_MODEL=memory_overhead: price of the ALWAYS-ON tagged
    allocation ledger (ISSUE 13 hard constraint: the memory plane must
    be as close to free as the flight recorder).

    Same noise-robust shape as flightrec_overhead — tight-loop deltas
    against measured best-of latencies:

    1. ``add_ns``: the EXACT extra work the per-op dispatch return site
       executes per eager op when the ledger is on — one
       ``(weakref.ref(buf), op_name)`` append onto the 'activation'
       pending deque (no callback, no nbytes read, no lock) — measured
       by toggling ``storage.set_ledger_enabled`` around the literal
       code shape, baseline subtracted.
    2. ``retire_ns``: the amortized drain-side cost of retiring ONE
       dead entry (popleft + dead-weakref check inside
       ``storage.ledger_metrics``) — the work the memwatch/sampler
       daemons do per transient buffer, off the dispatch thread.
    3. ``dispatch_us``: per-op eager dispatch latency with the ledger
       ON (its production state), best-of-N.
       Gate: (add_ns + retire_ns) / dispatch_us < 0.5%.
    4. ``step_ns``: the fused step's per-step ledger work — the
       ``ledger_register`` helper calls ``_adopt_fused`` /
       ``_adopt_state`` issue (3 per trainable param + state leaves) —
       against the measured fused-step latency of the train_step bench
       net. Gate: step_ns / fused_step_us < 0.5%.

    Plus two sanity legs: the ledger must actually have integrated the
    benched ops (a disabled ledger pricing at zero would lie), and a
    synthetic leak must trip the memwatch detector EXACTLY once — one
    flight-record dump naming the leaking tag, no dump storm."""
    import glob
    import tempfile
    import weakref as _weakref
    import mxnet_tpu as mx
    from mxnet_tpu import profiler, storage
    from mxnet_tpu.ndarray import register as R
    from mxnet_tpu._debug import flightrec, memwatch, watchdog

    n = int(os.environ.get("BENCH_EAGER_SIZE", 64))
    iters = int(os.environ.get("BENCH_EAGER_ITERS", 200))
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.rand(n, n).astype("float32"))
    y = mx.nd.array((rs.rand(n, n) + 0.5).astype("float32"))
    reps = 4
    ops_per_iter = reps * 4

    def run_chain():
        c = x
        for _ in range(reps):
            c = c * 0.5
            c = c + 1.0
            c = mx.nd.softmax(c)
            c = c + y
        return c

    profiler.set_config(
        filename=os.path.join(tempfile.mkdtemp(), "profile.json"),
        xprof=False)

    # -- 1. the per-op add path, in isolation ----------------------------
    # the literal ledger shape of register.invoke's return site
    buf = x._data
    _wref = _weakref.ref
    _LEDGER_ACT = R._LEDGER_ACT
    name = "bench.op"

    def add_loop(k):
        t0 = time.perf_counter()
        for _ in range(k):
            if R._storage._LEDGER_ON:
                _LEDGER_ACT((_wref(buf), name))
        return time.perf_counter() - t0

    k = 200000
    storage.set_ledger_enabled(True)
    add_loop(k // 10)
    storage.ledger_reset()
    on_ns = min(add_loop(k) for _ in range(7)) / k * 1e9
    storage.ledger_reset()
    storage.set_ledger_enabled(False)
    try:
        add_loop(k // 10)
        off_ns = min(add_loop(k) for _ in range(7)) / k * 1e9
    finally:
        storage.set_ledger_enabled(True)
    add_ns = max(0.0, on_ns - off_ns)

    # -- 2. the drain-side retire of a dead entry ------------------------
    # transient eager results die before integration: their whole
    # ledger lifecycle is one popleft + one dead-weakref probe on the
    # memwatch/sampler daemon
    class _Tiny:
        __slots__ = ("__weakref__",)

    def drain_round(k2):
        storage.ledger_reset()
        for _ in range(k2):
            _LEDGER_ACT((_wref(_Tiny()), name))  # dead on arrival
        t0 = time.perf_counter()
        storage.ledger_metrics()
        return (time.perf_counter() - t0) / k2

    drain_round(1000)
    retire_ns = min(drain_round(20000) for _ in range(5)) * 1e9
    storage.ledger_reset()

    # -- 3. eager dispatch latency, ledger ON (production state) ---------
    def dispatch_round(rounds):
        t0 = time.perf_counter()
        for _ in range(rounds):
            c = run_chain()
        c.wait_to_read()
        return (time.perf_counter() - t0) / (rounds * ops_per_iter)

    for _ in range(4):
        dispatch_round(4)  # warm: dispatch cache compiles on repeat
    dispatch_us = min(dispatch_round(max(1, iters // 5))
                      for _ in range(5)) * 1e6
    pair_ns = add_ns + retire_ns
    eager_pct = pair_ns / 1e3 / dispatch_us * 100.0
    # sanity: the ledger must actually see the benched ops. Transient
    # chain results die before any drain (that IS their retirement), so
    # hold one result alive across the drain — a disabled ledger would
    # still read zero here
    kept = run_chain()
    kept.wait_to_read()
    ledger_saw_ops = \
        storage.ledger_metrics()["by_tag"]["activation"] > 0
    del kept

    # -- 4. fused-step: per-step ledger work vs measured step ------------
    p_nd = mx.nd.array(rs.rand(64, 64).astype("float32"))
    pbuf = p_nd._data

    def helper_loop(k2):
        t0 = time.perf_counter()
        for _ in range(k2):
            storage.ledger_register(pbuf, "param", site="bench")
        return time.perf_counter() - t0

    helper_loop(k // 10)
    helper_ns = min(helper_loop(k) for _ in range(7)) / k * 1e9
    storage.ledger_reset()

    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    watchdog.reset()
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(16))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    l2 = gluon.loss.L2Loss()
    step = gluon.train_step(net, lambda o, t: l2(o, t), trainer)
    bx = mx.nd.array(rs.rand(32, 32).astype("float32"))
    by = mx.nd.array(rs.rand(32, 16).astype("float32"))
    for _ in range(6):
        step(bx, by, batch_size=32)
    assert step.last_mode == "fused", step.last_mode
    # count the ACTUAL per-step registrations (param+grad adoption plus
    # however many state leaves this optimizer re-adopts) from the
    # ledger's own cumulative integration counter — hardcoding a
    # formula overcounts optimizers with empty state
    def _regs():
        return sum(storage.ledger_metrics()["registered_total"].values())

    r0 = _regs()
    for _ in range(10):
        step(bx, by, batch_size=32)
        storage.ledger_metrics()  # drain while this step's buffers live
    regs_per_step = max(1, round((_regs() - r0) / 10))

    def step_round(rounds):
        t0 = time.perf_counter()
        for _ in range(rounds):
            loss = step(bx, by, batch_size=32)
        loss.wait_to_read()
        return (time.perf_counter() - t0) / rounds

    step_round(5)
    fused_step_us = min(step_round(20) for _ in range(5)) * 1e6
    step_ns = helper_ns * regs_per_step
    fused_pct = step_ns / 1e3 / fused_step_us * 100.0
    watchdog.reset()

    # -- 5. synthetic-leak sanity: trips once, dumps once ----------------
    leak_dir = tempfile.mkdtemp()
    prev_env = os.environ.get("MXTPU_FLIGHTREC_DIR")
    os.environ["MXTPU_FLIGHTREC_DIR"] = leak_dir
    try:
        memwatch.reset()
        storage.ledger_reset()
        memwatch.configure(window=4, warmup_s=0.0, min_bytes=1 << 20,
                           poll_s=100)
        leak = []
        trips = 0
        for i in range(12):  # keeps growing well past the trip point
            leak.append(mx.nd.ones((256, 1024)))  # 1 MiB each, retained
            trips += int(memwatch.check_now())
        mstats = memwatch.stats()
        leak_dumps = glob.glob(
            os.path.join(leak_dir, "flightrec_r*_memleak_*.json"))
        leak_ok = (trips == 1 and mstats["trips"] == 1
                   and mstats["dumps"] == 1 and len(leak_dumps) == 1)
        leak.clear()
    finally:
        memwatch.reset()
        storage.ledger_reset()
        if prev_env is None:
            os.environ.pop("MXTPU_FLIGHTREC_DIR", None)
        else:
            os.environ["MXTPU_FLIGHTREC_DIR"] = prev_env

    gate_ok = bool(eager_pct < 0.5 and fused_pct < 0.5
                   and ledger_saw_ops and leak_ok)
    return {
        "metric": "memory_overhead_pct",
        "value": round(eager_pct, 4),
        "unit": "%",
        "add_ns_per_op": round(add_ns, 1),
        "retire_ns_per_entry": round(retire_ns, 1),
        "pair_ns": round(pair_ns, 1),
        "dispatch_us_per_op": round(dispatch_us, 2),
        "eager_pct": round(eager_pct, 4),
        "helper_register_ns": round(helper_ns, 1),
        "regs_per_step": regs_per_step,
        "step_ledger_ns": round(step_ns, 1),
        "fused_step_us": round(fused_step_us, 1),
        "fused_pct": round(fused_pct, 4),
        "ledger_recorded_benched_ops": ledger_saw_ops,
        "leak_watchdog": {"trips": trips, "dumps": len(leak_dumps),
                          "ok": leak_ok},
        "gate": {"ok": gate_ok, "eager_budget_pct": 0.5,
                 "fused_budget_pct": 0.5},
    }


def bench_goodput_overhead():
    """BENCH_MODEL=goodput_overhead: price of the run-level goodput
    ledger's hot-path shapes (ISSUE 14 hard constraint: drain-time
    accounting, no per-op cost — the run recorder may cost <0.1% of a
    fused step).

    The ledger's ONLY hot-path work is per *step* / per *batch*, never
    per op:

    1. ``note_ns``: one ``goodput.note_step`` call (what the watchdog
       beacon pays per completed step, riding the beacon's existing
       clock reads) plus one ``goodput.note_input_wait`` (what a
       prefetch consumer pays per batch), measured tight-loop with a
       run open, closed-run baseline subtracted.
    2. ``fused_step_us``: the measured fused step of the train_step
       bench net. Gate: note_ns / fused_step_us < 0.1%.

    Sanity: the ledger must actually have classified the benched steps
    (a run that recorded zero compute would price a no-op and lie) —
    the mini training run's manifest must land on disk with nonzero
    compute seconds and the right step count."""
    import tempfile
    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    from mxnet_tpu._debug import goodput, watchdog

    profiler.set_config(
        filename=os.path.join(tempfile.mkdtemp(), "profile.json"),
        xprof=False)
    # sanity-run manifests go to a scratch dir; the operator's
    # MXTPU_RUNS_DIR (where the __main__ trajectory manifest lands) is
    # restored before returning
    prev_runs_dir = os.environ.get("MXTPU_RUNS_DIR")
    runs_dir = tempfile.mkdtemp(prefix="bench_goodput_runs_")
    os.environ["MXTPU_RUNS_DIR"] = runs_dir
    goodput.reset()
    watchdog.reset()

    # -- 1. the per-step/per-batch note cost, run open vs closed ---------
    # kept under the mailbox backstop so the timed region prices the
    # HOT shape (GIL-atomic appends); the fold between rounds is the
    # watchdog poller's off-thread job in production
    k = 100000

    def note_loop(kk):
        goodput.fold_pending()
        t0 = time.perf_counter()
        base = t0
        for i in range(kk):
            if goodput.OPEN:
                goodput.note_step(base, 0.001, warmup=False,
                                  mode="fused")
                goodput.note_input_wait(2.0)
        return time.perf_counter() - t0

    goodput.open_run(run_id="bench_hot")
    note_loop(k // 10)
    on_ns = min(note_loop(k) for _ in range(7)) / k * 1e9
    goodput.close_run()
    note_loop(k // 10)
    off_ns = min(note_loop(k) for _ in range(7)) / k * 1e9
    note_ns = max(0.0, on_ns - off_ns)

    # -- 2. measured fused step (the train_step bench net) ---------------
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    watchdog.reset()
    rs = np.random.RandomState(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(16))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    l2 = gluon.loss.L2Loss()
    step = gluon.train_step(net, lambda o, t: l2(o, t), trainer)
    bx = mx.nd.array(rs.rand(32, 32).astype("float32"))
    by = mx.nd.array(rs.rand(32, 16).astype("float32"))
    for _ in range(6):
        step(bx, by, batch_size=32)
    assert step.last_mode == "fused", step.last_mode

    def step_round(rounds):
        t0 = time.perf_counter()
        for _ in range(rounds):
            loss = step(bx, by, batch_size=32)
        loss.wait_to_read()
        return (time.perf_counter() - t0) / rounds

    step_round(5)
    fused_step_us = min(step_round(20) for _ in range(5)) * 1e6
    fused_pct = note_ns / 1e3 / fused_step_us * 100.0

    # -- 3. sanity: a real mini run classifies and publishes -------------
    goodput.reset()
    run_id = goodput.open_run(run_id="bench_sanity")
    sanity_steps = 10
    for _ in range(sanity_steps):
        step(bx, by, batch_size=32)
    manifest = goodput.close_run()
    compute_s = manifest["categories_s"]["compute"]
    recorded = (manifest["steps"]["count"] >= sanity_steps
                and compute_s > 0
                and os.path.exists(goodput.manifest_path(run_id))
                and "write_error" not in manifest)
    watchdog.reset()
    if prev_runs_dir is None:
        os.environ.pop("MXTPU_RUNS_DIR", None)
    else:
        os.environ["MXTPU_RUNS_DIR"] = prev_runs_dir

    gate_ok = bool(fused_pct < 0.1 and recorded)
    return {
        "metric": "goodput_overhead_pct",
        "value": round(fused_pct, 4),
        "unit": "%",
        "note_ns_per_step": round(note_ns, 1),
        "fused_step_us": round(fused_step_us, 1),
        "fused_pct": round(fused_pct, 4),
        "sanity_steps": sanity_steps,
        "sanity_compute_s": round(compute_s, 6),
        "sanity_goodput_ratio": round(manifest["goodput_ratio"], 4),
        "ledger_recorded_benched_steps": recorded,
        "gate": {"ok": gate_ok, "fused_budget_pct": 0.1},
    }


def bench_perf_attrib():
    """BENCH_MODEL=perf_attrib: the roofline/MFU attribution plane
    (ISSUE 17) — priced AND checked for correctness.

    1. ``note_ns``: the ONLY per-step work the plane adds on top of the
       watchdog beacon is one signature-tagged ``perfmodel.note_step``
       mailbox append (the beacon's already-computed duration; no lock,
       no clock read). Tight-loop priced, disabled-guard baseline
       subtracted. Gate: < 0.5% of a fused step.
    2. MFU join correctness: the train_step bench net is trained to
       fused mode under an open goodput run; the perfmodel row's
       reported MFU must match a hand-derived
       ``flops / (median_s * peak_tflops * 1e12)`` within 5%, with
       flops taken from the profiler compile registry (the independent
       modeled source) and the peak re-resolved from the comm_model
       ASSUMPTIONS table by the row's own dtype.
    3. The compare CLI: the real run manifest must render (exit 0), an
       identical synthetic pair must compare clean (exit 0), and a
       synthetic 2x-slowdown candidate (median doubled, MFU halved)
       must exit 1 — the cross-run regression gate actually gates."""
    import subprocess
    import tempfile
    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    from mxnet_tpu._debug import goodput, perfmodel, watchdog
    from mxnet_tpu.gluon.fused_step import _load_comm_model

    profiler.set_config(
        filename=os.path.join(tempfile.mkdtemp(), "profile.json"),
        xprof=False)
    prev_runs_dir = os.environ.get("MXTPU_RUNS_DIR")
    runs_dir = tempfile.mkdtemp(prefix="bench_perf_runs_")
    os.environ["MXTPU_RUNS_DIR"] = runs_dir
    goodput.reset()
    watchdog.reset()
    perfmodel.reset()

    # -- 1. the per-step note cost, enabled vs disabled-guard ------------
    k = 100000

    def note_loop(kk):
        perfmodel.fold_pending()
        t0 = time.perf_counter()
        for _ in range(kk):
            if perfmodel.ENABLED:
                perfmodel.note_step("fused_step:bench", 0.001)
        return time.perf_counter() - t0

    perfmodel.configure(enabled=True)
    note_loop(k // 10)
    on_ns = min(note_loop(k) for _ in range(7)) / k * 1e9
    perfmodel.configure(enabled=False)
    note_loop(k // 10)
    off_ns = min(note_loop(k) for _ in range(7)) / k * 1e9
    note_ns = max(0.0, on_ns - off_ns)
    perfmodel.reset()

    # -- 2. the bench net's MFU vs hand-derived --------------------------
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    watchdog.reset()
    rs = np.random.RandomState(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(16))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    l2 = gluon.loss.L2Loss()
    step = gluon.train_step(net, lambda o, t: l2(o, t), trainer)
    bx = mx.nd.array(rs.rand(32, 32).astype("float32"))
    by = mx.nd.array(rs.rand(32, 16).astype("float32"))
    run_id = goodput.open_run(run_id="bench_perf")
    for _ in range(6):
        step(bx, by, batch_size=32)
    assert step.last_mode == "fused", step.last_mode

    def step_round(rounds):
        t0 = time.perf_counter()
        for _ in range(rounds):
            loss = step(bx, by, batch_size=32)
        loss.wait_to_read()
        return (time.perf_counter() - t0) / rounds

    step_round(5)
    fused_step_us = min(step_round(20) for _ in range(5)) * 1e6
    fused_pct = note_ns / 1e3 / fused_step_us * 100.0

    perfmodel.fold_pending()
    rows = [r for r in perfmodel.table()
            if r["sig"].startswith("fused_step:") and r["mfu"]]
    joined = bool(rows)
    mfu_reported = mfu_hand = mfu_rel_err_pct = None
    row = {}
    if joined:
        row = rows[0]
        # the independent modeled source: the profiler compile
        # registry's XLA cost analysis, NOT perfmodel's own copy — and
        # the peak re-resolved from the ASSUMPTIONS table by dtype
        flops = profiler.compile_stats()["fused_step"]["flops"]
        cm = _load_comm_model()
        peak = cm.peak_tflops(row["dtype"])
        mfu_reported = row["mfu"]
        mfu_hand = flops / (row["median_s"] * peak * 1e12)
        mfu_rel_err_pct = abs(mfu_reported - mfu_hand) / mfu_hand * 100.0

    # -- 3. the compare CLI gates ----------------------------------------
    manifest = goodput.close_run()
    report = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "perf_report.py")

    def run_report(*argv):
        return subprocess.run(
            [sys.executable, report] + list(argv),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            timeout=120).returncode

    rc_render = run_report(goodput.manifest_path(run_id))
    synth_dir = tempfile.mkdtemp(prefix="bench_perf_cli_")

    def synth(name, median_s, mfu):
        p = os.path.join(synth_dir, name)
        with open(p, "w", encoding="utf-8") as f:
            json.dump({
                "schema": "mxtpu.goodput.run/1", "run_id": name,
                "outcome": "completed",
                "perf": {"schema": "mxtpu.perf/1", "signatures": {
                    "fused_step:cafef00d": {
                        "steps": 100, "median_s": median_s, "mfu": mfu,
                        "bound": "compute"}}}}, f)
        return p

    base = synth("base.json", 0.010, 0.40)
    rc_same = run_report("--compare", base, synth("same.json",
                                                  0.010, 0.40))
    rc_slow = run_report("--compare", base, synth("slow.json",
                                                  0.020, 0.20))

    watchdog.reset()
    perfmodel.reset()
    if prev_runs_dir is None:
        os.environ.pop("MXTPU_RUNS_DIR", None)
    else:
        os.environ["MXTPU_RUNS_DIR"] = prev_runs_dir

    gate_ok = bool(fused_pct < 0.5 and joined
                   and mfu_rel_err_pct is not None
                   and mfu_rel_err_pct < 5.0
                   and "perf" in manifest
                   and rc_render == 0 and rc_same == 0 and rc_slow == 1)
    return {
        "metric": "perf_attrib",
        "value": round(fused_pct, 4),
        "unit": "%",
        "note_ns_per_step": round(note_ns, 1),
        "fused_step_us": round(fused_step_us, 1),
        "fused_pct": round(fused_pct, 4),
        "joined": joined,
        "signature": row.get("sig"),
        "dtype": row.get("dtype"),
        "bound": row.get("bound"),
        "mfu_reported": mfu_reported,
        "mfu_hand_derived": mfu_hand,
        "mfu_rel_err_pct": (round(mfu_rel_err_pct, 4)
                            if mfu_rel_err_pct is not None else None),
        "manifest_has_perf_block": "perf" in manifest,
        "report_exit_render": rc_render,
        "report_exit_identical": rc_same,
        "report_exit_2x_slowdown": rc_slow,
        "gate": {"ok": gate_ok, "fused_budget_pct": 0.5,
                 "mfu_tolerance_pct": 5.0},
    }


def bench_health_overhead():
    """BENCH_MODEL=health_overhead: price of the training-health plane
    (ISSUE 15 hard constraint): the every-step sentinel — a handful of
    fused sum reductions in-graph plus ONE packed host fetch — must
    cost under 0.5% of a fused step, and the full per-layer Monitor
    pass (per-parameter host transfers) must run ONLY on
    `MXTPU_HEALTH_INTERVAL` boundaries, never per step.

    Prices the exact hot shapes (the memory/goodput gate discipline —
    an end-to-end on/off A/B at this budget sits below scheduler noise
    on a 100ms CPU step, so the components are measured tight-loop):

    1. ``summary_us``: the in-graph sentinel summary compiled
       STANDALONE over the bench net's param/loss shapes — an upper
       bound on its fused marginal cost (standalone it cannot fuse
       into the backward, and it pays its own dispatch).
    2. ``note_us``: the per-step host half (`healthmon.note_step`:
       one device transfer of the packed vector, CRC digest, loss
       window, episode latch) over a real committed summary.
    3. ``fused_step_us``: the measured fused step of the scaled bench
       net (3x Dense-512, batch 8192 — compute scales with
       batch x params while the sentinel scales with params alone,
       the ratio a real model has).

    Gate: (summary_us + note_us) / fused_step_us < 0.5%. Sanity legs:
    health=1 steady state actually runs 'fused' (a trace failure would
    silently price the eager path), the sentinels checked the benched
    steps, an interleaved end-to-end A/B delta stays under a loose 5%
    noise bound, and the layer-pass counter equals exactly the
    interval boundaries crossed."""
    import tempfile
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, profiler
    from mxnet_tpu.gluon import nn
    from mxnet_tpu._debug import healthmon, watchdog
    from mxnet_tpu.parallel import overlap

    profiler.set_config(
        filename=os.path.join(tempfile.mkdtemp(), "profile.json"),
        xprof=False)
    os.environ["MXTPU_HEALTH_ACTION"] = "record"
    watchdog.reset()
    rs = np.random.RandomState(0)
    batch = int(os.environ.get("BENCH_HEALTH_BATCH", "8192"))
    bx = rs.rand(batch, 512).astype("float32")
    by = rs.rand(batch, 16).astype("float32")

    def build_step():
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(512, activation="relu"),
                nn.Dense(512, activation="relu"), nn.Dense(16))
        net.initialize()
        net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.01, "momentum": 0.9})
        l2 = gluon.loss.L2Loss()
        step = gluon.train_step(net, lambda o, t: l2(o, t), trainer)
        return step

    def warm(health):
        os.environ["MXTPU_HEALTH"] = health
        step = build_step()
        x, y = mx.nd.array(bx), mx.nd.array(by)
        for _ in range(6):
            step(x, y, batch_size=batch)
        assert step.last_mode == "fused", step.last_mode
        return step, x, y

    def round_(cfg, n):
        health, step, x, y = cfg
        os.environ["MXTPU_HEALTH"] = health
        t0 = time.perf_counter()
        for _ in range(n):
            loss = step(x, y, batch_size=batch)
        loss.wait_to_read()
        return (time.perf_counter() - t0) / n

    healthmon.reset()
    cfg_off = ("0",) + warm("0")
    cfg_on = ("1",) + warm("1")
    # end-to-end A/B, interleaved (load drifts over seconds-long
    # blocks): a loose sanity bound only — the precise price comes
    # from the component measurements below
    round_(cfg_off, 2)
    round_(cfg_on, 2)
    offs, ons = [], []
    for _ in range(5):
        offs.append(round_(cfg_off, 4))
        ons.append(round_(cfg_on, 4))
    off_us = min(offs) * 1e6
    on_us = min(ons) * 1e6
    e2e_delta_pct = (on_us - off_us) / off_us * 100.0
    st = healthmon.stats()
    sentinels_ran = st["steps"] > 0 and healthmon.last_digest() is not None
    # every-step path must NOT have run the full per-layer pass
    # (interval defaults to 0 and no Monitor is attached)
    no_eager_layer_pass = st["layer_passes"] == 0

    # -- component 1: the standalone-jitted summary over the net shapes
    shapes = [(512, 512), (512,), (512, 512), (512,), (512, 16), (16,)]
    gs = [jnp.asarray(rs.rand(*s).astype(np.float32)) for s in shapes]
    ws = [jnp.asarray(rs.rand(*s).astype(np.float32)) for s in shapes]
    loss_v = jnp.asarray(rs.rand(batch).astype(np.float32))
    plan = overlap.bucket_plan(gs)

    @jax.jit
    def summary_fn(gs, ws, loss_v):
        return healthmon.graph_summary(plan, gs, ws, loss_v)[0]

    packed = summary_fn(gs, ws, loss_v)
    jax.block_until_ready(packed)

    def summary_round(n):
        t0 = time.perf_counter()
        for _ in range(n):
            out = summary_fn(gs, ws, loss_v)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / n

    summary_round(50)
    summary_us = min(summary_round(200) for _ in range(7)) * 1e6

    # -- component 2: the note_step host half over a committed summary
    names = ["p%d" % i for i in range(len(shapes))]
    hmeta = {"plan": [list(b) for b in plan], "names": names,
             "bucket_names": [[names[i] for i in b] for b in plan],
             "action": "record", "select": False}
    healthmon.reset()

    def note_round(n):
        t0 = time.perf_counter()
        for _ in range(n):
            healthmon.note_step(packed, hmeta, gs, ws, batch)
        return (time.perf_counter() - t0) / n

    note_round(100)
    note_us = min(note_round(500) for _ in range(7)) * 1e6
    healthmon.reset()
    overhead_pct = (summary_us + note_us) / off_us * 100.0

    # -- interval leg: the full pass runs exactly on boundaries ----------
    os.environ["MXTPU_HEALTH"] = "1"
    healthmon.reset()
    healthmon.configure(interval=5)
    step = build_step()
    x, y = mx.nd.array(bx), mx.nd.array(by)
    for _ in range(2 + 20):  # 2 eager warming + 20 checked steps
        step(x, y, batch_size=batch)
    st_int = healthmon.stats()
    interval_ok = st_int["steps"] == 20 and st_int["layer_passes"] == 4
    os.environ["MXTPU_HEALTH"] = "0"
    os.environ.pop("MXTPU_HEALTH_ACTION", None)
    healthmon.reset()
    watchdog.reset()

    e2e_ok = e2e_delta_pct < 5.0
    gate_ok = bool(overhead_pct < 0.5 and sentinels_ran
                   and no_eager_layer_pass and interval_ok and e2e_ok)
    return {
        "metric": "health_overhead_pct",
        "value": round(overhead_pct, 4),
        "unit": "%",
        "summary_us": round(summary_us, 1),
        "note_us": round(note_us, 1),
        "fused_step_off_us": round(off_us, 1),
        "fused_step_on_us": round(on_us, 1),
        "e2e_delta_pct": round(e2e_delta_pct, 3),
        "e2e_noise_bound_ok": e2e_ok,
        "sentinel_steps_checked": st["steps"],
        "sentinels_ran": sentinels_ran,
        "layer_passes_every_step_leg": st["layer_passes"],
        "interval_leg": {"steps": st_int["steps"],
                         "layer_passes": st_int["layer_passes"],
                         "ok": interval_ok},
        "gate": {"ok": gate_ok, "budget_pct": 0.5,
                 "e2e_noise_bound_pct": 5.0},
    }


def bench_comm_overlap():
    """BENCH_MODEL=comm_overlap: the ISSUE 7 overlap story, gated.

    1. MEASURED (virtual 8-device mesh, compiled HLO): the pure-dp
       transformer train step's all-reduce payload with the stock
       chunked CE (GSPMD keeps the unembedding-grad AR inside the chunk
       scan — the SCALING_r05 finding) vs ``ce_local_accum=True``
       (shard_map'd loss accumulates locally, reduces once). Gate:
       wire bytes DROP, by ~(loss_chunks-1)*vocab*dim*4.
    2. MODELED (v5e assumptions from benchmark/comm_model.py): exposed
       comm time per step at n chips for the two real measured
       workloads, serial (all reduction after backward) vs bucketed
       backward-overlap (parallel/overlap.py semantics: one size-capped
       bucket launches as soon as its backward segment completes; the
       wire drains buckets in completion order while the rest of the
       backward still computes). Gate: overlap STRICTLY reduces exposed
       comm time for every workload.
    """
    import math
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "benchmark"))
    import comm_model as CM

    # the HLO measurement needs a multi-device mesh: a virtual 8-device
    # CPU mesh, requested BEFORE the first backend client exists (on
    # this jax the XLA_FLAGS count is parsed once at client creation —
    # probing jax.devices() first would freeze it at 1).
    # force_virtual_cpu_devices owns the whole dance: config knob on
    # current jax, XLA_FLAGS replacement (including a stale pre-set
    # count) on older jax, clear_backends for preloaded plugins.
    from tools.launch import force_virtual_cpu_devices
    force_virtual_cpu_devices(8)
    import jax

    # -- 1. measured: chunked-CE wire bytes, stock vs local-accum -------
    import jax.numpy as jnp
    import jax.random as jr
    from mxnet_tpu.parallel import create_mesh
    from mxnet_tpu.parallel import transformer as T

    V, D, L, chunks = 512, 128, 2, 4
    ar_bytes = {}
    for local in (False, True):
        cfg = T.TransformerConfig(
            vocab_size=V, dim=D, n_layers=L, n_heads=4, ffn_hidden=4 * D,
            attn_mode="local", loss_chunks=chunks, ce_local_accum=local)
        mesh = create_mesh(devices=jax.devices()[:8])
        init_fn, step_fn = T.make_train_step(cfg, mesh)
        with mesh.mesh:
            state = init_fn(jr.PRNGKey(0))
            toks = jnp.zeros((16, 64), jnp.int32)
            compiled = step_fn.lower(state, toks, toks).compile()
        inv = CM.collect_hlo_inventory(compiled)
        ar_bytes["local_accum" if local else "baseline"] = \
            inv["bytes_by_kind"].get("all-reduce", 0)
    saved = ar_bytes["baseline"] - ar_bytes["local_accum"]
    expect_saved = (chunks - 1) * V * D * 4
    # gate the ANALYTIC drop, not merely "some" drop: a partial
    # regression of the local-accum path (one chunk's AR creeping back)
    # must trip this. 1% slack covers scalar/loss-bookkeeping ARs.
    ce_ok = saved > 0 and abs(saved - expect_saved) <= \
        max(4096, 0.01 * expect_saved)

    # -- 2. modeled: exposed comm, serial vs bucketed overlap ----------
    bucket_cap = float(os.environ.get("MXTPU_ELASTIC_BUCKET_MB", "4")) \
        * (1 << 20)
    bwd_frac = 2.0 / 3.0   # backward ~2x forward FLOPs

    def wire_s(payload, n):
        return sum(CM.allreduce_seconds(payload, n))

    def exposed(step_s, payload, n):
        """(serial, bucketed) exposed comm seconds. Buckets become
        data-ready uniformly through the backward (grad bytes are
        produced roughly linearly in backward time); the wire is one
        serialized channel that starts each bucket at
        max(data_ready, previous bucket done)."""
        t_bwd = step_s * bwd_frac
        serial = wire_s(payload, n)
        k = max(1, int(math.ceil(payload / bucket_cap)))
        sizes = [bucket_cap] * (k - 1) + [payload - bucket_cap * (k - 1)]
        finish = 0.0
        for i, b in enumerate(sizes, 1):
            ready = t_bwd * i / k
            finish = max(ready, finish) + wire_s(b, n)
        return serial, max(0.0, finish - t_bwd), k

    workloads = {
        # the two real single-chip workloads comm_model projects
        # (step times measured on the attached v5e, BENCH_r04/r05)
        "resnet50_b128_bf16": (0.0495, 4 * 25_557_032),
        "transformer_1p6B_b12_s2048": (1.909, 4 * 1_604_400_000),
    }
    ns = [8, 64, 256]
    rows, overlap_ok = {}, True
    for name, (step_s, payload) in workloads.items():
        per_n = []
        for n in ns:
            serial, ovl, k = exposed(step_s, payload, n)
            per_n.append({
                "n": n, "buckets": k,
                "exposed_comm_ms_serial": round(serial * 1e3, 3),
                "exposed_comm_ms_overlap": round(ovl * 1e3, 3),
                "step_ms_no_overlap": round((step_s + serial) * 1e3, 2),
                "step_ms_overlap": round((step_s + ovl) * 1e3, 2),
                "efficiency_no_overlap": round(
                    step_s / (step_s + serial), 4),
                "efficiency_overlap": round(step_s / (step_s + ovl), 4),
            })
            if not ovl < serial:
                overlap_ok = False
        rows[name] = per_n

    gate_ok = bool(ce_ok and overlap_ok)
    return {
        "metric": "comm_overlap_model",
        "value": rows["resnet50_b128_bf16"][-1]["efficiency_overlap"],
        "unit": "modeled efficiency at 256 chips (overlap)",
        "bucket_cap_bytes": int(bucket_cap),
        "backward_fraction": bwd_frac,
        "chunked_ce": {
            "config": {"vocab": V, "dim": D, "layers": L,
                       "loss_chunks": chunks, "mesh": "dp=8"},
            "allreduce_bytes_baseline": ar_bytes["baseline"],
            "allreduce_bytes_local_accum": ar_bytes["local_accum"],
            "bytes_saved": saved,
            "analytic_expected_saved": expect_saved,
        },
        "modeled": rows,
        "assumptions": CM.ASSUMPTIONS,
        "gate": {"ok": gate_ok, "ce_bytes_drop": bool(ce_ok),
                 "overlap_strictly_reduces_exposed": bool(overlap_ok)},
    }


def bench_fused_kernels():
    """BENCH_MODEL=fused_kernels: the PR 9 Pallas kernel campaign gate
    (ROADMAP item 4) over batchnorm_fused, optimizer_apply, and
    quantized_matmul — the modules KERNEL_BENCH maps here.

    On every backend: parity — fused BN vs its reference within 64 ULP
    (forward + grads), packed optimizer apply BITWISE-equal to the
    per-parameter step_fn chain inside one jit (SGD-momentum and Adam),
    int8 matmul exactly equal to the XLA int32 dot (integer math is
    exact), and a 5-step fused-train-step run bitwise-identical with
    MXTPU_FUSED_APPLY=0/1. The kernels run in interpreter mode on CPU
    (the real kernel code, interpreted) and compiled on TPU. On a real
    backend additionally: >=1.5x vs the jitted XLA baseline per kernel.
    Kernel first-builds must appear in profiler.compile_stats() (the
    ISSUE 8 Compile table). Exits non-zero on any breach."""
    import importlib

    import jax
    import jax.numpy as jnp

    BN = importlib.import_module(
        "mxnet_tpu.pallas_kernels.batchnorm_fused")
    OA = importlib.import_module(
        "mxnet_tpu.pallas_kernels.optimizer_apply")
    QM = importlib.import_module(
        "mxnet_tpu.pallas_kernels.quantized_matmul")
    from mxnet_tpu import profiler
    from mxnet_tpu.optimizer.optimizer import SGD, Adam

    # the ONE ULP-distance definition (shared with the per-op sweep)
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "benchmark"))
    from tpu_numerics import _max_ulp as _ulp

    def _max_ulp(a, b):
        return _ulp(np.asarray(a, np.float32), np.asarray(b, np.float32))

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    interp = not on_tpu
    breaches = []
    out = {"metric": "fused_kernels", "platform": platform,
           "mode": "compiled" if on_tpu else "interpret"}

    def _speedup(fast, slow, args):
        """median-of-3 alternating rounds of jitted fast vs slow."""
        jf, js = jax.jit(fast), jax.jit(slow)
        jax.block_until_ready(jf(*args))
        jax.block_until_ready(js(*args))
        iters = int(os.environ.get("BENCH_KERNEL_ITERS", 20))
        rates = {"f": [], "s": []}
        for _ in range(3):
            for key, fn in (("f", jf), ("s", js)):
                t0 = time.perf_counter()
                for _ in range(iters):
                    r = fn(*args)
                jax.block_until_ready(r)
                rates[key].append(iters / (time.perf_counter() - t0))
        med = {k: sorted(v)[1] for k, v in rates.items()}
        return med["f"] / med["s"]

    rs = np.random.RandomState(0)

    # -- (a) fused BatchNorm ------------------------------------------------
    x = jnp.asarray(rs.randn(8, 16, 16, 256).astype("float32") * 2 + 1)
    g = jnp.asarray(rs.rand(256).astype("float32") + 0.5)
    b = jnp.asarray(rs.randn(256).astype("float32"))
    o_k, m_k, v_k = jax.jit(
        lambda *a: BN.fused_batch_norm(*a, act="relu",
                                       interpret=interp))(x, g, b)
    o_r, m_r, v_r = jax.jit(
        lambda *a: BN.batchnorm_reference(*a, act="relu"))(x, g, b)
    bn_ulp = max(_max_ulp(o_k, o_r), _max_ulp(m_k, m_r),
                 _max_ulp(v_k, v_r))

    def loss_k(x, g, b):
        return jnp.sum(BN.fused_batch_norm(x, g, b,
                                           interpret=interp)[0] ** 2)

    def loss_r(x, g, b):
        return jnp.sum(BN.batchnorm_reference(x, g, b)[0] ** 2)

    gk = jax.jit(jax.grad(loss_k, argnums=(0, 1, 2)))(x, g, b)
    gr = jax.jit(jax.grad(loss_r, argnums=(0, 1, 2)))(x, g, b)
    bn_grad_ok = all(
        float(jnp.max(jnp.abs(a - c))) <=
        1e-4 * (1.0 + float(jnp.max(jnp.abs(c))))
        for a, c in zip(gk, gr))
    out["batchnorm_fused"] = {"max_ulp": bn_ulp, "grads_ok": bn_grad_ok}
    if bn_ulp > 64:
        breaches.append("batchnorm_fused parity %d ULP > 64" % bn_ulp)
    if not bn_grad_ok:
        breaches.append("batchnorm_fused grads diverge from reference")
    if on_tpu:
        sp = _speedup(
            lambda x, g, b: BN.fused_batch_norm(x, g, b, act="relu")[0],
            lambda x, g, b: jnp.maximum(
                BN.batchnorm_reference(x, g, b)[0], 0.0),
            (x, g, b))
        out["batchnorm_fused"]["speedup"] = round(sp, 2)
        if sp < 1.5:
            breaches.append("batchnorm_fused %.2fx < 1.5x" % sp)

    # -- (b) packed optimizer apply -----------------------------------------
    shapes = [(256, 256), (256,), (256, 128), (128,), (512, 64), (64,),
              (33, 7)]
    ws = [jnp.asarray(rs.randn(*s).astype("float32")) for s in shapes]
    gs = [jnp.asarray(rs.randn(*s).astype("float32")) for s in shapes]
    apply_res = {}
    for name, opt, states in [
            ("sgd_momentum", SGD(momentum=0.9, learning_rate=0.05,
                                 wd=1e-4),
             [jnp.zeros_like(w) for w in ws]),
            ("adam", Adam(learning_rate=1e-3),
             [(jnp.zeros_like(w), jnp.zeros_like(w)) for w in ws])]:
        lrs = [jnp.float32(0.05 + 0.001 * i) for i in range(len(ws))]
        wds = [jnp.float32(1e-4)] * len(ws)
        rescale = jnp.float32(1.0 / 32)

        def perparam(ws, gs, states, lrs, wds, rescale):
            outs = [opt.step_fn(w, g, st, lr, wd, rescale)
                    for w, g, st, lr, wd in zip(ws, gs, states, lrs,
                                                wds)]
            return [o[0] for o in outs], [o[1] for o in outs]

        def packed(ws, gs, states, lrs, wds, rescale):
            return OA.packed_apply(opt, ws, gs, states, lrs, wds,
                                   rescale, interpret=interp)

        r_pp = jax.jit(perparam)(ws, gs, states, lrs, wds, rescale)
        r_pk = jax.jit(packed)(ws, gs, states, lrs, wds, rescale)
        bitwise = all(
            bool(jnp.array_equal(a, c))
            for a, c in zip(jax.tree_util.tree_leaves(r_pp),
                            jax.tree_util.tree_leaves(r_pk)))
        apply_res[name] = {"bitwise": bitwise}
        if not bitwise:
            breaches.append("optimizer_apply %s not bitwise-equal to "
                            "step_fn" % name)
        if on_tpu:
            sp = _speedup(packed, perparam,
                          (ws, gs, states, lrs, wds, rescale))
            apply_res[name]["speedup"] = round(sp, 2)
            if sp < 1.5:
                breaches.append("optimizer_apply %s %.2fx < 1.5x"
                                % (name, sp))
    out["optimizer_apply"] = apply_res

    # -- (b2) the fused train step with MXTPU_FUSED_APPLY -------------------
    def train_params(mode):
        prev = os.environ.get("MXTPU_FUSED_APPLY")
        os.environ["MXTPU_FUSED_APPLY"] = mode
        try:
            import random as _pyrandom

            import mxnet_tpu as mx
            from mxnet_tpu import gluon
            _pyrandom.seed(0)
            np.random.seed(0)
            mx.random.seed(0)
            net = gluon.nn.HybridSequential()
            with net.name_scope():
                net.add(gluon.nn.Dense(32, in_units=16,
                                       activation="relu"))
                net.add(gluon.nn.Dense(1, in_units=32))
            net.initialize(mx.init.Uniform(0.1))
            net.hybridize()
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.05, "momentum": 0.9})
            step = gluon.train_step(net, gluon.loss.L2Loss(), tr)
            rsl = np.random.RandomState(0)
            xb = mx.nd.array(rsl.rand(16, 16).astype("float32"))
            yb = mx.nd.array(rsl.rand(16, 1).astype("float32"))
            for _ in range(5):
                step(xb, yb, batch_size=16)
            assert step.last_mode == "fused", step.last_mode
            return [p.data().asnumpy()
                    for _, p in sorted(net.collect_params().items())]
        finally:
            if prev is None:
                os.environ.pop("MXTPU_FUSED_APPLY", None)
            else:
                os.environ["MXTPU_FUSED_APPLY"] = prev

    base = train_params("0")
    fused_apply_bitwise = all(
        np.array_equal(a, c) for a, c in zip(base, train_params("1")))
    interp_bitwise = all(
        np.array_equal(a, c)
        for a, c in zip(base, train_params("interpret")))
    out["fused_step_apply_bitwise"] = {"packed": fused_apply_bitwise,
                                       "interpret": interp_bitwise}
    if not (fused_apply_bitwise and interp_bitwise):
        breaches.append("MXTPU_FUSED_APPLY train step not bitwise vs "
                        "per-param")

    # -- (c) quantized matmul -----------------------------------------------
    xq = jnp.asarray(rs.randint(-127, 128, (256, 512)).astype("int8"))
    wq = jnp.asarray(rs.randint(-127, 128, (512, 256)).astype("int8"))
    scales = jnp.asarray(rs.rand(256).astype("float32") * 0.01)
    acc_k = jax.jit(
        lambda x, w: QM.quantized_matmul(x, w, interpret=interp))(xq, wq)
    acc_r = jax.jit(QM.quantized_matmul_reference)(xq, wq)
    qm_exact = bool(jnp.array_equal(acc_k, acc_r))
    sc_k = jax.jit(lambda x, w, s: QM.quantized_matmul(
        x, w, scales=s, interpret=interp))(xq, wq, scales)
    sc_r = jax.jit(lambda x, w, s: QM.quantized_matmul_reference(
        x, w, scales=s))(xq, wq, scales)
    qm_scaled_ulp = _max_ulp(sc_k, sc_r)
    out["quantized_matmul"] = {"int32_exact": qm_exact,
                               "scaled_max_ulp": qm_scaled_ulp}
    if not qm_exact:
        breaches.append("quantized_matmul int32 accumulator != XLA dot")
    if qm_scaled_ulp > 1:
        breaches.append("quantized_matmul scaled epilogue %d ULP > 1"
                        % qm_scaled_ulp)
    if on_tpu:
        sp = _speedup(lambda x, w: QM.quantized_matmul(x, w),
                      QM.quantized_matmul_reference, (xq, wq))
        out["quantized_matmul"]["speedup"] = round(sp, 2)
        if sp < 1.5:
            breaches.append("quantized_matmul %.2fx < 1.5x" % sp)

    # -- compile attribution (ISSUE 8c): kernel builds in the Compile table
    compiles = [k for k in profiler.compile_stats() if
                k.startswith("pallas:")]
    out["compile_attribution"] = sorted(compiles)
    if not any("batchnorm_fused" in k for k in compiles) \
            or not any("optimizer_apply" in k for k in compiles) \
            or not any("quantized_matmul" in k for k in compiles):
        breaches.append("kernel compiles missing from "
                        "profiler.compile_stats(): %s" % compiles)

    out["value"] = len(breaches)
    out["unit"] = "breaches"
    out["gate"] = {"ok": not breaches, "breaches": breaches,
                   "min_speedup": 1.5}
    return out


def bench_gspmd_step():
    """BENCH_MODEL=gspmd_step: the ISSUE 16 3D-parallel fused-step gate.

    1. MEASURED (virtual 8-device mesh, compiled HLO of the Trainer-path
       ``FusedTrainStep``): the per-step all-reduce payload under
       dp-only (manual shard_map), dp×tp, and dp×tp×sp must match the
       analytic 4 bytes/param within 1% — ONE gradient reduction per
       step, no hidden resharding traffic. The GSPMD configs must also
       hold the matched-shardings contract (weight/opt-state output
       shardings == input shardings) and reach steady-state 'fused'.
    2. MEASURED (transformer fused loss, auto ``ce_local_accum``):
       all-reduce bytes for ``loss_chunks=2`` vs ``loss_chunks=4`` are
       IDENTICAL — the chunk count never appears on the wire, i.e. the
       unembedding grad reduces once regardless of chunking.
    """
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "benchmark"))
    import comm_model as CM

    from tools.launch import force_virtual_cpu_devices
    force_virtual_cpu_devices(8)
    import jax
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import create_mesh

    def _step_bytes(mesh, rules=None):
        rs = onp.random.RandomState(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu", in_units=12))
        net.add(nn.Dense(4, in_units=16))
        net.initialize()
        net.hybridize()
        for _, p in sorted(net.collect_params().items()):
            p.set_data(mx.nd.array(
                rs.randn(*p.shape).astype(onp.float32) * 0.1))
        loss = gluon.loss.L2Loss()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9})
        step = tr.fuse_step(lambda xx, yy: loss(net(xx), yy),
                            mesh=mesh, bucket_bytes=512, rules=rules)
        data = onp.random.RandomState(7)
        for _ in range(4):
            x = mx.nd.array(data.rand(8, 12).astype(onp.float32))
            y = mx.nd.array(data.rand(8, 4).astype(onp.float32))
            step(x, y, batch_size=8)
        _, hlo = step.last_program()
        inv = CM.collect_hlo_inventory(hlo or "")
        n_params = sum(int(onp.prod(p.shape))
                       for _, p in net.collect_params().items())
        return {
            "mode": step.last_mode,
            "gspmd": step._gspmd_mode(),
            "matched_step_shardings": step.matched_step_shardings(),
            "all_reduce_bytes": inv["bytes_by_kind"].get(
                "all-reduce", 0),
            "analytic_bytes": 4 * n_params,
            "unresolved_loops": inv["unresolved_loops"],
        }

    configs = {
        "dp8_manual": _step_bytes(create_mesh(devices=jax.devices()[:8])),
        "dp4_tp2": _step_bytes(create_mesh(dp=4, tp=2)),
        "dp2_tp2_sp2": _step_bytes(create_mesh(dp=2, tp=2, sp=2)),
    }
    wire_ok = True
    for name, c in configs.items():
        err = abs(c["all_reduce_bytes"] - c["analytic_bytes"]) \
            / max(1, c["analytic_bytes"])
        c["wire_error"] = round(err, 4)
        wire_ok &= (err < 0.01 and c["mode"] == "fused"
                    and c["unresolved_loops"] == 0)
        if c["gspmd"]:
            wire_ok &= c["matched_step_shardings"] is True

    # -- 2. chunk-count invariance of the fused-loss wire --------------
    import jax.numpy as jnp
    import jax.random as jr
    from mxnet_tpu.parallel import transformer as T

    V, D = 512, 128
    ar_by_chunks = {}
    for chunks in (2, 4):
        cfg = T.TransformerConfig(
            vocab_size=V, dim=D, n_layers=2, n_heads=4, ffn_hidden=4 * D,
            attn_mode="local", loss_chunks=chunks)
        mesh = create_mesh(devices=jax.devices()[:8])
        init_fn, step_fn = T.make_train_step(cfg, mesh)
        with mesh.mesh:
            state = init_fn(jr.PRNGKey(0))
            toks = jnp.zeros((16, 64), jnp.int32)
            compiled = step_fn.lower(state, toks, toks).compile()
        inv = CM.collect_hlo_inventory(compiled)
        ar_by_chunks[chunks] = inv["bytes_by_kind"].get("all-reduce", 0)
    chunks_invariant = ar_by_chunks[2] == ar_by_chunks[4]

    return {
        "metric": "gspmd_step",
        "configs": configs,
        "ce_ar_bytes_chunks2": ar_by_chunks[2],
        "ce_ar_bytes_chunks4": ar_by_chunks[4],
        "ce_chunk_invariant": chunks_invariant,
        "gate": bool(wire_ok and chunks_invariant),
    }


def bench_hlolint():
    """BENCH_MODEL=hlolint: the ISSUE 18 compiled-program contract gate.

    Captures the standing three-mesh fused-step programs (dp8 manual,
    dp4×tp2, dp2×tp2×sp2 — the bench_gspmd_step configs, first one
    lowered twice so H005 checks a real re-lowering group) and runs
    every HLO contract rule (H001 donation-took, H002 collective
    inventory vs the analytic plan, H003 replicated outputs, H004 dtype
    discipline, H005 collective-order determinism). Gate: ZERO findings
    with an EMPTY baseline, and analysis stays under 5 s per signature
    — the contracts hold on real programs, cheaply enough to run on
    every compile.
    """
    from tools.hlolint import capture as HC, core as HL

    artifacts = HC.dryrun_programs(repeat_first=True)
    baseline = HL.load_baseline()
    findings, n_baselined, per_sig = HL.run(artifacts, baseline=baseline)
    rep = HL.report(artifacts, findings, n_baselined, per_sig)
    gate = bool(artifacts) and not findings and not baseline \
        and rep["max_sig_seconds"] < 5.0
    return {
        "metric": "hlolint",
        "n_programs": len(artifacts),
        "n_signatures": len(per_sig),
        "programs": rep["programs"],
        "findings": rep["findings"],
        "baseline_entries": len(baseline),
        "max_sig_seconds": rep["max_sig_seconds"],
        "per_sig_seconds": rep["per_sig_seconds"],
        "gate": gate,
    }


def bench_numerics():
    """BENCH_NUMERICS=1: device-vs-CPU-golden op sweep + flash kernel
    check (benchmark/tpu_numerics.py; VERDICT r3 item 8). The full
    per-op max-ulp table is embedded in the bench JSON on purpose —
    that's the recorded artifact the sweep exists to produce — plus
    summary fields (worst op, matmul family) for quick reading."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "benchmark"))
    import tpu_numerics
    full = tpu_numerics.run_with_cpu_golden()
    matmul = {k: v["max_ulp"] for k, v in full["per_op"].items()
              if k in ("dot", "Convolution", "FullyConnected",
                       "linalg_gemm2", "dot_precision_highest",
                       "dot_policy_float32")}
    worst_nonmatmul = max(
        ((k, v["max_ulp"]) for k, v in full["per_op"].items()
         if k not in matmul), key=lambda kv: kv[1])
    return {
        "n_ops": full["n_ops"],
        "worst_op": full["worst_op"],
        "worst_ulp": full["worst_ulp"],
        "worst_nonmatmul_op": worst_nonmatmul[0],
        "worst_nonmatmul_ulp": worst_nonmatmul[1],
        "matmul_family_ulp": matmul,
        "model_resnet18_max_abs": full.get("model_resnet18_max_abs"),
        "model_resnet18_rel_err": full.get("model_resnet18_rel_err"),
        "flash_fwd_rel_err": full["flash_fwd_rel_err"],
        "flash_bwd_max_abs_err": full["flash_bwd_max_abs_err"],
        "pallas_active": full["pallas_active"],
        "gate": full["gate"],
        "per_op": full["per_op"],
    }


def bench_zero_badput():
    """BENCH_MODEL=zero_badput: the three zero-badput legs (ISSUE 19),
    measured on goodput manifests and gated through `goodput_report
    --compare` exit codes.

    A. **Async checkpoints** — two fault-free elastic runs at EQUAL
       cadence with a 60ms durable-write stall injected into BOTH
       halves (``checkpoint.save=delay:60ms`` models slow durable
       storage; raw tmpfs writes would hide the contrast): the async
       twin's blocking ``checkpoint`` seconds must be < 20% of the
       sync baseline's, its goodput floor must clear 0.95 (the PR 14
       chaos-pair control re-run with checkpointing ON), and compare
       must call the direction — sync->async exits 0 (an improvement
       is not a regression), async->sync exits 1 (the sync run's
       checkpoint badput IS one).
    B. **Persistent AOT compile cache** — a cold/warm subprocess pair
       sharing MXTPU_COMPILE_CACHE_DIR runs the same fixed-seed
       mini-trainer: the warm child must hit the cache (hits > 0
       after the cold child stored), its dispatch step must collapse
       below half the cold child's, and its trained params must be
       BITWISE identical to the cold child's — the deserialized
       executable is the same XLA program, not a retrace.
    C. **Restore-from-peer** — the PR 14 rank-death chaos pair re-run
       twice with a 300ms restore stall (``elastic.restore=
       delay:300ms`` models the durable read): the filesystem run
       rewinds to the last save_every multiple and replays; the peer
       run (a real AsyncPSServer snapshot table, a DP-identical twin
       publishing every completed step) restores the newest step over
       the wire with zero replay. Peer recovery+rewind must drop
       below half the filesystem run's, compare must call the
       direction, and BOTH faulted runs' final state must equal the
       unfaulted twin's bitwise."""
    import subprocess
    import tempfile
    import jax.numpy as jnp
    from mxnet_tpu import kvstore_async as KA
    from mxnet_tpu import profiler
    from mxnet_tpu._debug import faultpoint, goodput, watchdog
    from mxnet_tpu.parallel.elastic import (
        CheckpointManager, ElasticController, elastic_train_loop,
        publish_peer_snapshot)
    from tools import goodput_report

    profiler.set_config(
        filename=os.path.join(tempfile.mkdtemp(), "profile.json"),
        xprof=False)
    # all manifests (A, B's children, C) land in a scratch runs dir;
    # the operator's MXTPU_RUNS_DIR (where the __main__ trajectory
    # manifest lands) is restored before returning
    saved_env = {k: os.environ.get(k) for k in (
        "MXTPU_RUNS_DIR", "MXTPU_CKPT_ASYNC", "MXTPU_CKPT_DELTA",
        "MXTPU_PEER_RESTORE", "MXTPU_PS_SECRET",
        "MXTPU_COMPILE_CACHE_DIR")}
    runs_dir = tempfile.mkdtemp(prefix="bench_zb_runs_")
    work = tempfile.mkdtemp(prefix="bench_zb_")
    os.environ["MXTPU_RUNS_DIR"] = runs_dir
    for k in ("MXTPU_CKPT_ASYNC", "MXTPU_CKPT_DELTA",
              "MXTPU_PEER_RESTORE", "MXTPU_COMPILE_CACHE_DIR"):
        os.environ.pop(k, None)
    goodput.reset()
    watchdog.reset()

    sleep_s = 0.05
    batches = [jnp.asarray(float(i)) for i in range(10)]

    def zb_step(state, b):
        time.sleep(sleep_s)
        return {"acc": state["acc"] + b}, None

    def run_dir_of(manifest):
        return os.path.dirname(goodput.manifest_path(
            manifest["run_id"]))

    try:
        # -- A. async vs sync checkpoints, equal cadence ------------------
        faultpoint.configure("checkpoint.save=delay:60ms")
        try:
            sync_state = async_state = None
            ck = CheckpointManager(os.path.join(work, "ck_sync"),
                                   use_orbax=False, async_persist=False,
                                   delta=False)
            sync_state, _, done = elastic_train_loop(
                zb_step, {"acc": jnp.asarray(0.0)}, batches, ck,
                save_every=2)
            assert done
            m_sync = goodput.last_manifest()
            ck = CheckpointManager(os.path.join(work, "ck_async"),
                                   use_orbax=False, async_persist=True,
                                   delta=False)
            async_state, _, done = elastic_train_loop(
                zb_step, {"acc": jnp.asarray(0.0)}, batches, ck,
                save_every=2)
            assert done
            m_async = goodput.last_manifest()
        finally:
            faultpoint.reset()
        sync_ckpt_s = m_sync["categories_s"]["checkpoint"]
        async_ckpt_s = m_async["categories_s"]["checkpoint"]
        ckpt_ratio = async_ckpt_s / sync_ckpt_s if sync_ckpt_s else 0.0
        ca = m_async["categories_s"]
        goodput_floor = (ca["compute"] + ca["input_wait"]) / max(
            1e-9, m_async["wall_s"] - ca["compile"])
        cmp_sync_to_async = goodput_report.main(
            ["--compare", run_dir_of(m_sync), run_dir_of(m_async)])
        cmp_async_to_sync = goodput_report.main(
            ["--compare", run_dir_of(m_async), run_dir_of(m_sync)])
        unfaulted_acc = float(async_state["acc"])

        # -- B. cold/warm compile-cache subprocess pair -------------------
        cache_dir = os.path.join(work, "compile_cache")
        child_src = """
import json, sys, time
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, profiler
from mxnet_tpu._debug import goodput
from mxnet_tpu.gluon import compile_cache as cc

net = gluon.nn.HybridSequential()
with net.name_scope():
    net.add(gluon.nn.Dense(16, in_units=8, activation="relu"))
    net.add(gluon.nn.Dense(1, in_units=16))
net.initialize(mx.init.Uniform(0.1))
net.hybridize()
rs = np.random.RandomState(0)
for _, p in sorted(net.collect_params().items()):
    p.set_data(mx.nd.array(rs.rand(*p.data().shape).astype("float32")))
tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
lf = gluon.loss.L2Loss()
step = tr.fuse_step(lambda x, y: lf(net(x), y))
x = mx.nd.array(rs.rand(4, 8).astype("float32"))
y = mx.nd.array(rs.rand(4, 1).astype("float32"))
goodput.open_run(run_id=sys.argv[1])
walls = []
for _ in range(6):
    t0 = time.perf_counter()
    step(x, y, batch_size=4)
    walls.append(time.perf_counter() - t0)
m = goodput.close_run()
print(json.dumps({
    "max_wall_s": max(walls), "cc": cc.stats(),
    "compile_s": m["categories_s"]["compile"],
    "dispatch_us": profiler.metrics()["compile"]["fused_step"]["last_us"],
    "wsum": repr(float(sum(abs(p.data().asnumpy()).sum()
                           for _, p in sorted(
                               net.collect_params().items())))),
}))
"""
        env = dict(os.environ)
        env["MXTPU_COMPILE_CACHE_DIR"] = cache_dir
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.abspath(__file__))]
            + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))

        def child(rid):
            out = subprocess.run(
                [sys.executable, "-c", child_src, rid], env=env,
                capture_output=True, text=True, timeout=600)
            if out.returncode != 0:
                raise RuntimeError("zero_badput child %s failed: %s"
                                   % (rid, out.stderr[-2000:]))
            return json.loads(out.stdout.strip().splitlines()[-1])

        cold = child("zb_cc_cold")
        warm = child("zb_cc_warm")
        # dispatch_us is the fused dispatch's own trace+compile(+first
        # run) wall from the compile registry — the cache's target. The
        # raw max step wall is reported but NOT gated: it is dominated
        # by first-call backend init, identical in both children.
        dispatch_ratio = warm["dispatch_us"] / cold["dispatch_us"]
        cmp_cold_to_warm = goodput_report.main(
            ["--compare",
             os.path.dirname(goodput.manifest_path("zb_cc_cold")),
             os.path.dirname(goodput.manifest_path("zb_cc_warm"))])

        # -- C. rank-death chaos pair: filesystem vs peer restore ---------
        os.environ["MXTPU_PS_SECRET"] = "bench-zb-secret"

        class _ZbKV:
            """Dead-table fake in the PR 14 chaos idiom."""

            def __init__(self, nworkers=2):
                self.dead = []
                self.num_workers = nworkers
                self.resized = []

            def dead_nodes(self, timeout=3.0):
                return list(self.dead)

            def resize(self, n):
                self.resized.append(int(n))
                self.num_workers = int(n)

        class _ZbPeerKV(_ZbKV):
            """Same dead table, but the snapshot plane is the REAL v1
            wire: opcodes 18/19 against a live AsyncPSServer."""

            def __init__(self, client, rank, nworkers=2):
                _ZbKV.__init__(self, nworkers)
                self._client = client
                self._rank = int(rank)

            def publish_snapshot(self, step, blob):
                self._client.put_snapshot(self._rank, step, blob)

            def peer_snapshot(self, stale_timeout=None):
                return self._client.get_snapshot(self._rank,
                                                 stale_timeout)

        def chaos_run(kv, publish=None):
            """Death at batch 7 first time through; save_every=4 so the
            filesystem path rewinds to 4 and replays 5 and 6."""
            fired = []

            def step(state, b):
                i = int(b)
                if i == 7 and not fired:
                    fired.append(1)
                    kv.dead = [1]
                    raise ConnectionError("collective failed: peer gone")
                ns, met = zb_step(state, b)
                if publish is not None:
                    publish(i, ns)
                return ns, met

            ctl = ElasticController(kvstore=kv, world=range(2), rank=0,
                                    poll_interval=0.0)
            ck = CheckpointManager(
                tempfile.mkdtemp(dir=work, prefix="ck_chaos_"),
                use_orbax=False, async_persist=True, delta=False)
            state, _, done = elastic_train_loop(
                step, {"acc": jnp.asarray(0.0)}, batches, ck,
                save_every=4, max_failures=0, controller=ctl)
            assert done
            m = goodput.last_manifest()
            rec = [e for e in m["events"]
                   if e["kind"] == "recovery"][-1]
            return state, m, rec

        faultpoint.configure("elastic.restore=delay:300ms")
        srv = KA.AsyncPSServer()
        try:
            file_state, m_file, rec_file = chaos_run(_ZbKV())

            os.environ["MXTPU_PEER_RESTORE"] = "1"
            cli0 = KA.AsyncPSClient("127.0.0.1", srv.port)
            cli1 = KA.AsyncPSClient("127.0.0.1", srv.port)
            twin = _ZbPeerKV(cli1, rank=1)

            def twin_publish(i, ns):
                # the DP-identical peer: same post-step state, its own
                # rank's slot, a fresh heartbeat so the liveness filter
                # keeps serving its snapshot
                cli1.heartbeat(1)
                publish_peer_snapshot(twin, i, ns)

            peer_state, m_peer, rec_peer = chaos_run(
                _ZbPeerKV(cli0, rank=0), publish=twin_publish)
        finally:
            srv.stop()
            faultpoint.reset()
            os.environ.pop("MXTPU_PEER_RESTORE", None)
        file_rec_s = (m_file["categories_s"]["recovery"]
                      + m_file["categories_s"]["rewind_replay"])
        peer_rec_s = (m_peer["categories_s"]["recovery"]
                      + m_peer["categories_s"]["rewind_replay"])
        cmp_file_to_peer = goodput_report.main(
            ["--compare", run_dir_of(m_file), run_dir_of(m_peer)])
        cmp_peer_to_file = goodput_report.main(
            ["--compare", run_dir_of(m_peer), run_dir_of(m_file)])
    finally:
        watchdog.reset()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    bitwise = (float(file_state["acc"]) == unfaulted_acc
               and float(peer_state["acc"]) == unfaulted_acc
               and warm["wsum"] == cold["wsum"])
    gate_ok = bool(
        ckpt_ratio < 0.2 and goodput_floor >= 0.95
        and cmp_sync_to_async == 0 and cmp_async_to_sync == 1
        and warm["cc"]["hits"] > 0 and cold["cc"]["stores"] > 0
        and dispatch_ratio < 0.5 and cmp_cold_to_warm == 0
        and peer_rec_s < 0.5 * file_rec_s
        and cmp_file_to_peer == 0 and cmp_peer_to_file == 1
        and rec_peer["recovery_kind"] == "peer"
        and rec_peer["restored_step"] == 6
        and rec_peer["replay_span"] == 0
        and rec_file["restored_step"] == 4 and bitwise)
    return {
        "metric": "zero_badput",
        "value": round(ckpt_ratio, 4),
        "unit": "ratio",
        "sync_checkpoint_s": round(sync_ckpt_s, 4),
        "async_checkpoint_s": round(async_ckpt_s, 4),
        "async_persist_s": round(
            m_async["counters"]["checkpoint_persist_s"], 4),
        "checkpoint_ratio": round(ckpt_ratio, 4),
        "goodput_floor": round(goodput_floor, 4),
        "compile_cold": {"max_wall_s": round(cold["max_wall_s"], 4),
                         "compile_s": round(cold["compile_s"], 4),
                         "dispatch_us": round(cold["dispatch_us"], 1),
                         "cc": cold["cc"]},
        "compile_warm": {"max_wall_s": round(warm["max_wall_s"], 4),
                         "compile_s": round(warm["compile_s"], 4),
                         "dispatch_us": round(warm["dispatch_us"], 1),
                         "cc": warm["cc"]},
        "dispatch_ratio": round(dispatch_ratio, 4),
        "file_recovery_s": round(file_rec_s, 4),
        "peer_recovery_s": round(peer_rec_s, 4),
        "file_restored_step": rec_file["restored_step"],
        "peer_restored_step": rec_peer["restored_step"],
        "peer_replay_span": rec_peer["replay_span"],
        "bitwise_identical": bitwise,
        "compare_exits": {
            "sync_to_async": cmp_sync_to_async,
            "async_to_sync": cmp_async_to_sync,
            "cold_to_warm": cmp_cold_to_warm,
            "file_to_peer": cmp_file_to_peer,
            "peer_to_file": cmp_peer_to_file,
        },
        "gate": {
            "ok": gate_ok,
            "max_checkpoint_ratio": 0.2,
            "min_goodput_floor": 0.95,
            "max_dispatch_ratio": 0.5,
            "max_peer_recovery_ratio": 0.5,
        },
    }


def bench_control_plane():
    """BENCH_MODEL=control_plane: control-plane survivability (ISSUE 20),
    the three legs of the kvstore failover + preemption story.

    A. **Journaled failover** — a journaling AsyncPSServer takes real
       init/push traffic and dies abruptly (no clean stop, so recovery
       is journal replay, not the compaction snapshot); a standby
       replays the journal on a reserved port and the client walks its
       `MXTPU_PS_ENDPOINTS`-style failover list inside the ordinary
       `_call` retry budget. Gates: the kill→successful-pull window
       must be ≤ 0.25x the heartbeat dead-timeout (failover must beat
       the detector that exists to notice dead SERVERS' clients), the
       replayed value must be bitwise what the dead primary served,
       and at least one `kvstore.failovers.*` counter must tick.
    B. **Partition chaos** — an elastic run whose step drives real
       push/pull wire traffic under `net.delay` on-the-wire chaos,
       plus one induced rank-death recovery, against a fault-free
       twin: final state bitwise identical, goodput floor >= 0.95 on
       the CHAOS manifest (the delays land in-step as compute; the
       recovery is the only badput), and `goodput_report --compare`
       must call the direction both ways (clean->chaos regresses on
       the slowed median step; chaos->clean does not).
    C. **Coordinated preemption** — SIGTERM lands mid-run under an
       `MXTPU_PREEMPT_GRACE_S` budget: the run must announce
       (controller acked), checkpoint the in-flight step, and close
       `outcome=preempted`; the resumed incarnation must book its
       resume recovery with **replay_span 0** (the preemption save IS
       the newest step) and finish bitwise equal to an uninterrupted
       twin; the `preempt_notice` opcode must make the announced rank
       visible in a real server's dead-node reply immediately."""
    import signal as _signal
    import socket as _socket
    import tempfile
    import numpy as np
    import jax.numpy as jnp
    from mxnet_tpu import kvstore_async as KA
    from mxnet_tpu import profiler
    from mxnet_tpu._debug import faultpoint, goodput, watchdog
    from mxnet_tpu.parallel.elastic import (
        CheckpointManager, ElasticController, elastic_train_loop)
    from tools import goodput_report

    profiler.set_config(
        filename=os.path.join(tempfile.mkdtemp(), "profile.json"),
        xprof=False)
    saved_env = {k: os.environ.get(k) for k in (
        "MXTPU_RUNS_DIR", "MXTPU_PS_SECRET", "MXTPU_PS_JOURNAL_DIR",
        "MXTPU_PS_ENDPOINTS", "MXTPU_PS_FENCING",
        "MXTPU_PS_RECV_TIMEOUT", "MXTPU_PREEMPT_GRACE_S")}
    runs_dir = tempfile.mkdtemp(prefix="bench_cp_runs_")
    work = tempfile.mkdtemp(prefix="bench_cp_")
    os.environ["MXTPU_RUNS_DIR"] = runs_dir
    for k in ("MXTPU_PS_JOURNAL_DIR", "MXTPU_PS_ENDPOINTS",
              "MXTPU_PS_FENCING", "MXTPU_PS_RECV_TIMEOUT",
              "MXTPU_PREEMPT_GRACE_S"):
        os.environ.pop(k, None)
    os.environ["MXTPU_PS_SECRET"] = "bench-cp-secret"
    goodput.reset()
    watchdog.reset()

    dead_timeout = float(os.environ.get("MXTPU_PS_DEAD_TIMEOUT", "3.0"))
    sleep_s = 0.05

    def run_dir_of(manifest):
        return os.path.dirname(goodput.manifest_path(
            manifest["run_id"]))

    class _CpKV:
        """Dead-table fake in the PR 14 chaos idiom."""

        def __init__(self, nworkers=2):
            self.dead = []
            self.num_workers = nworkers
            self.resized = []

        def dead_nodes(self, timeout=3.0):
            return list(self.dead)

        def resize(self, n):
            self.resized.append(int(n))
            self.num_workers = int(n)

    try:
        # -- A. journaled failover ----------------------------------------
        journal = os.path.join(work, "journal")
        srv1 = KA.AsyncPSServer(journal_dir=journal)
        rsv = _socket.socket()
        rsv.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        rsv.bind(("127.0.0.1", 0))
        standby_port = rsv.getsockname()[1]
        cli = KA.AsyncPSClient(
            "127.0.0.1", srv1.port,
            endpoints=[("127.0.0.1", srv1.port),
                       ("127.0.0.1", standby_port)])
        cli.init("w", np.arange(8, dtype=np.float32))
        for _ in range(5):
            cli.push("w", np.ones(8, dtype=np.float32))
        before = np.asarray(cli.pull("w"))
        fo_base = {k: v for k, v in
                   profiler.metrics()["counters"].items()
                   if k.startswith("kvstore.failovers.")}
        # abrupt death: listener closed, accept loop stopped, the
        # client's established socket reset — never a clean stop(), so
        # the standby's state is journal replay, not the snapshot
        srv1._stop.set()
        srv1._srv.close()
        cli._sock.close()
        rsv.close()
        t0 = time.perf_counter()
        srv2 = KA.AsyncPSServer(port=standby_port, journal_dir=journal)
        after = np.asarray(cli.pull("w"))
        failover_s = time.perf_counter() - t0
        fo_now = {k: v for k, v in
                  profiler.metrics()["counters"].items()
                  if k.startswith("kvstore.failovers.")}
        failovers = sum(fo_now.values()) - sum(fo_base.values())
        replay_bitwise = bool(np.array_equal(before, after))
        journal_replayed = srv2.journal_replayed
        cli.stop_server()

        # -- B. partition chaos vs clean twin -----------------------------
        batches = [jnp.asarray(float(i)) for i in range(30)]
        srv_b = KA.AsyncPSServer()
        cli_b = KA.AsyncPSClient("127.0.0.1", srv_b.port)
        cli_b.init("s", np.zeros(4, dtype=np.float32))

        def wire_step(state, b):
            # real on-the-wire traffic every step: the net.delay chaos
            # lands inside these round trips (in-step => compute)
            cli_b.push("s", np.full(4, float(b), dtype=np.float32))
            cli_b.pull("s")
            time.sleep(sleep_s)
            return {"acc": state["acc"] + b}, None

        def elastic_run(chaos):
            fired = []

            def step(state, b):
                i = int(b)
                if chaos and i == 7 and not fired:
                    fired.append(1)
                    kv.dead = [1]
                    raise ConnectionError(
                        "collective failed: peer gone")
                return wire_step(state, b)

            kv = _CpKV()
            ctl = ElasticController(kvstore=kv, world=range(2), rank=0,
                                    poll_interval=0.0)
            ck = CheckpointManager(
                tempfile.mkdtemp(dir=work, prefix="ck_b_"),
                use_orbax=False, async_persist=False, delta=False)
            state, _, done = elastic_train_loop(
                step, {"acc": jnp.asarray(0.0)}, batches, ck,
                save_every=2, max_failures=0, controller=ctl)
            assert done
            return state, goodput.last_manifest()

        clean_state, m_clean = elastic_run(chaos=False)
        faultpoint.configure("net.delay=delay:5ms")
        try:
            chaos_state, m_chaos = elastic_run(chaos=True)
        finally:
            faultpoint.reset()
        cli_b.stop_server()
        cc = m_chaos["categories_s"]
        goodput_floor = (cc["compute"] + cc["input_wait"]) / max(
            1e-9, m_chaos["wall_s"] - cc["compile"])
        cmp_clean_to_chaos = goodput_report.main(
            ["--compare", run_dir_of(m_clean), run_dir_of(m_chaos)])
        cmp_chaos_to_clean = goodput_report.main(
            ["--compare", run_dir_of(m_chaos), run_dir_of(m_clean)])
        chaos_bitwise = float(chaos_state["acc"]) \
            == float(clean_state["acc"])

        # -- C. coordinated preemption + resume ---------------------------
        os.environ["MXTPU_PREEMPT_GRACE_S"] = "30"
        pre_batches = [jnp.asarray(float(i)) for i in range(10)]
        ck_dir = os.path.join(work, "ck_preempt")

        class _CpPreKV(_CpKV):
            def __init__(self):
                _CpKV.__init__(self)
                self.announced = []

            def announce_preemption(self, step):
                self.announced.append(int(step))
                return 1

        def pre_step(state, b):
            i = int(b)
            if i == 5:
                _signal.raise_signal(_signal.SIGTERM)
            time.sleep(sleep_s)
            return {"acc": state["acc"] + b}, None

        pre_kv = _CpPreKV()
        ctl = ElasticController(kvstore=pre_kv, world=range(2), rank=0,
                                poll_interval=0.0)
        ck = CheckpointManager(ck_dir, use_orbax=False,
                               async_persist=True, delta=False)
        _, pre_last, pre_done = elastic_train_loop(
            pre_step, {"acc": jnp.asarray(0.0)}, pre_batches, ck,
            save_every=4, max_failures=0, controller=ctl)
        m_pre = goodput.last_manifest()
        os.environ.pop("MXTPU_PREEMPT_GRACE_S", None)

        def plain_step(state, b):
            time.sleep(sleep_s)
            return {"acc": state["acc"] + b}, None

        ck = CheckpointManager(ck_dir, use_orbax=False,
                               async_persist=True, delta=False)
        res_state, _, res_done = elastic_train_loop(
            plain_step, {"acc": jnp.asarray(0.0)}, pre_batches, ck,
            save_every=4, max_failures=0)
        assert res_done
        m_res = goodput.last_manifest()
        resume_rec = [e for e in m_res["events"]
                      if e["kind"] == "recovery"][-1]

        ck = CheckpointManager(os.path.join(work, "ck_twin"),
                               use_orbax=False, async_persist=True,
                               delta=False)
        twin_state, _, twin_done = elastic_train_loop(
            plain_step, {"acc": jnp.asarray(0.0)}, pre_batches, ck,
            save_every=4, max_failures=0)
        assert twin_done
        preempt_bitwise = float(res_state["acc"]) \
            == float(twin_state["acc"])

        # the wire half of the notice: a real server's dead-node reply
        # includes an announced rank immediately, no heartbeat timeout
        srv_c = KA.AsyncPSServer()
        cli_c = KA.AsyncPSClient("127.0.0.1", srv_c.port)
        cli_c.preempt_notice(3, pre_last)
        notice_visible = 3 in cli_c.dead_nodes(timeout=dead_timeout)
        cli_c.stop_server()
    finally:
        watchdog.reset()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    gate_ok = bool(
        failover_s <= 0.25 * dead_timeout
        and failovers >= 1 and replay_bitwise and journal_replayed > 0
        and chaos_bitwise and goodput_floor >= 0.95
        and cmp_clean_to_chaos == 1 and cmp_chaos_to_clean == 0
        and m_pre["outcome"] == "preempted" and not pre_done
        and pre_last == 5 and pre_kv.announced == [5]
        and resume_rec["recovery_kind"] == "resume"
        and resume_rec["restored_step"] == 5
        and resume_rec["replay_span"] == 0
        and preempt_bitwise and notice_visible)
    return {
        "metric": "control_plane",
        "value": round(failover_s, 4),
        "unit": "s",
        "failover_s": round(failover_s, 4),
        "failover_budget_s": round(0.25 * dead_timeout, 4),
        "failovers": failovers,
        "journal_replayed": journal_replayed,
        "replay_bitwise": replay_bitwise,
        "goodput_floor": round(goodput_floor, 4),
        "chaos_bitwise": chaos_bitwise,
        "preempted_outcome": m_pre["outcome"],
        "preempt_step": pre_last,
        "preempt_acked": pre_kv.announced,
        "resume_restored_step": resume_rec["restored_step"],
        "resume_replay_span": resume_rec["replay_span"],
        "preempt_bitwise": preempt_bitwise,
        "notice_visible": notice_visible,
        "compare_exits": {
            "clean_to_chaos": cmp_clean_to_chaos,
            "chaos_to_clean": cmp_chaos_to_clean,
        },
        "gate": {
            "ok": gate_ok,
            "max_failover_ratio": 0.25,
            "min_goodput_floor": 0.95,
            "required_replay_span": 0,
        },
    }


if __name__ == "__main__":
    which = os.environ.get("BENCH_MODEL", "both")
    if which == "transformer":
        result = bench_transformer()
    elif which == "resnet50":
        result = bench_resnet()
    elif which == "resnet50_infer":
        result = bench_resnet_inference()
    elif which == "eager_ops":
        result = bench_eager_ops()
    elif which == "train_step":
        result = bench_train_step()
    elif which == "profiler_overhead":
        result = bench_profiler_overhead()
    elif which == "flightrec_overhead":
        result = bench_flightrec_overhead()
    elif which == "memory_overhead":
        result = bench_memory_overhead()
    elif which == "goodput_overhead":
        result = bench_goodput_overhead()
    elif which == "health_overhead":
        result = bench_health_overhead()
    elif which == "comm_overlap":
        result = bench_comm_overlap()
    elif which == "fused_kernels":
        result = bench_fused_kernels()
    elif which == "input_pipeline":
        result = bench_input_pipeline_gate()
    elif which == "gspmd_step":
        result = bench_gspmd_step()
    elif which == "hlolint":
        result = bench_hlolint()
    elif which == "perf_attrib":
        result = bench_perf_attrib()
    elif which == "zero_badput":
        result = bench_zero_badput()
    elif which == "control_plane":
        result = bench_control_plane()
    else:
        def _section(fn):
            # retry ONLY transient remote-attach channel drops — a
            # deterministic failure (e.g. HBM OOM) must not re-run a
            # minutes-long sub-bench; either way the headline survives
            try:
                return fn()
            except Exception as e:  # noqa: BLE001
                msg = str(e)
                if not ("remote_compile" in msg or "response body" in msg):
                    return {"error": msg[:200]}
            try:
                out = fn()
                out["retried_after"] = msg[:120]
                return out
            except Exception as e:  # noqa: BLE001
                return {"error": str(e)[:200], "attempts": 2}

        result = bench_resnet()
        result["inference"] = _section(bench_resnet_inference)
        result["transformer"] = _section(bench_transformer)
        result["eager_ops"] = _section(bench_eager_ops)
    # honored for every BENCH_MODEL, not just the default combined run.
    # Defaults ON for real-device runs: the recorded BENCH_r*.json is
    # the artifact the on-TPU numerics sweep exists to produce
    # (VERDICT r3 item 8); CPU runs skip it (golden == check there).
    numerics_default = "0"
    try:
        import jax
        numerics_default = "1" if jax.devices()[0].platform == "tpu" \
            else "0"
    except Exception:
        pass
    if os.environ.get("BENCH_NUMERICS", numerics_default) == "1":
        try:
            result["numerics"] = bench_numerics()
        except Exception as e:  # noqa: BLE001
            result["numerics"] = {"error": str(e)[:400]}
    # every gate result doubles as a goodput-run manifest under
    # MXTPU_RUNS_DIR (same schema as training runs), so
    # `tools/goodput_report.py --compare` tracks the bench trajectory
    # across rounds (ISSUE 14). Written BEFORE the gate exits below —
    # a breached gate is exactly the round the trajectory must record.
    try:
        from mxnet_tpu._debug import goodput as _goodput_manifest
        result["run_manifest"] = _goodput_manifest.write_bench_manifest(
            which, result)
    except Exception as e:  # noqa: BLE001 (the bench record survives)
        result["run_manifest"] = None
        result["run_manifest_error"] = str(e)[:200]
    print(json.dumps(result))
    if result.get("metric") == "profiler_off_overhead_pct" \
            and not result["gate"]["ok"]:
        # telemetry must never silently tax training: either the
        # profiling-off dispatch guard blew its <2% budget, the wire
        # trace-context costs >0.5% of a pull RTT, or a profiling-off
        # request carried context bytes — fail AFTER the JSON record
        wc = result["wire_ctx"]
        sys.exit("profiler overhead gate breached: off-path %.3f%% "
                 "(budget %.1f%%), wire-ctx %.4f%% of RTT (budget "
                 "%.1f%%), off-path stamped frames %d (must be 0)"
                 % (result["value"], result["gate"]["budget_pct"],
                    wc["added_rtt_pct"], result["gate"]["wire_budget_pct"],
                    wc["off_path_stamped_frames"]))
    if result.get("metric") == "flightrec_overhead_pct" \
            and not result["gate"]["ok"]:
        # the always-on black box must stay effectively free: the ring
        # may cost at most 0.5% of an eager dispatch and 0.1% of a
        # fused step — and it must actually have recorded the benched
        # ops (a disabled recorder pricing at zero would be a lie)
        sys.exit("flightrec overhead gate breached: eager %.4f%% "
                 "(budget %.1f%%), fused-step %.4f%% (budget %.1f%%), "
                 "ring_recorded=%s"
                 % (result["eager_pct"],
                    result["gate"]["eager_budget_pct"],
                    result["fused_pct"],
                    result["gate"]["fused_budget_pct"],
                    result["ring_recorded_benched_ops"]))
    if result.get("metric") == "memory_overhead_pct" \
            and not result["gate"]["ok"]:
        # the always-on allocation ledger must stay effectively free
        # (<0.5% of eager dispatch for the add/retire pair, <0.5% of a
        # fused step for the adoption registrations), it must actually
        # have recorded the benched ops, and the synthetic leak must
        # trip the memwatch detector exactly once with exactly one dump
        sys.exit("memory overhead gate breached: eager %.4f%% "
                 "(budget %.1f%%), fused-step %.4f%% (budget %.1f%%), "
                 "ledger_recorded=%s, leak_watchdog=%s"
                 % (result["eager_pct"],
                    result["gate"]["eager_budget_pct"],
                    result["fused_pct"],
                    result["gate"]["fused_budget_pct"],
                    result["ledger_recorded_benched_ops"],
                    result["leak_watchdog"]))
    if result.get("metric") == "goodput_overhead_pct" \
            and not result["gate"]["ok"]:
        # the run-level goodput recorder must stay drain-time-cheap:
        # the per-step note pair may cost at most 0.1% of a fused step,
        # and it must actually have classified the benched mini run
        # (zero recorded compute would price a disabled recorder)
        sys.exit("goodput overhead gate breached: fused-step %.4f%% "
                 "(budget %.1f%%), ledger_recorded=%s"
                 % (result["fused_pct"],
                    result["gate"]["fused_budget_pct"],
                    result["ledger_recorded_benched_steps"]))
    if result.get("metric") == "perf_attrib" \
            and not result["gate"]["ok"]:
        # the attribution plane must stay beacon-cheap (<0.5% of a
        # fused step for the sig-tagged note), its reported MFU must
        # reconcile with a hand derivation from the compile registry's
        # flops and the ASSUMPTIONS peak table (5%), and the compare
        # CLI must actually gate: clean pair exits 0, 2x slowdown 1
        sys.exit("perf attribution gate breached: note %.4f%% of a "
                 "fused step (budget %.1f%%), joined=%s, MFU err=%s%% "
                 "(tol %.1f%%), manifest_perf=%s, report exits "
                 "render=%s identical=%s 2x_slowdown=%s (want 0/0/1)"
                 % (result["fused_pct"],
                    result["gate"]["fused_budget_pct"],
                    result["joined"], result["mfu_rel_err_pct"],
                    result["gate"]["mfu_tolerance_pct"],
                    result["manifest_has_perf_block"],
                    result["report_exit_render"],
                    result["report_exit_identical"],
                    result["report_exit_2x_slowdown"]))
    if result.get("metric") == "health_overhead_pct" \
            and not result["gate"]["ok"]:
        # the training-health sentinels must stay effectively free on
        # the every-step path (<0.5% of a fused step), must actually
        # have checked the benched steps (a disabled plane pricing at
        # zero would lie), and the full per-layer pass may run ONLY on
        # MXTPU_HEALTH_INTERVAL boundaries, never per step
        sys.exit("health overhead gate breached: sentinel %.4f%% "
                 "(budget %.1f%%), sentinels_ran=%s, "
                 "every-step layer_passes=%d (must be 0), "
                 "interval leg ok=%s"
                 % (result["value"], result["gate"]["budget_pct"],
                    result["sentinels_ran"],
                    result["layer_passes_every_step_leg"],
                    result["interval_leg"]["ok"]))
    if result.get("metric") == "train_step_steps_per_sec" \
            and not result["gate"]["ok"]:
        # the fused step must actually pay for itself AND replay cleanly
        sys.exit("train_step gate breached: speedup %.2fx (need >= %.1fx), "
                 "parity=%s, replay=%s"
                 % (result["speedup"], result["gate"]["min_speedup"],
                    result["bitwise_parity"], result["replay"]))
    if result.get("metric") == "comm_overlap_model" \
            and not result["gate"]["ok"]:
        # the overlap machinery must pay: bucketed reduction strictly
        # shrinks exposed comm, and the local-accum chunked CE strictly
        # shrinks wire bytes vs the SCALING_r05 baseline pattern
        sys.exit("comm_overlap gate breached: ce_bytes_drop=%s "
                 "(baseline=%d local_accum=%d), "
                 "overlap_strictly_reduces_exposed=%s"
                 % (result["gate"]["ce_bytes_drop"],
                    result["chunked_ce"]["allreduce_bytes_baseline"],
                    result["chunked_ce"]["allreduce_bytes_local_accum"],
                    result["gate"]["overlap_strictly_reduces_exposed"]))
    if result.get("metric") == "input_pipeline_plane" \
            and not result["gate"]["ok"]:
        # the data plane must outrun the device 2x clean and 1x under
        # 15% injected decode/read chaos, with the prefetch queue
        # nonzero at full step rate — anything less and the input
        # pipeline, not the TPU, is the training ceiling (ROADMAP 5)
        sys.exit("input_pipeline gate breached: plain %.2fx (need >= "
                 "%.1fx), chaos %.2fx (need >= %.1fx, injected=%s), "
                 "queue-depth nonzero %.0f%% (need >= %.0f%%)"
                 % (result["plain_speedup"],
                    result["gate"]["min_speedup"],
                    result["chaos_speedup"],
                    result["gate"]["min_chaos_speedup"],
                    result["gate"]["chaos_injected"],
                    100 * result["queue_depth_nonzero_frac"],
                    100 * result["gate"]["min_depth_nonzero_frac"]))
    if result.get("metric") == "zero_badput" \
            and not result["gate"]["ok"]:
        # the zero-badput contract (ISSUE 19): async checkpointing
        # hides the durable write (<20% of sync's blocking seconds at
        # equal cadence, goodput floor >=0.95), a warm compile cache
        # collapses the dispatch step with hits counted and bitwise
        # params, peer restore beats the filesystem on recovery+rewind
        # — each proven by the compare CLI's exit codes both ways
        sys.exit("zero_badput gate breached: ckpt ratio %.3f (max "
                 "%.2f), goodput floor %.3f (min %.2f), dispatch "
                 "ratio %.3f (max %.2f, warm hits=%s), peer %.3fs vs "
                 "file %.3fs recovery (restored %s/%s, replay=%s), "
                 "bitwise=%s, compare exits=%s"
                 % (result["checkpoint_ratio"],
                    result["gate"]["max_checkpoint_ratio"],
                    result["goodput_floor"],
                    result["gate"]["min_goodput_floor"],
                    result["dispatch_ratio"],
                    result["gate"]["max_dispatch_ratio"],
                    result["compile_warm"]["cc"]["hits"],
                    result["peer_recovery_s"],
                    result["file_recovery_s"],
                    result["peer_restored_step"],
                    result["file_restored_step"],
                    result["peer_replay_span"],
                    result["bitwise_identical"],
                    result["compare_exits"]))
    if result.get("metric") == "fused_kernels" \
            and not result["gate"]["ok"]:
        # the kernel campaign contract: parity (ULP-bounded BN, bitwise
        # optimizer apply, exact int8 matmul) everywhere, >=1.5x vs the
        # XLA baseline where a real backend is present, and every
        # kernel build visible in the compile-attribution table
        sys.exit("fused_kernels gate breached: %s"
                 % "; ".join(result["gate"]["breaches"]))
    gate = result.get("numerics", {}).get("gate")
    if gate is not None and not gate["ok"]:
        # per-op ULP budget breached (benchmark/tpu_numerics.py
        # ULP_BUDGETS) — fail loudly AFTER printing the JSON record
        sys.exit("numerics ULP gate breached: %s"
                 % "; ".join(gate["breaches"]))
