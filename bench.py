"""Headline benchmark: ResNet-50 training throughput (images/sec/chip).

Matches the reference's own headline (ref: docs perf.md — ResNet-50 training
batch 32: 298.51 img/s on V100 fp32; BASELINE.md). Runs the full Gluon
training step (forward + backward + SGD-momentum update + BN stat updates)
as ONE fused XLA program via ShardedTrainStep on whatever chip is attached.

Prints one JSON line:
  {"metric": "resnet50_train_imgs_per_sec_per_chip", "value": N,
   "unit": "images/sec", "vs_baseline": N / 298.51}
"""
import json
import os
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 298.51  # ref V100 fp32 training, batch 32 (perf.md)


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    import mxnet_tpu.optimizer as opt
    from mxnet_tpu.parallel import create_mesh, data_parallel, \
        ShardedTrainStep

    platform = jax.devices()[0].platform
    batch = int(os.environ.get("BENCH_BATCH",
                               128 if platform != "cpu" else 8))
    dtype = os.environ.get("BENCH_DTYPE",
                           "bfloat16" if platform != "cpu" else "float32")

    net = resnet50_v1()
    net.initialize()
    net(mx.nd.array(np.zeros((1, 3, 224, 224), "float32")))  # deferred init
    if dtype != "float32":
        net.cast(dtype)

    mesh = create_mesh(devices=jax.devices()[:1], dp=1)
    step = ShardedTrainStep(net, SoftmaxCrossEntropyLoss(),
                            opt.create("sgd", learning_rate=0.01,
                                       momentum=0.9),
                            strategy=data_parallel(mesh))

    rng = np.random.RandomState(0)
    x = rng.rand(batch, 3, 224, 224).astype(dtype)
    y = rng.randint(0, 1000, (batch,)).astype("float32")
    xd, yd = step.place_batch(x, y)  # on-device once; input pipeline is
    # benchmarked separately (the reference prefetches via iter_prefetcher.h)

    float(step.step(xd, yd))  # compile + warm
    float(step.step(xd, yd))

    iters = int(os.environ.get("BENCH_ITERS", 20 if platform != "cpu" else 3))
    t0 = time.perf_counter()
    loss = None
    for _ in range(iters):
        loss = step.step(xd, yd)
    loss = float(loss)  # sync once at the end
    dt = time.perf_counter() - t0

    imgs_per_sec = batch * iters / dt
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 4),
        "platform": platform,
        "batch": batch,
        "dtype": dtype,
        "final_loss": round(float(loss), 4),
    }))


if __name__ == "__main__":
    main()
