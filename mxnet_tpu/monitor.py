"""Monitor outputs, weights, and gradients for debugging.

ref: python/mxnet/monitor.py (Monitor :33). The reference installs a C++
monitor callback on executors; here `install` wraps Gluon block forward hooks
and Module executors call `tic/toc` around forward, collecting the same
(batch, name, stat) rows.
"""
from __future__ import annotations

import logging
import re

from .ndarray import NDArray
from . import ndarray as nd

__all__ = ["Monitor"]


def _is_traced(array):
    """True when ``array`` is (or wraps) a jax tracer — forward hooks
    DO fire while a hybridized block's cached program is being traced,
    and a stat captured there is an abstract value that would blow up
    at ``toc()`` render time (and silently never update again: the
    cached program replays without Python). Such hook hits are dropped;
    the fused-step health plane (``_debug/healthmon``) is the supported
    per-layer stat route for cached programs."""
    import jax
    data = array._data if isinstance(array, NDArray) else array
    return isinstance(data, jax.core.Tracer)


class Monitor:
    """Collect per-tensor stats every `interval` batches (ref: monitor.py:33)."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                return x.abs().mean()
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self._bypass_warned = False  # hybridized-hook bypass, once

    def stat_helper(self, name, array):
        if not self.activated or not self.re_prog.match(name):
            return
        if _is_traced(array):
            return  # hook fired inside a trace: abstract value, no stat
        array = array if isinstance(array, NDArray) else nd.array(array)
        self.queue.append((self.step, name, self.stat_func(array)))

    def install(self, exe, strict=False):
        """Install the monitor on an executor or Gluon block.

        A HYBRIDIZED block's cached/fused program never calls the
        Python forward hooks this method registers — historically
        ``install`` succeeded and then silently produced empty hook
        rows forever (ISSUE 15 satellite). Now every Gluon block is
        registered with the training-health plane
        (``mxnet_tpu._debug.healthmon``, scoped to the block's own
        parameters), which routes per-layer weight/grad rows out of
        the fused step's in-graph health outputs under the same
        ``(batch, name, stat)`` row contract whenever
        ``MXTPU_HEALTH=1`` — including blocks that hybridize AFTER
        install. When the health plane is OFF, the bypass is loudly
        reported: a warning (at install if already hybridized, at the
        first bypassed ``toc()`` otherwise), or ``ValueError`` with
        ``strict=True``."""
        if hasattr(exe, "register_forward_hook"):
            mon = self

            def hook(block, inputs, output):
                outs = output if isinstance(output, (list, tuple)) \
                    else [output]
                for i, o in enumerate(outs):
                    mon.stat_helper("%s_output%d" % (block.name, i), o)
            exe.register_forward_hook(hook)
            if hasattr(exe, "collect_params"):
                from ._debug import healthmon as _healthmon
                _healthmon.attach_monitor(
                    self, params=exe.collect_params().keys())
                if getattr(exe, "_active", False) \
                        and not _healthmon.enabled():
                    msg = self._bypass_msg(exe)
                    if strict:
                        raise ValueError(msg)
                    self._bypass_warned = True
                    logging.warning(msg)
        self.exes.append(exe)

    @staticmethod
    def _bypass_msg(exe):
        return ("Monitor on %s: the block is hybridized — the "
                "cached/fused program never calls Python forward "
                "hooks, so hook rows stay empty. Set MXTPU_HEALTH=1 "
                "to route per-layer stats through the fused step's "
                "health outputs, or un-hybridize the block while "
                "debugging." % getattr(exe, "name", exe))

    def tic(self):
        """Start collecting for this batch if the interval hits
        (ref: monitor.py tic)."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Stop collecting and return (step, name, stat) rows.

        When the training-health plane already delivered this batch's
        per-layer rows out of the fused step (``healthmon`` sets
        ``_fused_batch`` at delivery), the eager ``collect_params``
        sweep is skipped for hybridized blocks — same rows, one
        source, no duplicates."""
        if not self.activated:
            return []
        self.activated = False
        fused_batch = getattr(self, "_fused_batch", None)
        for exe in self.exes:
            if fused_batch == self.step \
                    and getattr(exe, "_active", False):
                continue
            if getattr(exe, "_active", False) \
                    and not self._bypass_warned:
                # block hybridized AFTER install (the install-time
                # check could not see it): hook rows are bypassed and
                # the health plane is not delivering — say so ONCE
                from ._debug import healthmon as _healthmon
                if not _healthmon.enabled():
                    self._bypass_warned = True
                    logging.warning(self._bypass_msg(exe))
            if hasattr(exe, "collect_params"):
                for name, p in exe.collect_params().items():
                    if p._data is not None:
                        self.stat_helper_always(name, p.data())
                        if p._data._grad is not None:
                            self.stat_helper_always(name + "_grad", p.grad())
            elif hasattr(exe, "arg_dict"):
                for name, array in exe.arg_dict.items():
                    self.stat_helper_always(name, array)
                if hasattr(exe, "grad_dict"):
                    for name, array in exe.grad_dict.items():
                        if array is not None:
                            self.stat_helper_always(name + "_grad", array)
        res = []
        queue = self.queue
        if self.sort:
            queue = sorted(queue, key=lambda x: x[1])
        for n, k, v_list in queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            assert isinstance(v_list, list)
            s = ""
            for v in v_list:
                assert isinstance(v, NDArray)
                if v.shape == (1,) or v.shape == ():
                    s += str(v.asscalar()) + "\t"
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def stat_helper_always(self, name, array):
        if not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(array)))

    def toc_print(self):
        """Collect and print stats (ref: monitor.py toc_print)."""
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
        return res
