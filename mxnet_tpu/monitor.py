"""Monitor outputs, weights, and gradients for debugging.

ref: python/mxnet/monitor.py (Monitor :33). The reference installs a C++
monitor callback on executors; here `install` wraps Gluon block forward hooks
and Module executors call `tic/toc` around forward, collecting the same
(batch, name, stat) rows.
"""
from __future__ import annotations

import logging
import re

from .ndarray import NDArray
from . import ndarray as nd

__all__ = ["Monitor"]


class Monitor:
    """Collect per-tensor stats every `interval` batches (ref: monitor.py:33)."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                return x.abs().mean()
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def stat_helper(self, name, array):
        if not self.activated or not self.re_prog.match(name):
            return
        array = array if isinstance(array, NDArray) else nd.array(array)
        self.queue.append((self.step, name, self.stat_func(array)))

    def install(self, exe):
        """Install the monitor on an executor or Gluon block."""
        if hasattr(exe, "register_forward_hook"):
            mon = self

            def hook(block, inputs, output):
                outs = output if isinstance(output, (list, tuple)) \
                    else [output]
                for i, o in enumerate(outs):
                    mon.stat_helper("%s_output%d" % (block.name, i), o)
            exe.register_forward_hook(hook)
        self.exes.append(exe)

    def tic(self):
        """Start collecting for this batch if the interval hits
        (ref: monitor.py tic)."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Stop collecting and return (step, name, stat) rows."""
        if not self.activated:
            return []
        self.activated = False
        for exe in self.exes:
            if hasattr(exe, "collect_params"):
                for name, p in exe.collect_params().items():
                    if p._data is not None:
                        self.stat_helper_always(name, p.data())
                        if p._data._grad is not None:
                            self.stat_helper_always(name + "_grad", p.grad())
            elif hasattr(exe, "arg_dict"):
                for name, array in exe.arg_dict.items():
                    self.stat_helper_always(name, array)
                if hasattr(exe, "grad_dict"):
                    for name, array in exe.grad_dict.items():
                        if array is not None:
                            self.stat_helper_always(name + "_grad", array)
        res = []
        queue = self.queue
        if self.sort:
            queue = sorted(queue, key=lambda x: x[1])
        for n, k, v_list in queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            assert isinstance(v_list, list)
            s = ""
            for v in v_list:
                assert isinstance(v, NDArray)
                if v.shape == (1,) or v.shape == ():
                    s += str(v.asscalar()) + "\t"
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def stat_helper_always(self, name, array):
        if not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(array)))

    def toc_print(self):
        """Collect and print stats (ref: monitor.py toc_print)."""
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
        return res
