"""Training callbacks for the Module fit loop.

Own-idiom rebuild of the reference callback surface
(ref: python/mxnet/callback.py — module_checkpoint :31, do_checkpoint
:59, log_train_metric :83, Speedometer :108, ProgressBar :177,
LogValidationMetricsCallback :205). Every batch-end callback receives
the fit loop's BatchEndParam (fields: epoch, nbatch, eval_metric,
locals) and every epoch-end callback (iter_no, sym, arg, aux).

One TPU-relevant behavior worth knowing: metric values read here come
from the device-side accumulators in metric.py — the fit loop never
syncs per batch, so a Speedometer with frequent=50 forces at most one
device->host transfer per 50 batches, not per batch.
"""
from __future__ import annotations

import logging
import math
import sys
import time

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar", "LogValidationMetricsCallback"]

_log = logging.getLogger(__name__)


def _every(period):
    """True on epochs 0-indexed period-1, 2*period-1, ... (the reference
    checkpoints on (iter_no + 1) % period == 0)."""
    period = max(1, int(period))
    return lambda iter_no: (iter_no + 1) % period == 0


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end callback saving `mod` every `period` epochs
    (ref: callback.py:31)."""
    due = _every(period)

    def _on_epoch_end(iter_no, sym=None, arg=None, aux=None):
        if due(iter_no):
            mod.save_checkpoint(prefix, iter_no + 1,
                                save_optimizer_states)
    return _on_epoch_end


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving the (sym, arg, aux) triple every
    `period` epochs (ref: callback.py:59)."""
    from .model import save_checkpoint
    due = _every(period)

    def _on_epoch_end(iter_no, sym, arg, aux):
        if due(iter_no):
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _on_epoch_end


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the training metric every `period`
    batches (ref: callback.py:83)."""
    def _on_batch_end(param):
        metric = param.eval_metric
        if param.nbatch % period != 0 or metric is None:
            return
        for name, value in metric.get_name_value():
            _log.info("Iter[%d] Batch[%d] Train-%s=%f", param.epoch,
                      param.nbatch, name, value)
        if auto_reset:
            metric.reset_local()
    return _on_batch_end


class Speedometer:
    """Batch-end callback logging samples/sec plus the current metric
    every `frequent` batches (ref: callback.py:108).

    With auto_reset the metric window restarts after each report, so
    the printed values cover just the last `frequent` batches; without
    it they are epoch-cumulative (batch range logged accordingly).
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size, self.frequent = batch_size, frequent
        self.auto_reset = auto_reset
        self.last_count = 0
        self._window_start = None  # None => first call of an epoch

    def __call__(self, param):
        n = param.nbatch
        if self.last_count > n:  # nbatch restarted: new epoch
            self._window_start = None
        self.last_count = n

        if self._window_start is None:
            self._window_start = time.time()
            return
        if n % self.frequent != 0:
            return

        elapsed = time.time() - self._window_start
        speed = (self.frequent * self.batch_size / elapsed) if elapsed \
            else float("inf")
        metric = param.eval_metric
        if metric is None:
            _log.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                      param.epoch, n, speed)
        else:
            pairs = metric.get_name_value()
            lo = n - self.frequent if self.auto_reset else 0
            if self.auto_reset:
                metric.reset_local()
            _log.info("Epoch[%d] Batch [%d-%d]\tSpeed: %.2f "
                      "samples/sec%s", param.epoch, lo, n, speed,
                      "".join("\t%s=%f" % nv for nv in pairs))
        self._window_start = time.time()


class ProgressBar:
    """Batch-end callback drawing an ASCII bar over `total` batches
    (ref: callback.py:177)."""

    def __init__(self, total, length=80):
        self.bar_len, self.total = length, total

    def __call__(self, param):
        done = param.nbatch / float(self.total)
        fill = int(round(self.bar_len * done))
        sys.stdout.write("[%s] %s%%\r" % (
            "=" * fill + "-" * (self.bar_len - fill),
            math.ceil(100.0 * done)))


class LogValidationMetricsCallback:
    """Epoch-end (eval) callback logging every validation metric
    (ref: callback.py:205)."""

    def __call__(self, param):
        for name, value in (param.eval_metric.get_name_value()
                            if param.eval_metric else ()):
            _log.info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                      value)
