"""Elementwise ops (unary + binary with numpy broadcasting).

TPU-native re-design of the reference's elemwise operator families
(ref: src/operator/tensor/elemwise_unary_op_basic.cc,
elemwise_binary_broadcast_op_basic.cc, src/operator/mshadow_op.h). The
reference registers separate ``elemwise_*`` (same-shape) and ``broadcast_*``
ops; XLA broadcasts natively so both names map to one implementation and the
``broadcast_*`` spellings are registered as aliases.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from .registry import register

# ---------------------------------------------------------------------------
# binary arithmetic
# ---------------------------------------------------------------------------

_BINARY = {
    "add": jnp.add,
    "subtract": jnp.subtract,
    "multiply": jnp.multiply,
    "divide": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "arctan2": jnp.arctan2,
}

for _name, _fn in _BINARY.items():
    register(_name, num_inputs=2,
             aliases=("broadcast_" + _name,
                      *( ("elemwise_" + _name,) if _name in
                         ("add", "subtract", "multiply", "divide") else () ),
                      *( ("broadcast_sub", "elemwise_sub")
                         if _name == "subtract" else () ),
                      *( ("broadcast_mul", "elemwise_mul")
                         if _name == "multiply" else () ),
                      *( ("broadcast_div", "elemwise_div")
                         if _name == "divide" else () ),
                      *( ("broadcast_pow", "_power")
                         if _name == "power" else () ),
                      ))(_fn)

_COMPARE = {
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
    "greater": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "lesser": jnp.less,
    "lesser_equal": jnp.less_equal,
    "logical_and": jnp.logical_and,
    "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
}

for _name, _fn in _COMPARE.items():
    # comparisons keep the input dtype in the reference (1.0/0.0 outputs)
    def _mk(f):
        def _cmp(a, b):
            out = f(a, b)
            dt = jnp.result_type(a, b) if not jnp.issubdtype(
                jnp.result_type(a, b), jnp.bool_) else jnp.float32
            return out.astype(dt)
        return _cmp
    register(_name, num_inputs=2, no_grad=True,
             aliases=("broadcast_" + _name,))(_mk(_fn))


# ---------------------------------------------------------------------------
# unary math
# ---------------------------------------------------------------------------

# ULP-bounded formulations for the two transcendental outliers BENCH_r05
# measured against the CPU golden (log: 3,396 ULP, tanh: 1,267 —
# XLA:TPU's default polynomial approximations drift over the full
# argument range). Both reroute through a REDUCED domain where every
# backend's primitive is tight, glued together with exactly-rounded
# arithmetic; benchmark/tpu_numerics.py enforces <=256 ULP for each.

_LN2_HI = 0.69313812256  # f32 with 12 trailing zeros: e*LN2_HI is exact
_LN2_LO = 9.0580006145e-06  # ln2 - LN2_HI, in f32
_SQRT_HALF = 0.7071067811865476


@jax.custom_jvp
def _log_split(x):
    """log via exponent split + log1p (f32 core).

    Decompose x = m * 2^e with m in [sqrt(1/2), sqrt(2)) by exponent
    bit surgery — exact on every backend — then

        log(x) = e * LN2_HI + (log1p(m - 1) + e * LN2_LO)

    with ln2 split hi/lo so the dominant product is exactly
    representable. ``log1p`` only ever sees |m-1| < 0.4142, the range
    where the TPU polynomial is a few ULP, versus raw ``log`` whose
    error grows with the unreduced argument. Specials (0, negatives,
    inf, nan, subnormals) match jnp.log bit-for-bit. float64 inputs
    (jax_enable_x64 runs) keep the backend's native f64 log — the f32
    core would silently truncate their precision."""
    if jnp.dtype(jnp.asarray(x).dtype) == jnp.float64:
        return jnp.log(x)
    xf = x.astype(jnp.float32)
    # subnormals: scale into the normal range, correct e afterwards
    tiny = xf < jnp.float32(1.1754944e-38)
    xs = jnp.where(tiny, xf * jnp.float32(2.0 ** 25), xf)
    bits = jax.lax.bitcast_convert_type(xs, jnp.int32)
    e = ((bits >> 23) & 0xFF) - 127
    m = jax.lax.bitcast_convert_type(
        (bits & 0x007FFFFF) | 0x3F800000, jnp.float32)  # [1, 2)
    adj = m >= jnp.float32(2.0 * _SQRT_HALF)
    m = jnp.where(adj, m * 0.5, m)
    e = (e + adj.astype(jnp.int32)
         - jnp.where(tiny, 25, 0)).astype(jnp.float32)
    out = e * jnp.float32(_LN2_HI) \
        + (jnp.log1p(m - 1.0) + e * jnp.float32(_LN2_LO))
    out = jnp.where(xf == 0.0, -jnp.inf, out)
    out = jnp.where(xf < 0.0, jnp.nan, out)
    out = jnp.where(jnp.isfinite(xf), out, jnp.log(xf))  # inf/nan
    dt = x.dtype if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) \
        else jnp.float32
    return out.astype(dt)


@_log_split.defjvp
def _log_split_jvp(primals, tangents):
    (x,), (t,) = primals, tangents
    return _log_split(x), t / x


@jax.custom_jvp
def _tanh_expm1(x):
    """tanh via expm1: t = expm1(-2|x|); tanh = -t / (t + 2), sign
    restored by symmetry. ``expm1`` is the backend primitive that is
    accurate exactly where tanh needs it (small |2x|, where naive
    exp(2x)-1 cancels), saturates cleanly to +-1 for large |x|, and
    the reassembly is two correctly-rounded ops. jnp.tanh's TPU
    approximation measured 1,267 ULP in BENCH_r04/r05; this form
    budgets 256. float64 inputs keep the backend's native f64 tanh."""
    if jnp.dtype(jnp.asarray(x).dtype) == jnp.float64:
        return jnp.tanh(x)
    xf = x.astype(jnp.float32)
    a = jnp.abs(xf)
    t = jnp.expm1(-2.0 * a)
    r = -t / (t + 2.0)
    out = jnp.where(xf < 0.0, -r, r)
    # keep -0.0 and nan bit-identical to jnp.tanh
    out = jnp.where(xf == 0.0, xf, out)
    dt = x.dtype if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) \
        else jnp.float32
    return out.astype(dt)


@_tanh_expm1.defjvp
def _tanh_expm1_jvp(primals, tangents):
    (x,), (t,) = primals, tangents
    y = _tanh_expm1(x)
    return y, (1.0 - y * y) * t


_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "rint": jnp.rint,
    "round": jnp.round,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": _log_split,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "square": jnp.square,
    "cbrt": jnp.cbrt,
    "negative": jnp.negative,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": _tanh_expm1,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gammaln": jsp.gammaln,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype)
                    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.bool_)
                    else jnp.logical_not(x),
}

for _name, _fn in _UNARY.items():
    register(_name, num_inputs=1,
             aliases=(("gamma",) if _name == "gammaln" else ()))(_fn)


@register("add_n", aliases=("ElementWiseSum", "elemwise_sum"))
def add_n(*xs):
    """Variadic sum (ref: src/ndarray/ndarray_function.cc ElementwiseSum,
    src/operator/tensor/elemwise_sum.cc add_n) — XLA fuses the chain into
    one HBM pass."""
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@register("reciprocal", num_inputs=1)
def reciprocal(x):
    return 1.0 / x


@register("rsqrt", num_inputs=1)
def rsqrt(x):
    return jax.lax.rsqrt(x)


@register("rcbrt", num_inputs=1)
def rcbrt(x):
    return 1.0 / jnp.cbrt(x)


@register("sigmoid", num_inputs=1)
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register("hard_sigmoid", num_inputs=1)
def hard_sigmoid(x, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


@register("relu", num_inputs=1)
def relu(x):
    return jnp.maximum(x, 0)


@register("softsign", num_inputs=1)
def softsign(x):
    return x / (1.0 + jnp.abs(x))


@register("softrelu", num_inputs=1)
def softrelu(x):
    # log(1+exp(x)), numerically stable (ref: mshadow_op.h softrelu)
    return jax.nn.softplus(x)


@register("clip", num_inputs=1)
def clip(x, a_min=None, a_max=None):
    return jnp.clip(x, a_min, a_max)


@register("smooth_l1", num_inputs=1)
def smooth_l1(x, scalar=1.0):
    # ref: src/operator/tensor/elemwise_binary_scalar_op_extended.cc smooth_l1
    s2 = scalar * scalar
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0 / s2, 0.5 * s2 * x * x, absx - 0.5 / s2)


# scalar variants (the reference registers _plus_scalar etc.; our wrappers
# accept python scalars directly in the binary ops, so these are aliases kept
# for symbol-level name compat)
register("_plus_scalar", num_inputs=1)(lambda x, scalar=0.0: x + scalar)
register("_minus_scalar", num_inputs=1)(lambda x, scalar=0.0: x - scalar)
register("_rminus_scalar", num_inputs=1)(lambda x, scalar=0.0: scalar - x)
register("_mul_scalar", num_inputs=1)(lambda x, scalar=1.0: x * scalar)
register("_div_scalar", num_inputs=1)(lambda x, scalar=1.0: x / scalar)
register("_rdiv_scalar", num_inputs=1)(lambda x, scalar=1.0: scalar / x)
register("_power_scalar", num_inputs=1)(lambda x, scalar=1.0: x ** scalar)
register("_rpower_scalar", num_inputs=1)(lambda x, scalar=1.0: scalar ** x)
register("_mod_scalar", num_inputs=1)(lambda x, scalar=1.0: x % scalar)
register("_maximum_scalar", num_inputs=1)(lambda x, scalar=0.0: jnp.maximum(x, scalar))
register("_minimum_scalar", num_inputs=1)(lambda x, scalar=0.0: jnp.minimum(x, scalar))

# scalar comparisons (ref: src/operator/tensor/elemwise_binary_scalar_op_logic.cc)
# — 1.0/0.0 outputs in the input dtype, like the tensor-tensor comparisons
for _cname, _cfn in (("_equal_scalar", jnp.equal),
                     ("_not_equal_scalar", jnp.not_equal),
                     ("_greater_scalar", jnp.greater),
                     ("_greater_equal_scalar", jnp.greater_equal),
                     ("_lesser_scalar", jnp.less),
                     ("_lesser_equal_scalar", jnp.less_equal)):
    def _mk_cmp_scalar(f):
        def _cmp(x, scalar=0.0):
            dt = x.dtype if jnp.issubdtype(x.dtype, jnp.number) \
                else jnp.float32
            return f(x, scalar).astype(dt)
        return _cmp
    register(_cname, num_inputs=1, no_grad=True)(_mk_cmp_scalar(_cfn))
