"""Fused optimizer update ops — pure functional registry forms.

ref: src/operator/optimizer_op.cc registrations + kernels in
optimizer_op-inl.h (SGDKernel :382, SGDMomKernel :600, NAGMomKernel
:1060, AdamUpdateKernel :1302, RMSPropUpdateKernel :1717,
RMSPropAlexUpdateKernel :1619, FTRLKernel :1797, FTMLKernel :1214,
SignSGDKernel :1998, SignumKernel :2066), src/operator/contrib/adamw.cc,
multi_lars.cc, and the multi_sgd/preloaded variants.

The reference's ops mutate their state inputs in place. XLA programs
have no aliasing, so the registry forms here are PURE: every updated
tensor is an explicit output — ``sgd_mom_update`` returns
``(new_weight, new_mom)``. This is the TPU-idiomatic dataflow contract
and what the symbolic executor compiles. The `mx.nd.*_update` wrappers
(ndarray/optimizer_ops.py) restore the reference's imperative in-place
calling convention on top of these.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

__all__ = []


def _clip(g, c):
    return jnp.clip(g, -c, c) if c is not None and c >= 0 else g


def _wclip(w, c):
    if c is not None and c >= 0:
        return jnp.clip(w, -c, c)
    return w


@register("sgd_update", num_inputs=2, no_grad=True,
          input_names=("weight", "grad"))
def sgd_update(weight, grad, lr=None, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    """ref: optimizer_op-inl.h:382 SGDKernel."""
    g = _clip(rescale_grad * grad, clip_gradient)
    return (1.0 - lr * wd) * weight - lr * g


@register("sgd_mom_update", num_inputs=3, no_grad=True, num_outputs=2,
          input_names=("weight", "grad", "mom"),
          inplace=(2,))
def sgd_mom_update(weight, grad, mom, lr=None, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    """ref: optimizer_op-inl.h:600 SGDMomKernel -> (new_w, new_mom)."""
    g = _clip(rescale_grad * grad, clip_gradient)
    new_m = momentum * mom - lr * wd * weight - lr * g
    return weight + new_m, new_m


@register("mp_sgd_update", num_inputs=3, no_grad=True, num_outputs=2,
          input_names=("weight", "grad", "weight32"),
          inplace=(2,))
def mp_sgd_update(weight, grad, weight32, lr=None, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    """ref: optimizer_op-inl.h MP_SGDKernel -> (new_w, new_w32)."""
    g = _clip(rescale_grad * grad.astype(jnp.float32), clip_gradient)
    new_w32 = (1.0 - lr * wd) * weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", num_inputs=4, no_grad=True, num_outputs=3,
          input_names=("weight", "grad", "mom", "weight32"),
          inplace=(2, 3))
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=None, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    """ref: optimizer_op-inl.h MP_SGDMomKernel -> (new_w, new_mom,
    new_w32)."""
    g = _clip(rescale_grad * grad.astype(jnp.float32), clip_gradient)
    new_m = momentum * mom - lr * wd * weight32 - lr * g
    new_w32 = weight32 + new_m
    return new_w32.astype(weight.dtype), new_m, new_w32


@register("nag_mom_update", num_inputs=3, no_grad=True, num_outputs=2,
          input_names=("weight", "grad", "mom"),
          inplace=(2,))
def nag_mom_update(weight, grad, mom, lr=None, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    """Nesterov momentum (ref: optimizer_op-inl.h:1060 NAGMomKernel)
    -> (new_w, new_mom)."""
    g = _clip(rescale_grad * grad, clip_gradient) + wd * weight
    m_scaled = momentum * mom
    new_m = m_scaled - lr * g
    new_w = weight - m_scaled + (momentum + 1.0) * new_m
    return new_w, new_m


@register("mp_nag_mom_update", num_inputs=4, no_grad=True, num_outputs=3,
          input_names=("weight", "grad", "mom", "weight32"),
          inplace=(2, 3))
def mp_nag_mom_update(weight, grad, mom, weight32, lr=None, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """ref: optimizer_op-inl.h MP_NAGMomKernel -> (new_w, new_mom,
    new_w32)."""
    g = _clip(rescale_grad * grad.astype(jnp.float32), clip_gradient) \
        + wd * weight32
    m_scaled = momentum * mom
    new_m = m_scaled - lr * g
    new_w32 = weight32 - m_scaled + (momentum + 1.0) * new_m
    return new_w32.astype(weight.dtype), new_m, new_w32


@register("adam_update", num_inputs=4, no_grad=True, num_outputs=3,
          input_names=("weight", "grad", "mean", "var"),
          inplace=(2, 3))
def adam_update(weight, grad, mean, var, lr=None, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    """ref: optimizer_op-inl.h:1302 AdamUpdateKernel (no bias correction —
    the Python optimizer folds it into lr) -> (new_w, new_mean, new_var)."""
    g = _clip(grad * rescale_grad + wd * weight, clip_gradient)
    new_m = beta1 * mean + (1.0 - beta1) * g
    new_v = beta2 * var + (1.0 - beta2) * g * g
    new_w = weight - lr * new_m / (jnp.sqrt(new_v) + epsilon)
    return new_w, new_m, new_v


@register("rmsprop_update", num_inputs=3, no_grad=True, num_outputs=2,
          input_names=("weight", "grad", "n"),
          inplace=(2,))
def rmsprop_update(weight, grad, n, lr=None, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    """ref: optimizer_op-inl.h:1717 RMSPropUpdateKernel -> (new_w, new_n)."""
    g = _clip(rescale_grad * grad + wd * weight, clip_gradient)
    new_n = (1.0 - gamma1) * g * g + gamma1 * n
    new_w = _wclip(weight - lr * g / jnp.sqrt(new_n + epsilon),
                   clip_weights)
    return new_w, new_n


@register("rmspropalex_update", num_inputs=5, no_grad=True, num_outputs=4,
          input_names=("weight", "grad", "n", "g", "delta"),
          inplace=(2, 3, 4))
def rmspropalex_update(weight, grad, n, g, delta, lr=None, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    """Graves' RMSProp (ref: optimizer_op-inl.h:1619) -> (new_w, new_n,
    new_g, new_delta)."""
    gr = _clip(rescale_grad * grad + wd * weight, clip_gradient)
    new_n = (1.0 - gamma1) * gr * gr + gamma1 * n
    new_g = (1.0 - gamma1) * gr + gamma1 * g
    new_d = gamma2 * delta \
        - lr * gr / jnp.sqrt(new_n - new_g * new_g + epsilon)
    new_w = _wclip(weight + new_d, clip_weights)
    return new_w, new_n, new_g, new_d


@register("ftrl_update", num_inputs=4, no_grad=True, num_outputs=3,
          input_names=("weight", "grad", "z", "n"),
          inplace=(2, 3))
def ftrl_update(weight, grad, z, n, lr=None, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    """ref: optimizer_op-inl.h:1797 FTRLKernel -> (new_w, new_z, new_n)."""
    g = _clip(rescale_grad * grad, clip_gradient)
    new_z = z + g - (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / lr * weight
    new_n = n + g * g
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1, jnp.zeros_like(weight),
        (jnp.sign(new_z) * lamda1 - new_z)
        / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return new_w, new_z, new_n


@register("ftml_update", num_inputs=5, no_grad=True, num_outputs=4,
          input_names=("weight", "grad", "d", "v", "z"),
          inplace=(2, 3, 4))
def ftml_update(weight, grad, d, v, z, lr=None, t=1, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0):
    """ref: optimizer_op-inl.h:1214 FTMLKernel -> (new_w, new_d, new_v,
    new_z)."""
    g = _clip(rescale_grad * grad + wd * weight, clip_grad)
    t = float(t)
    new_v = beta2 * v + (1.0 - beta2) * g * g
    d_t = (1.0 - beta1 ** t) / lr * (
        jnp.sqrt(new_v / (1.0 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1.0 - beta1) * g - sigma * weight
    return -new_z / d_t, d_t, new_v, new_z


@register("signsgd_update", num_inputs=2, no_grad=True,
          input_names=("weight", "grad"))
def signsgd_update(weight, grad, lr=None, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    """ref: optimizer_op-inl.h:1998 SignSGDKernel."""
    return (1.0 - lr * wd) * weight - lr * jnp.sign(grad)


@register("signum_update", num_inputs=3, no_grad=True, num_outputs=2,
          input_names=("weight", "grad", "mom"),
          inplace=(2,))
def signum_update(weight, grad, mom, lr=None, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    """ref: optimizer_op-inl.h:2066 SignumKernel -> (new_w, new_mom)."""
    g = _clip(rescale_grad * grad, clip_gradient)
    new_m = momentum * mom - (1.0 - momentum) * wd * weight \
        - (1.0 - momentum) * g
    return (1.0 - lr * wd_lh) * weight + lr * jnp.sign(new_m), new_m


@register("adamw_update", num_inputs=4, no_grad=True, num_outputs=3,
          input_names=("weight", "grad", "mean", "var"),
          inplace=(2, 3))
def adamw_update(weight, grad, mean, var, rescale_grad=1.0, lr=None,
                 eta=None, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                 clip_gradient=-1.0):
    """Decoupled weight decay Adam (ref: contrib/adamw.cc _adamw_update;
    rescale_grad is a scalar attr here, a tensor there)
    -> (new_w, new_mean, new_var)."""
    g = _clip(grad * rescale_grad, clip_gradient)
    new_m = beta1 * mean + (1.0 - beta1) * g
    new_v = beta2 * var + (1.0 - beta2) * g * g
    new_w = weight - eta * (lr * new_m / (jnp.sqrt(new_v) + epsilon)
                            + wd * weight)
    return new_w, new_m, new_v


@register("mp_adamw_update", num_inputs=5, no_grad=True, num_outputs=4,
          input_names=("weight", "grad", "mean", "var", "weight32"),
          inplace=(2, 3, 4))
def mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad=1.0,
                    lr=None, eta=None, beta1=0.9, beta2=0.999, epsilon=1e-8,
                    wd=0.0, clip_gradient=-1.0):
    """ref: contrib/adamw.cc _mp_adamw_update -> (new_w, new_mean,
    new_var, new_w32)."""
    g = _clip(grad.astype(jnp.float32) * rescale_grad, clip_gradient)
    new_m = beta1 * mean + (1.0 - beta1) * g
    new_v = beta2 * var + (1.0 - beta2) * g * g
    new_w32 = weight32 - eta * (lr * new_m / (jnp.sqrt(new_v) + epsilon)
                                + wd * weight32)
    return new_w32.astype(weight.dtype), new_m, new_v, new_w32


@register("lamb_update_phase1", num_inputs=4, no_grad=True, num_outputs=3,
          input_names=("weight", "grad", "mean", "var"),
          inplace=(2, 3))
def lamb_update_phase1(weight, grad, mean, var, lr=None, beta1=0.9,
                       beta2=0.999, epsilon=1e-6, t=1,
                       bias_correction=True, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0):
    """ref: optimizer_op.cc lamb_update_phase1 -> (g_out, new_mean,
    new_var)."""
    g = _clip(rescale_grad * grad, clip_gradient)
    new_m = beta1 * mean + (1.0 - beta1) * g
    new_v = beta2 * var + (1.0 - beta2) * g * g
    mh, vh = new_m, new_v
    if bias_correction:
        t = float(t)
        mh = new_m / (1.0 - beta1 ** t)
        vh = new_v / (1.0 - beta2 ** t)
    return mh / (jnp.sqrt(vh) + epsilon) + wd * weight, new_m, new_v


@register("lamb_update_phase2", num_inputs=4, no_grad=True,
          input_names=("weight", "g", "r1", "r2"))
def lamb_update_phase2(weight, g, r1, r2, lr=None, lower_bound=-1.0,
                       upper_bound=-1.0):
    """ref: optimizer_op.cc lamb_update_phase2."""
    r1v, r2v = r1, r2
    if lower_bound is not None and lower_bound >= 0:
        r1v = jnp.maximum(r1v, lower_bound)
    if upper_bound is not None and upper_bound >= 0:
        r1v = jnp.minimum(r1v, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1v > 0, r2v > 0), r1v / r2v, 1.0)
    return weight - lr * ratio * g


@register("sparse_adagrad_update", num_inputs=3, no_grad=True,
          num_outputs=2, aliases=("group_adagrad_update",),
          input_names=("weight", "grad", "history"),
          inplace=(2,))
def sparse_adagrad_update(weight, grad, history, lr=None, epsilon=1e-7,
                          wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """AdaGrad with accumulated history (ref: optimizer_op.cc
    _sparse_adagrad_update; contrib group_adagrad shares the kernel)
    -> (new_w, new_history)."""
    g = _clip(rescale_grad * grad, clip_gradient)
    new_h = history + g * g
    new_w = weight - lr * (g / (jnp.sqrt(new_h) + epsilon) + wd * weight)
    return new_w, new_h


@register("multi_lars", num_inputs=4, no_grad=True,
          input_names=("lrs", "weights_sum_sq", "grads_sum_sq", "wds"))
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
               eps=1e-8, rescale_grad=1.0):
    """LARS trust-ratio learning rates (ref: contrib/multi_lars.cc)."""
    wn = jnp.sqrt(weights_sum_sq)
    gn = jnp.sqrt(grads_sum_sq) * rescale_grad
    ratio = jnp.where(jnp.logical_and(wn > 0, gn > 0),
                      eta * wn / (gn + wds * wn + eps), jnp.ones_like(wn))
    return lrs * ratio


def _norm_list(v, n):
    # entries may be python floats (attrs) or traced jax scalars (the
    # preloaded variants index their lrs/wds tensor inputs) — no float()
    if isinstance(v, (tuple, list)):
        return list(v)
    return [v] * n


def _multi_pure(single, n_per, n_states, data, num_weights, lrs, wds,
                kwargs):
    """Apply a pure single update over interleaved groups; returns all
    new weights, then all new state tensors group-major (the reference
    mutates states in place; the pure form makes them outputs)."""
    num_weights = int(num_weights)
    lrs = _norm_list(lrs, num_weights)
    wds = _norm_list(wds, num_weights)
    new_ws, new_states = [], []
    for i in range(num_weights):
        group = data[i * n_per:(i + 1) * n_per]
        res = single(*group, lr=lrs[i], wd=wds[i], **kwargs)
        if n_states:
            new_ws.append(res[0])
            new_states.extend(res[1:])
        else:
            new_ws.append(res)
    return tuple(new_ws) + tuple(new_states)


def _multi_nout(states_per_weight):
    def count(attrs):
        return int(attrs.get("num_weights", 1)) * (1 + states_per_weight)
    return count


@register("multi_sgd_update", no_grad=True, num_outputs=_multi_nout(0))
def multi_sgd_update(*data, lrs=None, wds=None, num_weights=1,
                     rescale_grad=1.0, clip_gradient=-1.0):
    """ref: optimizer_op.cc multi_sgd_update — (w, g) x N -> new weights."""
    return _multi_pure(sgd_update, 2, 0, data, num_weights, lrs, wds,
                       dict(rescale_grad=rescale_grad,
                            clip_gradient=clip_gradient))


@register("multi_sgd_mom_update", no_grad=True, num_outputs=_multi_nout(1))
def multi_sgd_mom_update(*data, lrs=None, wds=None, num_weights=1,
                         momentum=0.0, rescale_grad=1.0,
                         clip_gradient=-1.0):
    """ref: optimizer_op.cc multi_sgd_mom_update — (w, g, mom) x N
    -> (new_w x N, new_mom x N)."""
    return _multi_pure(sgd_mom_update, 3, 1, data, num_weights, lrs, wds,
                       dict(momentum=momentum, rescale_grad=rescale_grad,
                            clip_gradient=clip_gradient))


@register("multi_mp_sgd_update", no_grad=True, num_outputs=_multi_nout(1))
def multi_mp_sgd_update(*data, lrs=None, wds=None, num_weights=1,
                        rescale_grad=1.0, clip_gradient=-1.0):
    """ref: optimizer_op.cc multi_mp_sgd_update — (w, g, w32) x N
    -> (new_w x N, new_w32 x N)."""
    return _multi_pure(mp_sgd_update, 3, 1, data, num_weights, lrs, wds,
                       dict(rescale_grad=rescale_grad,
                            clip_gradient=clip_gradient))


@register("multi_mp_sgd_mom_update", no_grad=True,
          num_outputs=_multi_nout(2))
def multi_mp_sgd_mom_update(*data, lrs=None, wds=None, num_weights=1,
                            momentum=0.0, rescale_grad=1.0,
                            clip_gradient=-1.0):
    """ref: optimizer_op.cc multi_mp_sgd_mom_update — (w, g, mom, w32) x N
    -> (new_w x N, (new_mom, new_w32) x N)."""
    return _multi_pure(mp_sgd_mom_update, 4, 2, data, num_weights, lrs,
                       wds, dict(momentum=momentum,
                                 rescale_grad=rescale_grad,
                                 clip_gradient=clip_gradient))


def _preloaded_pure(multi, n_per, data, num_weights, kwargs):
    # trailing two tensors are the preloaded lrs/wds vectors
    # (ref: optimizer_op.cc preloaded_multi_sgd_update)
    lrs, wds = data[-2], data[-1]
    num_weights = int(num_weights)
    return multi(*data[:-2], lrs=[lrs[i] for i in range(num_weights)],
                 wds=[wds[i] for i in range(num_weights)],
                 num_weights=num_weights, **kwargs)


@register("preloaded_multi_sgd_update", no_grad=True,
          num_outputs=_multi_nout(0))
def preloaded_multi_sgd_update(*data, num_weights=1, rescale_grad=1.0,
                               clip_gradient=-1.0):
    """ref: optimizer_op.cc preloaded_multi_sgd_update."""
    return _preloaded_pure(multi_sgd_update, 2, data, num_weights,
                           dict(rescale_grad=rescale_grad,
                                clip_gradient=clip_gradient))


@register("preloaded_multi_sgd_mom_update", no_grad=True,
          num_outputs=_multi_nout(1))
def preloaded_multi_sgd_mom_update(*data, num_weights=1, momentum=0.0,
                                   rescale_grad=1.0, clip_gradient=-1.0):
    """ref: optimizer_op.cc preloaded_multi_sgd_mom_update."""
    return _preloaded_pure(multi_sgd_mom_update, 3, data, num_weights,
                           dict(momentum=momentum,
                                rescale_grad=rescale_grad,
                                clip_gradient=clip_gradient))


@register("preloaded_multi_mp_sgd_update", no_grad=True,
          num_outputs=_multi_nout(1))
def preloaded_multi_mp_sgd_update(*data, num_weights=1, rescale_grad=1.0,
                                  clip_gradient=-1.0):
    """ref: optimizer_op.cc preloaded_multi_mp_sgd_update."""
    return _preloaded_pure(multi_mp_sgd_update, 3, data, num_weights,
                           dict(rescale_grad=rescale_grad,
                                clip_gradient=clip_gradient))


@register("preloaded_multi_mp_sgd_mom_update", no_grad=True,
          num_outputs=_multi_nout(2))
def preloaded_multi_mp_sgd_mom_update(*data, num_weights=1, momentum=0.0,
                                      rescale_grad=1.0,
                                      clip_gradient=-1.0):
    """ref: optimizer_op.cc preloaded_multi_mp_sgd_mom_update."""
    return _preloaded_pure(multi_mp_sgd_mom_update, 4, data, num_weights,
                           dict(momentum=momentum,
                                rescale_grad=rescale_grad,
                                clip_gradient=clip_gradient))
