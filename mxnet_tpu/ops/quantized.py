"""INT8 quantized operator family under the reference's registry names.

ref: src/operator/quantization/ — quantize_v2.cc, requantize.cc,
calibrate.cc (entropy/KL), quantized_conv.cc, quantized_fully_connected.cc,
quantized_pooling.cc, quantized_activation.cc, quantized_flatten.cc,
quantized_concat.cc, quantized_batch_norm.cc.

Scheme: symmetric int8 (scale = max_abs/127, zero-point 0) like the
reference's default `auto` path for weights. Each quantized op takes
int8 payloads plus their float min/max ranges and returns
(payload, out_min, out_max) exactly like the reference's 3-output
convention; matmul/conv accumulate in int32 (XLA lowers int8 x int8 ->
int32 dot onto the MXU's int path on TPU).

The graph-surgery driver that swaps float layers for these lives in
contrib/quantization.py (quantize_net / calib_graph).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
# direct submodule import: the package __init__ re-exports the
# quantized_matmul FUNCTION under the module's name
from ..pallas_kernels.quantized_matmul import (
    engaged as _qmm_engaged, quantized_matmul as _qmm)

__all__ = []


def _int8_dot(x2, wt):
    """(M, K) int8 @ (K, N) int8 -> (M, N) int32, through the Pallas
    MXU int-path kernel when it engages (TPU + aligned shapes, or the
    ``MXTPU_QUANT_MATMUL=interpret`` test hook) and the XLA int32
    ``dot_general`` otherwise. Integer accumulation is exact, so the
    two paths are bitwise identical."""
    if _qmm_engaged(x2, wt):
        return _qmm(x2, wt)
    return lax.dot_general(x2.astype(jnp.int32), wt.astype(jnp.int32),
                           (((1,), (0,)), ((), ())))


def _scale(mn, mx):
    return jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-12) / 127.0


@register("_contrib_quantize", no_grad=True, aliases=("quantize_v1",))
def quantize_v1(data, min_range, max_range, out_type="int8"):
    """3-in/3-out quantize with explicit range inputs
    (ref: quantization/quantize.cc)."""
    s = _scale(min_range, max_range)
    q = jnp.clip(jnp.round(data / s), -127, 127).astype(jnp.int8)
    return q, jnp.min(min_range), jnp.max(max_range)


@register("_contrib_quantize_v2", no_grad=True, aliases=("quantize_v2",))
def quantize_v2(data, out_type="int8", min_calib_range=None,
                max_calib_range=None):
    """Quantize float->int8; range from calibration params or the data
    (ref: quantization/quantize_v2.cc)."""
    if min_calib_range is not None and max_calib_range is not None:
        mn = jnp.asarray(float(min_calib_range))
        mx = jnp.asarray(float(max_calib_range))
    else:
        mn = jnp.min(data)
        mx = jnp.max(data)
    s = _scale(mn, mx)
    q = jnp.clip(jnp.round(data / s), -127, 127).astype(jnp.int8)
    return q, mn, mx


@register("_contrib_requantize", no_grad=True, aliases=("requantize",))
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 -> int8 rescale (ref: quantization/requantize.cc). The int32
    payload carries scale in_range/2^31; output is int8 at the calibrated
    (or max-abs) range."""
    in_s = jnp.maximum(jnp.maximum(jnp.abs(min_range),
                                   jnp.abs(max_range)), 1e-12) / (2.0 ** 31)
    f = data.astype(jnp.float32) * in_s
    if min_calib_range is not None and max_calib_range is not None:
        mn = jnp.asarray(float(min_calib_range))
        mx = jnp.asarray(float(max_calib_range))
    else:
        mn = jnp.min(f)
        mx = jnp.max(f)
    s = _scale(mn, mx)
    q = jnp.clip(jnp.round(f / s), -127, 127).astype(jnp.int8)
    return q, mn, mx


@register("_contrib_calibrate_entropy", no_grad=True,
          aliases=("calibrate_entropy",), nojit=True)
def calibrate_entropy(hist, hist_edges, num_quantized_bins=255):
    """KL-divergence-optimal calibration threshold from an activation
    histogram (ref: quantization/calibrate.cc). Runs on host numpy (the
    reference is CPU-only too) and returns (min, max) scalars."""
    import numpy as onp
    from ..contrib.quantization import _get_optimal_threshold
    h = onp.asarray(hist)
    e = onp.asarray(hist_edges)
    thr = _get_optimal_threshold(h, e, int(num_quantized_bins))
    return (jnp.asarray(-thr, jnp.float32), jnp.asarray(thr, jnp.float32))


def _deq(q, mn, mx):
    return q.astype(jnp.float32) * _scale(mn, mx)


def _int32_range(sc):
    m = sc * (2.0 ** 31)
    return -m, m


@register("_contrib_quantized_act", no_grad=True, aliases=("quantized_act",))
def quantized_act(data, min_data, max_data, act_type="relu"):
    """int8 activation (ref: quantized_activation.cc); relu keeps the
    range, matching the reference's passthrough min/max."""
    if act_type != "relu":
        raise NotImplementedError("quantized_act supports relu (the "
                                  "reference's only int8 activation)")
    return jnp.maximum(data, 0).astype(jnp.int8), min_data, max_data


@register("_contrib_quantized_flatten", no_grad=True,
          aliases=("quantized_flatten",))
def quantized_flatten(data, min_data, max_data):
    return data.reshape(data.shape[0], -1), min_data, max_data


@register("_contrib_quantized_pooling", no_grad=True,
          aliases=("quantized_pooling",))
def quantized_pooling(data, min_data, max_data, kernel=(2, 2),
                      pool_type="max", stride=(1, 1), pad=(0, 0),
                      global_pool=False):
    """int8 max/avg pooling on NCHW (ref: quantized_pooling.cc)."""
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = int(stride[0]), int(stride[1])
    ph, pw = int(pad[0]), int(pad[1])
    if global_pool:
        kh, kw = data.shape[2], data.shape[3]
        sh = sw = 1
        ph = pw = 0
    x = data.astype(jnp.int32)
    dims = (1, 1, kh, kw)
    strides = (1, 1, sh, sw)
    padding = ((0, 0), (0, 0), (ph, ph), (pw, pw))
    if pool_type == "max":
        out = lax.reduce_window(x, jnp.iinfo(jnp.int32).min, lax.max,
                                dims, strides, padding)
    else:
        out = lax.reduce_window(x, 0, lax.add, dims, strides, padding)
        out = out // (kh * kw)
    return out.astype(jnp.int8), min_data, max_data


@register("_contrib_quantized_elemwise_add", no_grad=True,
          aliases=("quantized_elemwise_add",))
def quantized_elemwise_add(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max):
    """int8 + int8 -> int32 with rescaling to a shared scale
    (ref: quantized_elemwise_add.cc)."""
    f = _deq(lhs, lhs_min, lhs_max) + _deq(rhs, rhs_min, rhs_max)
    mx = jnp.maximum(jnp.maximum(jnp.abs(lhs_min), jnp.abs(lhs_max)),
                     jnp.maximum(jnp.abs(rhs_min), jnp.abs(rhs_max))) * 2
    s = mx / (2.0 ** 31)
    out = jnp.clip(jnp.round(f / jnp.maximum(s, 1e-38)),
                   -(2.0 ** 31 - 1), 2.0 ** 31 - 1).astype(jnp.int32)
    return out, -mx, mx


@register("_contrib_quantized_fully_connected", no_grad=True,
          aliases=("quantized_fully_connected",))
def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias, max_bias,
                              num_hidden=1, no_bias=False, flatten=True):
    """int8 FC -> int32 (ref: quantized_fully_connected.cc). The int8 x
    int8 dot accumulates in int32 on the MXU int path — via the Pallas
    tiled kernel (pallas_kernels/quantized_matmul.py) when it
    engages."""
    x = data.reshape(data.shape[0], -1) if flatten else data
    if x.ndim == 2 and jnp.dtype(x.dtype) == jnp.int8 \
            and jnp.dtype(weight.dtype) == jnp.int8:
        acc = _int8_dot(x, weight.T)
    else:
        acc = lax.dot_general(x.astype(jnp.int32),
                              weight.astype(jnp.int32),
                              (((x.ndim - 1,), (1,)), ((), ())))
    sd = _scale(min_data, max_data)
    sw = _scale(min_weight, max_weight)
    out_scale = sd * sw
    if bias is not None and not no_bias:
        sb = _scale(min_bias, max_bias)
        acc = acc + jnp.round(bias.astype(jnp.float32) * sb
                              / out_scale).astype(jnp.int32)
    mn, mx = _int32_range(out_scale)
    return acc, mn, mx


@register("_contrib_quantized_conv", no_grad=True,
          aliases=("quantized_conv",))
def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias, max_bias, kernel=(1, 1),
                   stride=(1, 1), pad=(0, 0), dilate=(1, 1), num_filter=1,
                   num_group=1, no_bias=False, layout="NCHW"):
    """int8 conv -> int32 (ref: quantized_conv.cc). 1x1/stride-1
    convolutions — the ResNet bottleneck reductions that dominate
    quantized inference — are a plain matmul over the flattened
    spatial positions and route through the Pallas int8 kernel when it
    engages; everything else stays on the XLA int32 conv."""
    sh, sw = int(stride[0]), int(stride[1])
    ph, pw = int(pad[0]), int(pad[1])
    dh, dw = int(dilate[0]), int(dilate[1])
    n, ci, h, w_sp = data.shape
    is_1x1 = (weight.shape[2:] == (1, 1) and (sh, sw) == (1, 1)
              and (ph, pw) == (0, 0) and int(num_group) == 1
              and jnp.dtype(data.dtype) == jnp.int8
              and jnp.dtype(weight.dtype) == jnp.int8)
    if is_1x1:
        x2 = jnp.transpose(data, (0, 2, 3, 1)).reshape(-1, ci)
        wt = weight.reshape(weight.shape[0], ci).T     # (Ci, Co)
        acc = _int8_dot(x2, wt)
        acc = jnp.transpose(
            acc.reshape(n, h, w_sp, weight.shape[0]), (0, 3, 1, 2))
    else:
        acc = lax.conv_general_dilated(
            data.astype(jnp.int32), weight.astype(jnp.int32),
            window_strides=(sh, sw), padding=((ph, ph), (pw, pw)),
            rhs_dilation=(dh, dw), feature_group_count=int(num_group),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
    sd = _scale(min_data, max_data)
    sw_ = _scale(min_weight, max_weight)
    out_scale = sd * sw_
    if bias is not None and not no_bias:
        sb = _scale(min_bias, max_bias)
        acc = acc + jnp.round(bias.astype(jnp.float32) * sb
                              / out_scale).astype(jnp.int32).reshape(
                                  1, -1, 1, 1)
    mn, mx = _int32_range(out_scale)
    return acc, mn, mx


@register("_contrib_quantized_concat", no_grad=True,
          aliases=("quantized_concat",))
def quantized_concat(*args, dim=1, num_args=None):
    """Concat int8 payloads after rescaling to the widest input range
    (ref: quantized_concat.cc). Inputs: d0..dk, min0, max0, ..."""
    k = len(args) // 3
    datas = args[:k]
    mins = args[k::2][:k]
    maxs = args[k + 1::2][:k]
    mx = jnp.stack([jnp.maximum(jnp.abs(a), jnp.abs(b))
                    for a, b in zip(mins, maxs)]).max()
    s_out = mx / 127.0
    parts = []
    for d, mn_i, mx_i in zip(datas, mins, maxs):
        f = _deq(d, mn_i, mx_i)
        parts.append(jnp.clip(jnp.round(f / s_out), -127, 127)
                     .astype(jnp.int8))
    return jnp.concatenate(parts, axis=int(dim)), -mx, mx


@register("_contrib_quantized_batch_norm", no_grad=True,
          aliases=("quantized_batch_norm",))
def quantized_batch_norm(data, gamma, beta, moving_mean, moving_var,
                         min_data, max_data, eps=1e-3,
                         min_calib_range=None, max_calib_range=None):
    """int8 BN using folded scale/shift, re-quantized to the calibrated
    range (ref: quantized_batch_norm.cc)."""
    f = _deq(data, min_data, max_data)
    inv = 1.0 / jnp.sqrt(moving_var + eps)
    f = (f - moving_mean.reshape(1, -1, 1, 1)) \
        * (gamma * inv).reshape(1, -1, 1, 1) + beta.reshape(1, -1, 1, 1)
    if min_calib_range is not None:
        mn = jnp.asarray(float(min_calib_range))
        mx = jnp.asarray(float(max_calib_range))
    else:
        mn, mx = jnp.min(f), jnp.max(f)
    s = _scale(mn, mx)
    return (jnp.clip(jnp.round(f / s), -127, 127).astype(jnp.int8), mn, mx)
