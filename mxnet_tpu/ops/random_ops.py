"""Random sampling ops.

TPU-native re-design of the reference's random operator family
(ref: src/operator/random/sample_op.cc, multisample_op.cc,
unique_sample_op.cc, src/common/random_generator.h). Every op takes an
explicit ``key`` (threaded by the NDArray wrapper from the global / trace RNG
in mxnet_tpu/random.py) — functional purity keeps them jittable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _dt(dtype):
    return jnp.dtype(dtype if dtype and dtype != "None" else "float32")


@register("random_uniform", no_grad=True, aliases=("uniform", "_random_uniform"))
def random_uniform(key=None, low=0.0, high=1.0, shape=(), dtype="float32", ctx=None):
    return jax.random.uniform(key, shape, _dt(dtype), low, high)


@register("random_normal", no_grad=True,
          aliases=("normal", "_random_normal", "randn"))
def random_normal(key=None, loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None):
    return loc + scale * jax.random.normal(key, shape, _dt(dtype))


@register("random_gamma", no_grad=True, aliases=("_random_gamma",))
def random_gamma(key=None, alpha=1.0, beta=1.0, shape=(), dtype="float32", ctx=None):
    return jax.random.gamma(key, alpha, shape, _dt(dtype)) * beta


@register("random_exponential", no_grad=True, aliases=("_random_exponential",))
def random_exponential(key=None, lam=1.0, shape=(), dtype="float32", ctx=None):
    return jax.random.exponential(key, shape, _dt(dtype)) / lam


@register("random_poisson", no_grad=True, aliases=("_random_poisson",))
def random_poisson(key=None, lam=1.0, shape=(), dtype="float32", ctx=None):
    return jax.random.poisson(key, lam, shape).astype(_dt(dtype))


@register("random_negative_binomial", no_grad=True,
          aliases=("_random_negative_binomial",))
def random_negative_binomial(key=None, k=1, p=1.0, shape=(), dtype="float32",
                             ctx=None):
    # NB(k, p) == Poisson(Gamma(k, (1-p)/p))
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, k, shape) * ((1 - p) / p)
    return jax.random.poisson(kp, lam, shape).astype(_dt(dtype))


@register("random_generalized_negative_binomial", no_grad=True,
          aliases=("_random_generalized_negative_binomial",))
def random_generalized_negative_binomial(key=None, mu=1.0, alpha=1.0, shape=(),
                                         dtype="float32", ctx=None):
    if alpha <= 0:
        return jax.random.poisson(key, mu, shape).astype(_dt(dtype))
    kg, kp = jax.random.split(key)
    r = 1.0 / alpha
    lam = jax.random.gamma(kg, r, shape) * (mu * alpha)
    return jax.random.poisson(kp, lam, shape).astype(_dt(dtype))


@register("random_randint", no_grad=True, aliases=("randint", "_random_randint"))
def random_randint(key=None, low=0, high=1, shape=(), dtype="int32", ctx=None):
    return jax.random.randint(key, shape, low, high, _dt(dtype))


@register("sample_uniform", no_grad=True, num_inputs=2)
def sample_uniform(low, high, key=None, shape=(), dtype="float32"):
    shp = low.shape + (tuple(shape) if shape else ())
    u = jax.random.uniform(key, shp, _dt(dtype))
    ex = (Ellipsis,) + (None,) * (len(shp) - low.ndim)
    return low[ex] + u * (high - low)[ex]


@register("sample_normal", no_grad=True, num_inputs=2)
def sample_normal(mu, sigma, key=None, shape=(), dtype="float32"):
    shp = mu.shape + (tuple(shape) if shape else ())
    z = jax.random.normal(key, shp, _dt(dtype))
    ex = (Ellipsis,) + (None,) * (len(shp) - mu.ndim)
    return mu[ex] + z * sigma[ex]


@register("sample_gamma", no_grad=True, num_inputs=2)
def sample_gamma(alpha, beta, key=None, shape=(), dtype="float32"):
    shp = alpha.shape + (tuple(shape) if shape else ())
    ex = (Ellipsis,) + (None,) * (len(shp) - alpha.ndim)
    g = jax.random.gamma(key, jnp.broadcast_to(alpha[ex], shp), dtype=_dt(dtype))
    return g * beta[ex]


@register("sample_multinomial", no_grad=True, num_inputs=1,
          aliases=("multinomial", "_sample_multinomial"))
def sample_multinomial(data, key=None, shape=(), get_prob=False, dtype="int32"):
    # data: (..., k) probabilities; sample `shape` draws per distribution
    nsamp = 1
    if shape:
        for s in (shape if isinstance(shape, (tuple, list)) else (shape,)):
            nsamp *= s
    logits = jnp.log(jnp.maximum(data, 1e-37))
    draws = jax.random.categorical(key, logits, axis=-1,
                                   shape=(nsamp,) + data.shape[:-1])
    draws = jnp.moveaxis(draws, 0, -1)
    out_shape = data.shape[:-1] + (tuple(shape) if shape else ())
    if not shape:
        draws = draws[..., 0]
        out_shape = data.shape[:-1]
    samples = draws.reshape(out_shape).astype(_dt(dtype))
    if get_prob:
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(logits),
            samples.astype(jnp.int32).reshape(data.shape[:-1] + (-1,)),
            axis=-1).reshape(out_shape)
        return samples, lp
    return samples


@register("shuffle", no_grad=True, num_inputs=1, aliases=("_shuffle",))
def shuffle(data, key=None):
    return jax.random.permutation(key, data, axis=0)


@register("bernoulli", no_grad=True, num_inputs=1)
def bernoulli(p, key=None, dtype="float32"):
    return jax.random.bernoulli(key, p).astype(_dt(dtype))
