"""CTC loss — XLA-native replacement for warp-ctc.

The reference binds Baidu warp-ctc headers (ref: src/operator/nn/ctc_loss.cc,
3rdparty/ctc_include/). Here the standard alpha (forward) recursion runs in
log space under ``lax.scan`` — static shapes, masked variable lengths — so it
compiles to one fused TPU loop instead of a custom CUDA kernel.

Conventions (matching gluon.loss.CTCLoss, ref: python/mxnet/gluon/loss.py):
- blank index = 0
- labels padded with negative values (or pass label_lengths)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

_NEG = -1e30


@register("ctc_loss", aliases=("CTCLoss", "contrib_ctc_loss"))
def ctc_loss(pred, label, pred_lengths=None, label_lengths=None,
             layout="NTC", label_layout="NT"):
    if layout == "TNC":
        pred = jnp.transpose(pred, (1, 0, 2))
    if label_layout == "TN":
        label = jnp.transpose(label)
    N, T, C = pred.shape
    L = label.shape[1]
    S = 2 * L + 1

    logp = jax.nn.log_softmax(pred, axis=-1)          # (N, T, C)
    lab = label.astype(jnp.int32)
    valid_lab = lab >= 0
    if label_lengths is None:
        lab_len = valid_lab.astype(jnp.int32).sum(axis=1)
    else:
        lab_len = label_lengths.astype(jnp.int32)
    if pred_lengths is None:
        pred_len = jnp.full((N,), T, jnp.int32)
    else:
        pred_len = pred_lengths.astype(jnp.int32)

    lab_safe = jnp.where(valid_lab, lab, 0)
    # extended label sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.zeros((N, S), jnp.int32)
    ext = ext.at[:, 1::2].set(lab_safe)

    pos = jnp.arange(S)[None, :]                       # (1, S)
    valid_pos = pos < (2 * lab_len[:, None] + 1)

    # skip transition allowed when s odd-label differs from label two back
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)))[:, :S]
    can_skip = (pos >= 2) & (pos % 2 == 1) & (ext != ext_m2)

    def emit(t):
        return jnp.take_along_axis(logp[:, t, :], ext, axis=1)  # (N, S)

    alpha0 = jnp.full((N, S), _NEG)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, 0])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(lab_len > 0, jnp.take_along_axis(
            logp[:, 0, :], lab_safe[:, :1], axis=1)[:, 0], _NEG))
    alpha0 = jnp.where(valid_pos, alpha0, _NEG)

    def step(alpha, t):
        a0 = alpha
        a1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=_NEG)[:, :S]
        a2 = jnp.where(can_skip,
                       jnp.pad(alpha, ((0, 0), (2, 0)),
                               constant_values=_NEG)[:, :S], _NEG)
        stacked = jnp.stack([a0, a1, a2])
        new = jax.scipy.special.logsumexp(stacked, axis=0) + emit(t)
        new = jnp.where(valid_pos, new, _NEG)
        # freeze once past this sequence's length
        active = (t < pred_len)[:, None]
        new = jnp.where(active, new, alpha)
        return new, None

    alphaT, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))

    last = 2 * lab_len                                  # blank at end
    a_last = jnp.take_along_axis(alphaT, last[:, None], axis=1)[:, 0]
    a_prev = jnp.where(
        lab_len > 0,
        jnp.take_along_axis(alphaT, jnp.maximum(last - 1, 0)[:, None],
                            axis=1)[:, 0], _NEG)
    ll = jax.scipy.special.logsumexp(jnp.stack([a_last, a_prev]), axis=0)
    return -ll
