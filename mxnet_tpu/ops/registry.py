"""Operator registry.

TPU-native analog of the nnvm op registry + FCompute attribute system
(ref: include/mxnet/op_attr_types.h:124-304, src/operator/ NNVM_REGISTER_OP).
Each op is a pure function on jax arrays: ``fn(*arrays, **static_params)``.
Gradients come from ``jax.vjp`` of the same function, so there is no separate
FGradient registration; XLA fuses the forward and backward pipelines.

The Python user-facing wrappers (NDArray level, autograd-aware) are generated
from this registry by ``mxnet_tpu/ndarray/register.py``, mirroring how the
reference autogenerates wrappers at import time
(ref: python/mxnet/ndarray/register.py).
"""
from __future__ import annotations

__all__ = ["register", "get_op", "list_ops", "OpDef"]

_OPS = {}  # mxlint: disable=MX003 (populated by register() at import/plugin-load time; plugin loads serialize under lib_api's load lock)


class OpDef:
    __slots__ = ("name", "fn", "no_grad", "num_inputs", "aliases",
                 "wrap_kwargs", "num_outputs", "input_names", "nojit",
                 "inplace")

    def __init__(self, name, fn, no_grad=False, num_inputs=None, aliases=(),
                 wrap_kwargs=None, num_outputs=None, input_names=None,
                 nojit=False, inplace=()):
        self.name = name
        self.fn = fn
        self.no_grad = no_grad          # outputs not differentiable (int/bool)
        self.num_inputs = num_inputs    # None = variadic / inspect at call
        self.aliases = aliases
        self.wrap_kwargs = wrap_kwargs or {}
        # symbol-graph output count: int, or callable(attrs) -> int for
        # attr-dependent counts (the reference's FNumOutputs); None = 1
        self.num_outputs = num_outputs
        # explicit ordered tensor-input names; None = derive from the fn
        # signature via the INPUT_PARAM_NAMES heuristic (symbol frontend)
        self.input_names = input_names
        # opt-out of the imperative jitted dispatch cache + bulking
        # (host callbacks, data-dependent output shapes); the eager
        # untraced path is always used for these
        self.nojit = nojit
        # positional tensor-input indices the op conceptually overwrites —
        # the reference's ``req='write'`` analog (kWriteInplace,
        # op_attr_types.h). The jitted dispatch path donates these input
        # buffers to XLA so the update can reuse them in place.
        self.inplace = tuple(inplace)


def register(name, no_grad=False, num_inputs=None, aliases=(),
             num_outputs=None, input_names=None, nojit=False, inplace=()):
    """Decorator: register a functional op under ``name`` (+ aliases)."""
    def _reg(fn):
        opdef = OpDef(name, fn, no_grad=no_grad, num_inputs=num_inputs,
                      aliases=aliases, num_outputs=num_outputs,
                      input_names=input_names, nojit=nojit, inplace=inplace)
        _OPS[name] = opdef
        for a in aliases:
            _OPS[a] = opdef
        return fn
    return _reg


def get_op(name):
    return _OPS[name]


def list_ops():
    return sorted(_OPS)
