"""Remaining operator long tail: point processes, sketching, index
utilities, sparsity regularization, window functions.

Reference sources:
- hawkesll: src/operator/contrib/hawkes_ll.cc:40 (+hawkes_ll-inl.h:112
  forward kernel, :161 compensator) — marked Hawkes process
  log-likelihood with exponential kernel
- count_sketch: src/operator/contrib/count_sketch.cc:65
  (+count_sketch-inl.h:58) — Count Sketch projection (compact bilinear
  pooling building block)
- index_array: src/operator/contrib/index_array.cc:120 — per-element
  coordinate array
- IdentityAttachKLSparseReg: src/operator/identity_attach_KL_sparse_reg.cc:56
  — identity forward + KL sparseness penalty on the gradient
- _npi_hanning/_npi_hamming/_npi_blackman:
  src/operator/numpy/np_window_op.cc — NumPy-compatible window functions
- _rnn_param_concat: src/operator/nn/concat.cc (_rnn_param_concat
  registration) — concat variant used to pack fused-RNN parameters

TPU-first: hawkesll's sequential event loop is a lax.scan over the time
axis (vmapped over the batch) — gradients come from jax autodiff of the
scan instead of the reference's hand-written backward kernels.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = []


# ---------------------------------------------------------------------------
# Hawkes process log-likelihood
# ---------------------------------------------------------------------------

@register("_contrib_hawkesll", aliases=("hawkesll",))
def hawkesll(mu, alpha, beta, state, lags, marks, valid_length, max_time):
    """Log-likelihood of a marked self-exciting Hawkes process with an
    exponential decay kernel, one sequence per batch row
    (ref: contrib/hawkes_ll-inl.h:112 hawkesll_forward, :161
    compensator). Inputs: mu (N,K), alpha (K,), beta (K,), state (N,K),
    lags (N,T) interarrival times, marks (N,T) int, valid_length (N,),
    max_time (N,). Returns (loglike (N,), out_state (N,K))."""
    K = mu.shape[1]
    marks = marks.astype(jnp.int32)

    def one(mu_i, state_i, lags_i, marks_i, vl_i, mt_i):
        def step(carry, inp):
            ll, st, last, t = carry
            lag, mark, j = inp
            onehot = (jnp.arange(K) == mark)
            t_new = t + lag
            d = t_new - last
            ed = jnp.exp(-beta * d)
            lda = mu_i + alpha * beta * st * ed
            comp = mu_i * d + alpha * st * (1.0 - ed)
            active = j < vl_i
            contrib = jnp.where(onehot, jnp.log(lda) - comp, 0.0).sum()
            ll = ll + jnp.where(active, contrib, 0.0)
            upd = jnp.where(active & onehot, 1.0 + st * ed, st)
            last_upd = jnp.where(active & onehot, t_new, last)
            t = jnp.where(active, t_new, t)
            return (ll, upd, last_upd, t), None

        init = (jnp.zeros(()), state_i, jnp.zeros((K,)), jnp.zeros(()))
        T = lags_i.shape[0]
        (ll, st, last, _t), _ = lax.scan(
            step, init, (lags_i, marks_i, jnp.arange(T)))
        # remaining compensator to max_time (ref: hawkes_ll-inl.h:161)
        d = mt_i - last
        ed = jnp.exp(-beta * d)
        rem = mu_i * d + alpha * st * (1.0 - ed)
        return ll - rem.sum(), ed * st

    return jax.vmap(one)(mu, state, lags, marks, valid_length, max_time)


# ---------------------------------------------------------------------------
# Count sketch
# ---------------------------------------------------------------------------

@register("_contrib_count_sketch", aliases=("count_sketch",))
def count_sketch(data, h, s, out_dim=1, processing_batch_size=32):
    """Count Sketch: out[n, h[i]] += s[i] * data[n, i]
    (ref: contrib/count_sketch-inl.h:58). h/s are 1D (hash bucket per
    input dim, sign ±1). Leading dims beyond the last are preserved
    (the reference FlatTo2D's 4D inputs the same way)."""
    D = data.shape[-1]
    lead = data.shape[:-1]
    x = data.reshape(-1, D)
    hh = h.reshape(-1).astype(jnp.int32)[:D]
    ss = s.reshape(-1)[:D]
    signed = x * ss[None, :]
    out = jnp.zeros((x.shape[0], int(out_dim)), data.dtype)
    out = out.at[:, hh].add(signed)
    return out.reshape(lead + (int(out_dim),))


# ---------------------------------------------------------------------------
# index_array
# ---------------------------------------------------------------------------

@register("_contrib_index_array", no_grad=True, aliases=("index_array",))
def index_array(data, axes=None):
    """N-D coordinate array: out[i0..ik, :] = (i0..ik) (or the subset
    named by `axes`), int64 (ref: contrib/index_array.cc:120)."""
    shape = data.shape
    nd = len(shape)
    ax = list(range(nd)) if axes is None else [int(a) % nd for a in axes]
    # reference emits int64; without the x64 flag jax ints are 32-bit,
    # which covers any shape a single chip can hold
    idt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    coords = [lax.broadcasted_iota(idt, shape, a) for a in ax]
    return jnp.stack(coords, axis=-1)


# ---------------------------------------------------------------------------
# IdentityAttachKLSparseReg
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _kl_sparse_reg(data, sparseness_target, penalty, momentum):
    return data


def _kl_fwd(data, sparseness_target, penalty, momentum):
    return data, data


def _kl_bwd(sparseness_target, penalty, momentum, data, g):
    # rho_hat: batch-mean activation per unit (the reference keeps a
    # momentum moving average in an aux state; the functional design uses
    # the batch mean — the momentum=0 case — documented deviation)
    x2 = data.reshape(data.shape[0], -1)
    rho_hat = jnp.mean(x2, axis=0)
    reg = penalty * (-sparseness_target / rho_hat
                     + (1.0 - sparseness_target) / (1.0 - rho_hat))
    return (g + reg.reshape((1,) + data.shape[1:]).astype(g.dtype),)


_kl_sparse_reg.defvjp(_kl_fwd, _kl_bwd)


@register("IdentityAttachKLSparseReg",
          aliases=("identity_attach_kl_sparse_reg",))
def identity_attach_kl_sparse_reg(data, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9):
    """Identity forward; attaches the KL sparseness penalty gradient
    d/dx KL(rho || rho_hat) on the way back
    (ref: src/operator/identity_attach_KL_sparse_reg-inl.h:100-111)."""
    return _kl_sparse_reg(data, float(sparseness_target), float(penalty),
                          float(momentum))


# ---------------------------------------------------------------------------
# NumPy window functions
# ---------------------------------------------------------------------------

@register("_npi_hanning", num_inputs=0, no_grad=True, aliases=("hanning",))
def hanning(M=1, dtype="float32", ctx=None):
    """ref: src/operator/numpy/np_window_op.cc (numpy semantics)."""
    M = int(M)
    if M < 1:
        return jnp.zeros((0,), dtype)
    if M == 1:
        return jnp.ones((1,), dtype)
    n = jnp.arange(M, dtype=jnp.float32)
    return (0.5 - 0.5 * jnp.cos(2.0 * jnp.pi * n / (M - 1))).astype(dtype)


@register("_npi_hamming", num_inputs=0, no_grad=True, aliases=("hamming",))
def hamming(M=1, dtype="float32", ctx=None):
    M = int(M)
    if M < 1:
        return jnp.zeros((0,), dtype)
    if M == 1:
        return jnp.ones((1,), dtype)
    n = jnp.arange(M, dtype=jnp.float32)
    return (0.54 - 0.46 * jnp.cos(2.0 * jnp.pi * n / (M - 1))).astype(dtype)


@register("_npi_blackman", num_inputs=0, no_grad=True, aliases=("blackman",))
def blackman(M=1, dtype="float32", ctx=None):
    M = int(M)
    if M < 1:
        return jnp.zeros((0,), dtype)
    if M == 1:
        return jnp.ones((1,), dtype)
    n = jnp.arange(M, dtype=jnp.float32)
    w = (0.42 - 0.5 * jnp.cos(2.0 * jnp.pi * n / (M - 1))
         + 0.08 * jnp.cos(4.0 * jnp.pi * n / (M - 1)))
    return w.astype(dtype)


# ---------------------------------------------------------------------------
# _rnn_param_concat
# ---------------------------------------------------------------------------

@register("_rnn_param_concat")
def rnn_param_concat(*args, dim=0, num_args=None):
    """Concat used to pack fused-RNN parameter blobs (ref:
    src/operator/nn/concat.cc _rnn_param_concat — same compute as
    Concat, different shape-inference for the packed-weight vector)."""
    return jnp.concatenate([a.reshape(-1) if a.ndim == 1 else a
                            for a in args], axis=int(dim))
