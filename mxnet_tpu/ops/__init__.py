"""Functional operator layer: pure jax-array functions behind a registry.

This package replaces the reference's src/operator/ C++/CUDA kernel corpus
(578 files; ref: SURVEY.md §2.1) with XLA-lowered pure functions. Import
order registers the op families; user-facing NDArray/Symbol wrappers are
generated from the registry (mxnet_tpu/ndarray/register.py).
"""
from .registry import register, get_op, list_ops, OpDef
from . import elemwise       # noqa: F401
from . import tensor         # noqa: F401
from . import linalg         # noqa: F401
from . import nn             # noqa: F401
from . import random_ops     # noqa: F401
from . import ctc            # noqa: F401
from . import extended       # noqa: F401  (after nn: aliases core ops)
from . import detection      # noqa: F401  (Faster-RCNN/R-FCN/SSD family)
from . import image          # noqa: F401  (mx.nd.image namespace ops)
from . import optimizer_ops  # noqa: F401  (pure fused update ops)
from . import misc_tail      # noqa: F401  (hawkesll/count_sketch/...)
from . import quantized      # noqa: F401  (INT8 op family)

__all__ = ["register", "get_op", "list_ops", "OpDef"]
