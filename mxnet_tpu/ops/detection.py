"""Detection-model operator family: deformable conv, position-sensitive
ROI pooling, RPN proposals, SSD target assignment, rotated ROI align.

These unlock the reference's flagship detection workloads (Faster-RCNN /
R-FCN / Deformable-ConvNets / SSD examples). Reference sources:
- DeformableConvolution: src/operator/contrib/deformable_convolution.cc:93
  (+ nn/deformable_im2col.h:239 offset layout: per deformable group,
  channel 2*(i*kw+j) is the h-offset, +1 the w-offset)
- PSROIPooling: src/operator/contrib/psroi_pooling.cc:56-110
- DeformablePSROIPooling: src/operator/contrib/deformable_psroi_pooling.cc:60-146
- Proposal: src/operator/contrib/proposal.cc:281-420 (+proposal-inl.h:213
  GenerateAnchors)
- MultiProposal: src/operator/contrib/multi_proposal.cc (batched Proposal)
- MultiBoxTarget: src/operator/contrib/multibox_target.cc:71-281
- RROIAlign: src/operator/contrib/rroi_align.cc:40-210
- Crop: src/operator/crop.cc

TPU-first design notes: every op is jit-safe — static output shapes, no
data-dependent Python control flow. Data-dependent loop bounds in the
reference (integer ROI bins, greedy bipartite matching, NMS) become
masked reductions / lax.fori_loop with static trip counts. Sorting
replaces compaction; invalid slots carry sentinel values exactly like
the reference's -1 markers.
"""
from __future__ import annotations

import math

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = []


# ---------------------------------------------------------------------------
# bilinear sampling helpers
# ---------------------------------------------------------------------------

def _bilinear_gather(img, y, x):
    """Sample img [H, W] at float coords (y, x) (any broadcastable shape)
    with zero padding outside [-1, H/W] and edge clamping inside, matching
    im2col_bilinear_cpu (ref: contrib/nn/deformable_im2col.h:75)."""
    H, W = img.shape
    valid = (y > -1.0) & (y < H) & (x > -1.0) & (x < W)
    y = jnp.clip(y, 0.0, H - 1.0)
    x = jnp.clip(x, 0.0, W - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly = y - y0
    lx = x - x0
    v00 = img[y0, x0]
    v01 = img[y0, x1]
    v10 = img[y1, x0]
    v11 = img[y1, x1]
    out = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
           + v10 * ly * (1 - lx) + v11 * ly * lx)
    return jnp.where(valid, out, 0.0)


# ---------------------------------------------------------------------------
# DeformableConvolution
# ---------------------------------------------------------------------------

@register("_contrib_DeformableConvolution", aliases=("DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=1, num_group=1,
                           num_deformable_group=1, no_bias=False,
                           workspace=1024, layout=None):
    """Deformable convolution v1 (Dai et al.).

    data [N,C,H,W]; offset [N, 2*dg*kh*kw, H', W'] (per deformable group,
    channel 2*(i*kw+j) = h-offset, 2*(i*kw+j)+1 = w-offset — ref:
    contrib/nn/deformable_im2col.h:239); weight [F, C/num_group, kh, kw].

    Implementation: deformable im2col as a batched bilinear gather per
    static kernel tap (kh*kw python loop — unrolled in the jaxpr), then
    one grouped matmul on the MXU. The O(S^2)-free gather dominates HBM
    traffic exactly like the reference's deformable_im2col buffer.
    """
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = (int(stride[0]), int(stride[1])) if stride else (1, 1)
    dh, dw = (int(dilate[0]), int(dilate[1])) if dilate else (1, 1)
    ph, pw = (int(pad[0]), int(pad[1])) if pad else (0, 0)
    ng = int(num_group)
    dg = int(num_deformable_group)
    F = int(num_filter)
    N, C, H, W = data.shape
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1

    # base sampling grid per output position
    hs = jnp.arange(Ho) * sh - ph          # (Ho,)
    ws = jnp.arange(Wo) * sw - pw          # (Wo,)
    off = offset.reshape(N, dg, kh * kw, 2, Ho, Wo)

    cols = []  # per kernel tap: (N, C, Ho, Wo)
    sample = jax.vmap(jax.vmap(_bilinear_gather, (0, 0, 0)),
                      (0, 0, 0))           # over (N, C_dg)
    cpg = C // dg                          # channels per deformable group
    for i in range(kh):
        for j in range(kw):
            t = i * kw + j
            # (N, dg, Ho, Wo) absolute sample coords for this tap
            y = hs[None, None, :, None] + i * dh + off[:, :, t, 0]
            x = ws[None, None, None, :] + j * dw + off[:, :, t, 1]
            # broadcast coords over the channels of each deformable group
            yb = jnp.repeat(y, cpg, axis=1).reshape(N, C, Ho, Wo)
            xb = jnp.repeat(x, cpg, axis=1).reshape(N, C, Ho, Wo)
            cols.append(sample(data, yb, xb))
    # (N, C, kh*kw, Ho, Wo)
    col = jnp.stack(cols, axis=2)

    cg = C // ng
    col = col.reshape(N, ng, cg * kh * kw, Ho * Wo)
    wr = weight.reshape(ng, F // ng, cg * kh * kw)
    out = jnp.einsum("ngkp,gfk->ngfp", col, wr,
                     preferred_element_type=jnp.float32)
    out = out.reshape(N, F, Ho, Wo).astype(data.dtype)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, F, 1, 1)
    return out


# ---------------------------------------------------------------------------
# PSROIPooling
# ---------------------------------------------------------------------------

@register("_contrib_PSROIPooling", aliases=("PSROIPooling",))
def psroi_pooling(data, rois, spatial_scale=1.0, output_dim=1,
                  pooled_size=1, group_size=0):
    """Position-sensitive ROI pooling (R-FCN). data [N, OD*G*G, H, W],
    rois [R, 5] = (batch_idx, x1, y1, x2, y2) in image coords.

    ref: src/operator/contrib/psroi_pooling.cc:56-110 — integer bin
    [floor, ceil) bounds, plain average, empty bin -> 0. The reference's
    data-dependent bin loops become masked means over the full H/W axes
    (mask = idx in [hstart, hend)), which is jit-safe and keeps the
    reduction on-device.
    """
    G = int(group_size) or int(pooled_size)
    P = int(pooled_size)
    OD = int(output_dim)
    N, C, H, W = data.shape
    R = rois.shape[0]
    scale = float(spatial_scale)

    batch = rois[:, 0].astype(jnp.int32)
    x1 = jnp.round(rois[:, 1]) * scale
    y1 = jnp.round(rois[:, 2]) * scale
    x2 = jnp.round(rois[:, 3] + 1.0) * scale
    y2 = jnp.round(rois[:, 4] + 1.0) * scale
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)
    bin_h = rh / P                              # (R,)
    bin_w = rw / P

    phs = jnp.arange(P, dtype=data.dtype)
    hstart = jnp.floor(phs[None, :] * bin_h[:, None] + y1[:, None])
    hend = jnp.ceil((phs[None, :] + 1) * bin_h[:, None] + y1[:, None])
    wstart = jnp.floor(phs[None, :] * bin_w[:, None] + x1[:, None])
    wend = jnp.ceil((phs[None, :] + 1) * bin_w[:, None] + x1[:, None])
    hstart = jnp.clip(hstart, 0, H)
    hend = jnp.clip(hend, 0, H)
    wstart = jnp.clip(wstart, 0, W)
    wend = jnp.clip(wend, 0, W)

    hidx = jnp.arange(H, dtype=data.dtype)
    widx = jnp.arange(W, dtype=data.dtype)
    # (R, P, H) / (R, P, W) bin membership masks
    mh = ((hidx[None, None, :] >= hstart[:, :, None])
          & (hidx[None, None, :] < hend[:, :, None])).astype(data.dtype)
    mw = ((widx[None, None, :] >= wstart[:, :, None])
          & (widx[None, None, :] < wend[:, :, None])).astype(data.dtype)

    # static position-sensitive channel map c[ctop, ph, pw]
    gh = _np.minimum(_np.maximum(
        _np.floor(_np.arange(P) * G / P), 0), G - 1).astype(_np.int32)
    cmap = ((_np.arange(OD)[:, None, None] * G + gh[None, :, None]) * G
            + gh[None, None, :])                # (OD, P, P)
    cmap = jnp.asarray(cmap)

    dr = data[batch]                            # (R, C, H, W)
    dsel = dr[:, cmap]                          # (R, OD, P, P, H, W)
    num = jnp.einsum("rcijhw,rih,rjw->rcij", dsel, mh, mw)
    cnt = jnp.einsum("rih,rjw->rij", mh, mw)[:, None]
    out = jnp.where(cnt > 0, num / jnp.maximum(cnt, 1.0), 0.0)
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# DeformablePSROIPooling
# ---------------------------------------------------------------------------

@register("_contrib_DeformablePSROIPooling",
          aliases=("DeformablePSROIPooling",))
def deformable_psroi_pooling(data, rois, trans=None, spatial_scale=1.0,
                             output_dim=1, group_size=1, pooled_size=1,
                             part_size=0, sample_per_part=1, trans_std=0.0,
                             no_trans=False):
    """Deformable position-sensitive ROI pooling (Deformable ConvNets).

    ref: src/operator/contrib/deformable_psroi_pooling.cc:60-146. Each
    output bin averages sample_per_part^2 bilinear samples at positions
    shifted by the (class-shared) trans offsets; samples outside
    [-0.5, size-0.5] are dropped from both sum and count.
    """
    P = int(pooled_size)
    G = int(group_size)
    OD = int(output_dim)
    PS = int(part_size) or P
    SP = int(sample_per_part)
    scale = float(spatial_scale)
    tstd = float(trans_std)
    N, C, H, W = data.shape
    R = rois.shape[0]

    batch = rois[:, 0].astype(jnp.int32)
    x1 = jnp.round(rois[:, 1]) * scale - 0.5
    y1 = jnp.round(rois[:, 2]) * scale - 0.5
    x2 = (jnp.round(rois[:, 3]) + 1.0) * scale - 0.5
    y2 = (jnp.round(rois[:, 4]) + 1.0) * scale - 0.5
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)
    bin_h = rh / P
    bin_w = rw / P
    sub_h = bin_h / SP
    sub_w = bin_w / SP

    # static per-bin part / group indices
    part = _np.floor(_np.arange(P) / P * PS).astype(_np.int32)
    ghs = _np.minimum(_np.maximum(
        _np.floor(_np.arange(P) * G / P), 0), G - 1).astype(_np.int32)

    if no_trans or trans is None:
        n_classes = 1
        tx = jnp.zeros((R, 1, P, P), data.dtype)
        ty = jnp.zeros((R, 1, P, P), data.dtype)
    else:
        n_classes = trans.shape[1] // 2
        # trans [R, 2*n_classes, PS, PS]; class of ctop = ctop // (OD/ncls)
        tr = trans.reshape(R, n_classes, 2, PS, PS)
        tx = tr[:, :, 0][:, :, part][:, :, :, part] * tstd  # (R,ncls,P,P)
        ty = tr[:, :, 1][:, :, part][:, :, :, part] * tstd

    cls_of = _np.arange(OD) // max(1, OD // n_classes)      # (OD,)

    # sample coordinates per (R, OD?, ph, pw, ih, iw): class only affects
    # the trans offsets
    phs = jnp.arange(P, dtype=data.dtype)
    ih = jnp.arange(SP, dtype=data.dtype)
    # base start per (R, ph/pw)
    hstart0 = phs[None, :] * bin_h[:, None] + y1[:, None]   # (R, P)
    wstart0 = phs[None, :] * bin_w[:, None] + x1[:, None]

    # (R, ncls, P, P)
    hstart = hstart0[:, None, :, None] + ty * rh[:, None, None, None]
    wstart = wstart0[:, None, None, :] + tx * rw[:, None, None, None]
    # (R, ncls, P, P, SP, SP)
    ys = hstart[..., None, None] + ih[:, None] * sub_h[:, None, None, None,
                                                       None, None]
    xs = wstart[..., None, None] + ih[None, :] * sub_w[:, None, None, None,
                                                       None, None]
    ys, xs = jnp.broadcast_arrays(ys, xs)
    valid = ((ys >= -0.5) & (ys <= H - 0.5)
             & (xs >= -0.5) & (xs <= W - 0.5))
    yc = jnp.clip(ys, 0.0, H - 1.0)
    xc = jnp.clip(xs, 0.0, W - 1.0)

    # channel map (OD, P, P) like PSROIPooling
    cmap = ((_np.arange(OD)[:, None, None] * G + ghs[None, :, None]) * G
            + ghs[None, None, :])
    dr = data[batch]                                        # (R, C, H, W)
    dsel = jnp.asarray(dr)[:, jnp.asarray(cmap)]            # (R, OD, P, P, H, W)

    # pick the class-specific coords per output channel
    yso = yc[:, jnp.asarray(cls_of)]                        # (R, OD, P, P, SP, SP)
    xso = xc[:, jnp.asarray(cls_of)]
    vo = valid[:, jnp.asarray(cls_of)]

    flat = dsel.reshape(R * OD * P * P, H, W)
    yf = yso.reshape(R * OD * P * P, SP, SP)
    xf = xso.reshape(R * OD * P * P, SP, SP)
    vals = jax.vmap(_bilinear_gather)(flat, yf, xf)
    vals = vals.reshape(R, OD, P, P, SP, SP)
    vf = vo.astype(data.dtype)
    cnt = vf.sum((-1, -2))
    ssum = (vals * vf).sum((-1, -2))
    out = jnp.where(cnt > 0, ssum / jnp.maximum(cnt, 1.0), 0.0)
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# Proposal / MultiProposal
# ---------------------------------------------------------------------------

def _generate_anchors(feature_stride, scales, ratios):
    """ref: contrib/proposal-inl.h:213 GenerateAnchors (+_Transform:195) —
    note ratio-major, scale-minor loop order."""
    base = [0.0, 0.0, feature_stride - 1.0, feature_stride - 1.0]
    w = base[2] - base[0] + 1.0
    h = base[3] - base[1] + 1.0
    x_ctr = base[0] + 0.5 * (w - 1.0)
    y_ctr = base[1] + 0.5 * (h - 1.0)
    size = w * h
    anchors = []
    for ratio in ratios:
        size_ratios = math.floor(size / ratio)
        new_w = math.floor(math.sqrt(size_ratios) + 0.5)
        new_h = math.floor(new_w * ratio + 0.5)
        for scale in scales:
            sw = new_w * scale
            sh = new_h * scale
            anchors.append([x_ctr - 0.5 * (sw - 1.0),
                            y_ctr - 0.5 * (sh - 1.0),
                            x_ctr + 0.5 * (sw - 1.0),
                            y_ctr + 0.5 * (sh - 1.0)])
    return _np.array(anchors, dtype=_np.float32)


def _nms_keep(boxes, scores, thresh, n_keep):
    """Greedy NMS over boxes already sorted by descending score. Returns
    (order, valid_count): `order` lists kept indices first (in score
    order), padded by cycling (ref: proposal.cc:214 NonMaximumSuppression
    + the output fill loop :408-420)."""
    n = boxes.shape[0]
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = (x2 - x1 + 1.0) * (y2 - y1 + 1.0)
    ix = jnp.maximum(0.0, jnp.minimum(x2[:, None], x2[None]) -
                     jnp.maximum(x1[:, None], x1[None]) + 1.0)
    iy = jnp.maximum(0.0, jnp.minimum(y2[:, None], y2[None]) -
                     jnp.maximum(y1[:, None], y1[None]) + 1.0)
    inter = ix * iy
    iou = inter / (area[:, None] + area[None] - inter)

    def body(i, supp):
        row = jnp.where(supp[i], jnp.zeros_like(iou[i]), iou[i])
        new = supp | ((row > thresh) & (jnp.arange(n) > i))
        return new

    # boxes marked invalid upstream (score<0) start suppressed
    supp0 = scores < 0.0
    supp = lax.fori_loop(0, n, body, supp0)
    kept = ~supp
    # order kept-first preserving score order
    key = jnp.where(kept, jnp.arange(n), n + jnp.arange(n))
    order = jnp.argsort(key)
    cnt = kept.sum()
    cnt = jnp.maximum(cnt, 1)
    idx = jnp.arange(n_keep)
    return order[idx % cnt], kept.sum()


def _proposal_single(scores, bbox_deltas, im_info, anchors, feature_stride,
                     rpn_pre_nms_top_n, rpn_post_nms_top_n, threshold,
                     rpn_min_size, iou_loss):
    """One image. scores (A, H, W) foreground; bbox_deltas (4A, H, W);
    im_info (3,) = (height, width, scale)."""
    A, H, W = scores.shape
    fs = float(feature_stride)
    # shifted anchors, layout index = h*(W*A) + w*A + a (ref: proposal.cc:355)
    shift_x = jnp.broadcast_to(jnp.arange(W, dtype=jnp.float32)[None, :],
                               (H, W)) * fs
    shift_y = jnp.broadcast_to(jnp.arange(H, dtype=jnp.float32)[:, None],
                               (H, W)) * fs
    shifts = jnp.stack([shift_x, shift_y, shift_x, shift_y], -1)
    anc = anchors[None, None, :, :] + shifts[:, :, None, :]  # (H, W, A, 4)
    anc = anc.reshape(-1, 4)
    sc = scores.transpose(1, 2, 0).reshape(-1)   # (H*W*A,)
    deltas = bbox_deltas.reshape(A, 4, H, W).transpose(2, 3, 0, 1)
    deltas = deltas.reshape(-1, 4)               # (H*W*A, 4)

    im_h, im_w, im_scale = im_info[0], im_info[1], im_info[2]
    if iou_loss:
        px1 = anc[:, 0] + deltas[:, 0]
        py1 = anc[:, 1] + deltas[:, 1]
        px2 = anc[:, 2] + deltas[:, 2]
        py2 = anc[:, 3] + deltas[:, 3]
    else:
        w = anc[:, 2] - anc[:, 0] + 1.0
        h = anc[:, 3] - anc[:, 1] + 1.0
        cx = anc[:, 0] + 0.5 * (w - 1.0)
        cy = anc[:, 1] + 0.5 * (h - 1.0)
        pcx = deltas[:, 0] * w + cx
        pcy = deltas[:, 1] * h + cy
        pw = jnp.exp(deltas[:, 2]) * w
        ph = jnp.exp(deltas[:, 3]) * h
        px1 = pcx - 0.5 * (pw - 1.0)
        py1 = pcy - 0.5 * (ph - 1.0)
        px2 = pcx + 0.5 * (pw - 1.0)
        py2 = pcy + 0.5 * (ph - 1.0)
    px1 = jnp.clip(px1, 0.0, im_w - 1.0)
    py1 = jnp.clip(py1, 0.0, im_h - 1.0)
    px2 = jnp.clip(px2, 0.0, im_w - 1.0)
    py2 = jnp.clip(py2, 0.0, im_h - 1.0)

    # mask predictions from the padded region (ref: proposal.cc:362-373)
    real_h = jnp.floor(im_h / fs)
    real_w = jnp.floor(im_w / fs)
    hh = jnp.arange(H, dtype=jnp.float32)
    ww = jnp.arange(W, dtype=jnp.float32)
    pad_mask = jnp.broadcast_to(
        (hh[:, None, None] >= real_h) | (ww[None, :, None] >= real_w),
        (H, W, A))
    sc = jnp.where(pad_mask.reshape(-1), -1.0, sc)

    # FilterBox (ref: proposal.cc:145): too-small boxes get score -1
    min_size = rpn_min_size * im_scale
    bw = px2 - px1 + 1.0
    bh = py2 - py1 + 1.0
    small = (bw < min_size) | (bh < min_size)
    px1 = jnp.where(small, px1 - min_size / 2, px1)
    py1 = jnp.where(small, py1 - min_size / 2, py1)
    px2 = jnp.where(small, px2 + min_size / 2, px2)
    py2 = jnp.where(small, py2 + min_size / 2, py2)
    sc = jnp.where(small, -1.0, sc)

    boxes = jnp.stack([px1, py1, px2, py2], -1)
    count = boxes.shape[0]
    pre_n = min(rpn_pre_nms_top_n if rpn_pre_nms_top_n > 0 else count, count)
    top_sc, top_idx = lax.top_k(sc, pre_n)
    top_boxes = boxes[top_idx]
    order, _n_kept = _nms_keep(top_boxes, top_sc, threshold,
                               rpn_post_nms_top_n)
    out_boxes = top_boxes[order]
    out_scores = top_sc[order]
    return out_boxes, out_scores


@register("_contrib_Proposal", aliases=("Proposal",))
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False):
    """RPN proposal generation (ref: src/operator/contrib/proposal.cc:281).
    cls_prob [1, 2A, H, W] (bg scores first, fg second — the fg half is
    used); bbox_pred [1, 4A, H, W]; im_info [1, 3]. Returns rois
    [post_nms_top_n, 5] (batch_idx 0 + corners), plus scores when
    output_score."""
    anchors = jnp.asarray(_generate_anchors(float(feature_stride),
                                            [float(s) for s in scales],
                                            [float(r) for r in ratios]))
    A = cls_prob.shape[1] // 2
    boxes, scores = _proposal_single(
        cls_prob[0, A:], bbox_pred[0], im_info[0], anchors,
        feature_stride, int(rpn_pre_nms_top_n), int(rpn_post_nms_top_n),
        float(threshold), float(rpn_min_size), bool(iou_loss))
    n = boxes.shape[0]
    rois = jnp.concatenate([jnp.zeros((n, 1), boxes.dtype), boxes], axis=1)
    if output_score:
        return rois, scores[:, None]
    return rois


@register("_contrib_MultiProposal", aliases=("MultiProposal",))
def multi_proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                   rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                   scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                   feature_stride=16, output_score=False, iou_loss=False):
    """Batched Proposal (ref: src/operator/contrib/multi_proposal.cc).
    Output rois [N*post_nms_top_n, 5] with per-image batch indices."""
    anchors = jnp.asarray(_generate_anchors(float(feature_stride),
                                            [float(s) for s in scales],
                                            [float(r) for r in ratios]))
    A = cls_prob.shape[1] // 2
    fn = jax.vmap(lambda s, d, i: _proposal_single(
        s, d, i, anchors, feature_stride, int(rpn_pre_nms_top_n),
        int(rpn_post_nms_top_n), float(threshold), float(rpn_min_size),
        bool(iou_loss)))
    boxes, scores = fn(cls_prob[:, A:], bbox_pred, im_info)
    N, P = boxes.shape[:2]
    bidx = jnp.broadcast_to(
        jnp.arange(N, dtype=boxes.dtype)[:, None, None], (N, P, 1))
    rois = jnp.concatenate([bidx, boxes], axis=-1).reshape(N * P, 5)
    if output_score:
        return rois, scores.reshape(N * P, 1)
    return rois


# ---------------------------------------------------------------------------
# MultiBoxTarget
# ---------------------------------------------------------------------------

@register("_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",))
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training target assignment
    (ref: src/operator/contrib/multibox_target.cc:71-281).

    anchor [1, A, 4] corner-format; label [N, L, 5+] rows
    (class, x1, y1, x2, y2), padded with -1 rows; cls_pred [N, n_cls, A].
    Returns (loc_target [N, A*4], loc_mask [N, A*4], cls_target [N, A]).

    The reference's greedy bipartite match loop becomes a lax.fori_loop
    with trip count L (each iteration matches at most one gt); its
    stable_sort negative mining becomes a top_k over masked scores.
    """
    anc = anchor.reshape(-1, 4)
    A = anc.shape[0]
    N, L = label.shape[0], label.shape[1]
    vx, vy, vw, vh = [float(v) for v in variances]
    ot = float(overlap_threshold)
    neg_ratio = float(negative_mining_ratio)
    neg_thresh = float(negative_mining_thresh)
    ign = float(ignore_label)

    def one_batch(lab, cpred):
        # valid gt prefix (reference stops at the first class==-1 row)
        valid = jnp.cumprod((lab[:, 0] != -1.0).astype(jnp.int32)) > 0  # (L,)
        # IoU (A, L)
        ax1, ay1, ax2, ay2 = anc[:, 0], anc[:, 1], anc[:, 2], anc[:, 3]
        gx1, gy1, gx2, gy2 = lab[:, 1], lab[:, 2], lab[:, 3], lab[:, 4]
        iw = jnp.maximum(0.0, jnp.minimum(ax2[:, None], gx2[None])
                         - jnp.maximum(ax1[:, None], gx1[None]))
        ih = jnp.maximum(0.0, jnp.minimum(ay2[:, None], gy2[None])
                         - jnp.maximum(ay1[:, None], gy1[None]))
        inter = iw * ih
        union = ((ax2 - ax1) * (ay2 - ay1))[:, None] \
            + ((gx2 - gx1) * (gy2 - gy1))[None] - inter
        iou = jnp.where(union > 0, inter / union, 0.0)
        iou = jnp.where(valid[None, :], iou, -1.0)         # mask invalid gts

        # phase 1: greedy bipartite matching (ref: multibox_target.cc:112)
        def bip_body(_, st):
            a_matched, g_matched, m_iou, m_gt = st
            m = jnp.where(a_matched[:, None] | g_matched[None, :],
                          -1.0, iou)
            best = jnp.argmax(m)
            bi, bk = best // L, best % L
            ok = m[bi, bk] > 1e-6
            a_matched = a_matched.at[bi].set(jnp.where(ok, True,
                                                       a_matched[bi]))
            g_matched = g_matched.at[bk].set(jnp.where(ok, True,
                                                       g_matched[bk]))
            m_iou = m_iou.at[bi].set(jnp.where(ok, m[bi, bk], m_iou[bi]))
            m_gt = m_gt.at[bi].set(jnp.where(ok, bk, m_gt[bi]))
            return a_matched, g_matched, m_iou, m_gt

        a_matched = jnp.zeros((A,), bool)
        g_matched = jnp.zeros((L,), bool)
        m_iou = jnp.full((A,), -1.0)
        m_gt = jnp.full((A,), -1, jnp.int32)
        a_matched, g_matched, m_iou, m_gt = lax.fori_loop(
            0, L, bip_body, (a_matched, g_matched, m_iou, m_gt))

        # phase 2: per-anchor best gt above overlap_threshold (cc:150)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        unmatched = ~a_matched
        m_iou = jnp.where(unmatched, best_iou, m_iou)
        m_gt = jnp.where(unmatched, best_gt.astype(jnp.int32), m_gt)
        pos2 = unmatched & (best_iou > ot) if ot > 0 else \
            jnp.zeros((A,), bool)
        positive = a_matched | pos2
        num_pos = positive.sum()

        # negatives (cc:181 negative mining, or all)
        if neg_ratio > 0:
            n_cls = cpred.shape[0]
            mx = cpred.max(axis=0)
            prob_bg = jnp.exp(cpred[0] - mx) / \
                jnp.exp(cpred - mx[None]).sum(axis=0)
            cand = (~positive) & (m_iou < neg_thresh)
            num_neg = jnp.minimum((num_pos * neg_ratio).astype(jnp.int32),
                                  (A - num_pos).astype(jnp.int32))
            # hardest negatives = lowest background prob
            score = jnp.where(cand, -prob_bg, -jnp.inf)
            order = jnp.argsort(-score)
            rank = jnp.zeros((A,), jnp.int32).at[order].set(jnp.arange(A))
            negative = cand & (rank < num_neg)
        else:
            negative = ~positive

        # assign targets (cc:251)
        g = m_gt.clip(0)
        gl = lab[g]                                     # (A, 5+)
        aw = ax2 - ax1
        ah = ay2 - ay1
        acx = (ax1 + ax2) * 0.5
        acy = (ay1 + ay2) * 0.5
        gw = gl[:, 3] - gl[:, 1]
        gh = gl[:, 4] - gl[:, 2]
        gcx = (gl[:, 1] + gl[:, 3]) * 0.5
        gcy = (gl[:, 2] + gl[:, 4]) * 0.5
        lt = jnp.stack([(gcx - acx) / aw / vx,
                        (gcy - acy) / ah / vy,
                        jnp.log(jnp.maximum(gw / aw, 1e-12)) / vw,
                        jnp.log(jnp.maximum(gh / ah, 1e-12)) / vh], -1)
        loc_t = jnp.where(positive[:, None], lt, 0.0).reshape(-1)
        loc_m = jnp.where(positive[:, None],
                          jnp.ones((A, 4)), 0.0).reshape(-1)
        cls_t = jnp.full((A,), ign)
        cls_t = jnp.where(negative, 0.0, cls_t)
        cls_t = jnp.where(positive, gl[:, 0] + 1.0, cls_t)

        has_gt = valid.any()
        loc_t = jnp.where(has_gt, loc_t, 0.0)
        loc_m = jnp.where(has_gt, loc_m, 0.0)
        cls_t = jnp.where(has_gt, cls_t, jnp.full((A,), ign))
        return loc_t, loc_m, cls_t

    loc_t, loc_m, cls_t = jax.vmap(one_batch)(label, cls_pred)
    return loc_t, loc_m, cls_t


# ---------------------------------------------------------------------------
# RROIAlign
# ---------------------------------------------------------------------------

@register("_contrib_RROIAlign", aliases=("RROIAlign",))
def rroi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0,
               sampling_ratio=-1):
    """Rotated ROI align (ref: src/operator/contrib/rroi_align.cc:40-210).
    rois [R, 6] = (batch_idx, cx, cy, w, h, theta_degrees). Averages a
    fixed bilinear sample grid rotated by theta about the ROI center;
    out-of-bounds samples contribute 0 but still count in the average
    (matching the reference). sampling_ratio<=0 (reference: adaptive
    ceil(roi/pool)) is approximated with a fixed grid of 2 for
    jit-safety — pass an explicit ratio for exact parity."""
    PH, PW = int(pooled_size[0]), int(pooled_size[1])
    SR = int(sampling_ratio) if int(sampling_ratio) > 0 else 2
    scale = float(spatial_scale)
    N, C, H, W = data.shape
    R = rois.shape[0]

    batch = rois[:, 0].astype(jnp.int32)
    cx = rois[:, 1] * scale
    cy = rois[:, 2] * scale
    rw = jnp.maximum(rois[:, 3] * scale, 1.0)
    rh = jnp.maximum(rois[:, 4] * scale, 1.0)
    theta = rois[:, 5] * (math.pi / 180.0)
    cos_t = jnp.cos(theta)
    sin_t = jnp.sin(theta)

    bin_h = rh / PH
    bin_w = rw / PW
    start_h = -rh / 2.0
    start_w = -rw / 2.0

    ph = jnp.arange(PH, dtype=data.dtype)
    pw = jnp.arange(PW, dtype=data.dtype)
    iy = jnp.arange(SR, dtype=data.dtype)
    # yy/xx in ROI-local coords (R, PH/PW, SR)
    yy = (start_h[:, None, None] + ph[None, :, None] * bin_h[:, None, None]
          + (iy[None, None, :] + 0.5) * bin_h[:, None, None] / SR)
    xx = (start_w[:, None, None] + pw[None, :, None] * bin_w[:, None, None]
          + (iy[None, None, :] + 0.5) * bin_w[:, None, None] / SR)
    # rotate + translate: (R, PH, PW, SR, SR)
    x = (xx[:, None, :, None, :] * cos_t[:, None, None, None, None]
         + yy[:, :, None, :, None] * sin_t[:, None, None, None, None]
         + cx[:, None, None, None, None])
    y = (yy[:, :, None, :, None] * cos_t[:, None, None, None, None]
         - xx[:, None, :, None, :] * sin_t[:, None, None, None, None]
         + cy[:, None, None, None, None])

    oob = (y < -1.0) | (y > H) | (x < -1.0) | (x > W)
    yc = jnp.clip(y, 0.0, H - 1.0)
    xc = jnp.clip(x, 0.0, W - 1.0)

    dr = data[batch]                                  # (R, C, H, W)
    yf = jnp.broadcast_to(yc[:, None], (R, C, PH, PW, SR, SR))
    xf = jnp.broadcast_to(xc[:, None], (R, C, PH, PW, SR, SR))
    flat = dr.reshape(R * C, H, W)
    vals = jax.vmap(_bilinear_gather)(
        flat, yf.reshape(R * C, PH, PW, SR, SR),
        xf.reshape(R * C, PH, PW, SR, SR))
    vals = vals.reshape(R, C, PH, PW, SR, SR)
    vals = jnp.where(oob[:, None], 0.0, vals)
    out = vals.sum((-1, -2)) / (SR * SR)
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# Crop (legacy)
# ---------------------------------------------------------------------------

@register("Crop", aliases=("crop_like",))
def crop(data, *crop_like, num_args=1, offset=(0, 0), h_w=(0, 0),
         center_crop=False):
    """Legacy Crop op (ref: src/operator/crop.cc). Crops the spatial dims
    of `data` [N,C,H,W] to `h_w`, or to the H/W of a second input when
    given (num_args=2). With center_crop the crop window is centered;
    otherwise it starts at `offset` (y, x)."""
    if len(crop_like) >= 1 and crop_like[0] is not None:
        th, tw = int(crop_like[0].shape[2]), int(crop_like[0].shape[3])
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    H, W = int(data.shape[2]), int(data.shape[3])
    if center_crop:
        oy = (H - th) // 2
        ox = (W - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    return data[:, :, oy:oy + th, ox:ox + tw]
