"""Image operator family — the reference's ``mx.nd.image`` namespace.

ref: src/operator/image/image_random.cc (+ image_random-inl.h semantics:
to_tensor, normalize, flips, brightness/contrast/saturation/hue jitter,
random_color_jitter, random_lighting) and src/operator/image/resize.cc,
crop.cc. TPU-first: all pure jnp (resize via jax.image on device);
random ops take the wrapper-threaded PRNG ``key`` so they stay jittable
instead of the reference's per-op Resource PRNG state.

Layout convention matches the reference: HWC (or NHWC batched) uint8/float
inputs for everything except normalize, which takes the CHW/NCHW float
output of to_tensor.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []

# ITU-R BT.601 luma weights — same constants the reference uses
# (image_random-inl.h RGB2GrayConvert)
_R, _G, _B = 0.299, 0.587, 0.114


@register("_image_to_tensor", aliases=("image_to_tensor",))
def to_tensor(data):
    """HWC [0,255] -> CHW float32 [0,1] (ref: image_random.cc ToTensor);
    batched NHWC -> NCHW."""
    x = data.astype(jnp.float32) / 255.0
    if x.ndim == 3:
        return x.transpose(2, 0, 1)
    return x.transpose(0, 3, 1, 2)


@register("_image_normalize", aliases=("image_normalize",))
def normalize(data, mean=0.0, std=1.0):
    """(data - mean) / std per channel on CHW/NCHW float input
    (ref: image_random.cc Normalize)."""
    mean = jnp.asarray(mean, data.dtype)
    std = jnp.asarray(std, data.dtype)
    if mean.ndim == 1:
        shape = (-1,) + (1,) * (2)
        mean = mean.reshape(shape)
        std = std.reshape(shape) if std.ndim == 1 else std
    elif std.ndim == 1:
        std = std.reshape((-1, 1, 1))
    return (data - mean) / std


def _hwc_axis(data, axis_from_end):
    return data.ndim - axis_from_end


@register("_image_flip_left_right", aliases=("image_flip_left_right",))
def flip_left_right(data):
    """ref: image_random.cc FlipLeftRight (HWC width axis)."""
    return jnp.flip(data, axis=_hwc_axis(data, 2))


@register("_image_flip_top_bottom", aliases=("image_flip_top_bottom",))
def flip_top_bottom(data):
    return jnp.flip(data, axis=_hwc_axis(data, 3))


@register("_image_random_flip_left_right", no_grad=True,
          aliases=("image_random_flip_left_right",))
def random_flip_left_right(data, key=None, p=0.5):
    do = jax.random.bernoulli(key, p)
    return jnp.where(do, jnp.flip(data, axis=_hwc_axis(data, 2)), data)


@register("_image_random_flip_top_bottom", no_grad=True,
          aliases=("image_random_flip_top_bottom",))
def random_flip_top_bottom(data, key=None, p=0.5):
    do = jax.random.bernoulli(key, p)
    return jnp.where(do, jnp.flip(data, axis=_hwc_axis(data, 3)), data)


@register("_image_resize", aliases=("image_resize",))
def resize(data, size=(0, 0), keep_ratio=False, interp=1):
    """Resize HWC/NHWC to size (w, h) (ref: src/operator/image/resize.cc;
    interp 0=nearest, 1=bilinear — the cv2 codes the reference forwards
    to OpenCV). keep_ratio scales the short side to size[0]."""
    if isinstance(size, int):
        size = (size, size)
    hw_ax = data.ndim - 3
    H, W = data.shape[hw_ax], data.shape[hw_ax + 1]
    if keep_ratio:
        short = min(H, W)
        s = float(size[0]) / short
        new_h, new_w = int(round(H * s)), int(round(W * s))
    else:
        new_w, new_h = int(size[0]), int(size[1]) or int(size[0])
    shape = list(data.shape)
    shape[hw_ax], shape[hw_ax + 1] = new_h, new_w
    method = "nearest" if int(interp) == 0 else "linear"
    out = jax.image.resize(data.astype(jnp.float32), tuple(shape), method)
    return out.astype(data.dtype)


@register("_image_crop", aliases=("image_crop",))
def image_crop(data, x=0, y=0, width=1, height=1):
    """Static crop of HWC/NHWC (ref: src/operator/image/crop.cc)."""
    hw_ax = data.ndim - 3
    sl = [slice(None)] * data.ndim
    sl[hw_ax] = slice(int(y), int(y) + int(height))
    sl[hw_ax + 1] = slice(int(x), int(x) + int(width))
    return data[tuple(sl)]


def _blend(a, b, alpha):
    return a * alpha + b * (1.0 - alpha)


def _grayscale(x):
    # HWC/NHWC channel-last weighted sum, keepdims for broadcasting
    w = jnp.asarray([_R, _G, _B], jnp.float32)
    return (x.astype(jnp.float32) * w).sum(-1, keepdims=True)


@register("_image_random_brightness", no_grad=True,
          aliases=("image_random_brightness",))
def random_brightness(data, key=None, min_factor=0.0, max_factor=1.0):
    """scale by U(min_factor, max_factor)
    (ref: image_random-inl.h RandomBrightness)."""
    a = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    return (data.astype(jnp.float32) * a).astype(data.dtype)


@register("_image_random_contrast", no_grad=True,
          aliases=("image_random_contrast",))
def random_contrast(data, key=None, min_factor=0.0, max_factor=1.0):
    """blend with the mean gray level (ref: RandomContrast)."""
    a = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    x = data.astype(jnp.float32)
    gray = _grayscale(x).mean(axis=(-3, -2, -1), keepdims=True)
    return _blend(x, gray, a).astype(data.dtype)


@register("_image_random_saturation", no_grad=True,
          aliases=("image_random_saturation",))
def random_saturation(data, key=None, min_factor=0.0, max_factor=1.0):
    """blend with the per-pixel gray value (ref: RandomSaturation)."""
    a = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    x = data.astype(jnp.float32)
    return _blend(x, _grayscale(x), a).astype(data.dtype)


@register("_image_random_hue", no_grad=True, aliases=("image_random_hue",))
def random_hue(data, key=None, min_factor=0.0, max_factor=1.0):
    """rotate hue by U(min,max) turns via the YIQ-space matrix trick the
    reference uses (image_random-inl.h RandomHue)."""
    import math as _m
    a = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    alpha = a * (2.0 * _m.pi)
    x = data.astype(jnp.float32)
    u, w = jnp.cos(alpha), jnp.sin(alpha)
    # yiq rotation composite matrix (same constants as the reference)
    t_yiq = jnp.asarray([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]], jnp.float32)
    t_rgb = jnp.asarray([[1.0, 0.956, 0.621],
                         [1.0, -0.272, -0.647],
                         [1.0, -1.107, 1.705]], jnp.float32)
    rot = jnp.concatenate([
        jnp.asarray([[1.0, 0.0, 0.0]], jnp.float32),
        jnp.stack([jnp.zeros(()), u, -w])[None],
        jnp.stack([jnp.zeros(()), w, u])[None]], 0)
    m = t_rgb @ rot @ t_yiq
    return jnp.einsum("...c,dc->...d", x, m).astype(data.dtype)


@register("_image_random_color_jitter", no_grad=True,
          aliases=("image_random_color_jitter",))
def random_color_jitter(data, key=None, brightness=0.0, contrast=0.0,
                        saturation=0.0, hue=0.0):
    """apply the four jitters in random order-free composition like the
    reference's RandomColorJitter (which applies sequentially)."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    x = data
    if brightness > 0:
        x = random_brightness(x, k1, 1 - brightness, 1 + brightness)
    if contrast > 0:
        x = random_contrast(x, k2, 1 - contrast, 1 + contrast)
    if saturation > 0:
        x = random_saturation(x, k3, 1 - saturation, 1 + saturation)
    if hue > 0:
        x = random_hue(x, k4, -hue, hue)
    return x


@register("_image_random_lighting", no_grad=True,
          aliases=("image_random_lighting",))
def random_lighting(data, key=None, alpha_std=0.05):
    """AlexNet-style PCA lighting noise with the reference's fixed
    eigen-decomposition of ImageNet RGB (image_random-inl.h
    RandomLighting eig constants)."""
    eigval = jnp.asarray([55.46, 4.794, 1.148], jnp.float32)
    eigvec = jnp.asarray([[-0.5675, 0.7192, 0.4009],
                          [-0.5808, -0.0045, -0.814],
                          [-0.5836, -0.6948, 0.4203]], jnp.float32)
    alpha = jax.random.normal(key, (3,)) * alpha_std
    delta = eigvec @ (alpha * eigval)
    return (data.astype(jnp.float32) + delta).astype(data.dtype)
