"""Extended operator long tail: tensor utilities, FFT, linalg extras,
detection/bounding-box ops, multi-tensor/AMP helpers, legacy aliases.

Covers the remaining user-visible registrations of the reference's
`src/operator/` inventory (SURVEY §2.1) not in the core tiers:
- init/indexing/util ops (ref: src/operator/tensor/init_op.cc,
  indexing_op.cc, ravel.cc, matrix_op.cc, histogram.cc)
- moments/all_finite/multi_sum_sq/amp_multicast
  (ref: src/operator/nn/moments.cc, contrib/all_finite.cc,
  contrib/multi_sum_sq.cc, tensor/amp_cast.cc)
- FFT (ref: src/operator/contrib/fft.cc, ifft.cc — interleaved re/im
  layout, unnormalized inverse like cuFFT)
- linalg syevd/extracttrian/maketrian (ref: src/operator/tensor/la_op.cc)
- bounding-box / anchor ops (ref: src/operator/contrib/bounding_box.cc,
  multibox_prior.cc, multibox_detection.cc, roi_align.cc,
  src/operator/roi_pooling.cc)
- SpatialTransformer, BilinearResize2D, AdaptiveAvgPooling2D, SVMOutput,
  quadratic, index_copy
- legacy *_v1 / SyncBatchNorm aliases

Everything is a pure jit-safe function: static shapes, sorts instead of
data-dependent compaction, masks instead of dynamic filtering.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, _OPS

__all__ = []


# ---------------------------------------------------------------------------
# init / ranges
# ---------------------------------------------------------------------------

@register("_eye", num_inputs=0, no_grad=True, aliases=("eye",))
def _eye(N=1, M=0, k=0, dtype="float32"):
    """ref: src/operator/tensor/init_op.cc _eye."""
    return jnp.eye(int(N), int(M) or None, int(k), dtype=dtype or "float32")


@register("_linspace", num_inputs=0, no_grad=True, aliases=("linspace",))
def _linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32"):
    """ref: src/operator/tensor/init_op.cc _linspace."""
    return jnp.linspace(float(start), float(stop), int(num),
                        endpoint=bool(endpoint), dtype=dtype or "float32")


@register("_arange", num_inputs=0, no_grad=True)
def _arange(start=0.0, stop=None, step=1.0, repeat=1, infer_range=False,
            dtype="float32"):
    """ref: src/operator/tensor/init_op.cc _arange (with repeat)."""
    a = jnp.arange(float(start),
                   None if stop is None else float(stop),
                   float(step), dtype=dtype or "float32")
    if int(repeat) > 1:
        a = jnp.repeat(a, int(repeat))
    return a


@register("_zeros_without_dtype", num_inputs=0, no_grad=True)
def _zeros_without_dtype(shape=(), dtype=None):
    """ref: src/operator/tensor/init_op.cc _zeros_without_dtype."""
    return jnp.zeros(tuple(shape), dtype or "float32")


# ---------------------------------------------------------------------------
# indexing / shape utilities
# ---------------------------------------------------------------------------

@register("batch_take", num_inputs=2)
def batch_take(a, indices):
    """out[i] = a[i, indices[i]] (ref: src/operator/tensor/indexing_op.cc
    batch_take)."""
    idx = indices.astype(jnp.int32)
    return jnp.take_along_axis(a, idx[..., None], axis=-1)[..., 0] \
        if a.ndim == idx.ndim + 1 else \
        jnp.take_along_axis(a, idx, axis=-1)


@register("reshape_like", num_inputs=2)
def reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    """Reshape lhs to rhs's shape (sub-ranges supported)
    (ref: src/operator/tensor/elemwise_unary_op_basic.cc reshape_like)."""
    if lhs_begin is None and rhs_begin is None:
        return jnp.reshape(lhs, rhs.shape)
    lb = 0 if lhs_begin is None else int(lhs_begin)
    le = lhs.ndim if lhs_end is None else int(lhs_end)
    rb = 0 if rhs_begin is None else int(rhs_begin)
    re_ = rhs.ndim if rhs_end is None else int(rhs_end)
    new_shape = (lhs.shape[:lb] + rhs.shape[rb:re_] + lhs.shape[le:])
    return jnp.reshape(lhs, new_shape)


@register("_split_v2", num_inputs=1, aliases=("split_v2",))
def _split_v2(data, indices=(), axis=0, squeeze_axis=False, sections=0):
    """ref: src/operator/tensor/matrix_op.cc _split_v2."""
    axis = int(axis)
    if int(sections) > 0:
        parts = jnp.split(data, int(sections), axis=axis)
    else:
        parts = jnp.split(data, [int(i) for i in indices], axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("_ravel_multi_index", num_inputs=1, no_grad=True,
          aliases=("ravel_multi_index",))
def _ravel_multi_index(data, shape=()):
    """ref: src/operator/tensor/ravel.cc _ravel_multi_index.
    data: [ndim, N] indices -> [N] flat indices."""
    dims = [int(s) for s in shape]
    idx = data.astype(jnp.int32)
    flat = jnp.zeros(idx.shape[1:], jnp.int32)
    for d, size in enumerate(dims):
        flat = flat * size + idx[d]
    return flat.astype(data.dtype)


@register("_unravel_index", num_inputs=1, no_grad=True,
          aliases=("unravel_index",))
def _unravel_index(data, shape=()):
    """ref: src/operator/tensor/ravel.cc _unravel_index."""
    dims = [int(s) for s in shape]
    idx = data.astype(jnp.int32)
    out = []
    for size in reversed(dims):
        out.append(idx % size)
        idx = idx // size
    return jnp.stack(list(reversed(out)), axis=0).astype(data.dtype)


@register("_slice_assign", num_inputs=2)
def _slice_assign(lhs, rhs, begin=(), end=(), step=()):
    """out = lhs with lhs[begin:end:step] = rhs
    (ref: src/operator/tensor/matrix_op.cc _slice_assign)."""
    idx = _mx_slice(lhs.shape, begin, end, step)
    return lhs.at[idx].set(rhs)


@register("_slice_assign_scalar", num_inputs=1)
def _slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=()):
    """ref: src/operator/tensor/matrix_op.cc _slice_assign_scalar."""
    idx = _mx_slice(data.shape, begin, end, step)
    return data.at[idx].set(jnp.asarray(scalar, data.dtype))


def _mx_slice(shape, begin, end, step):
    out = []
    step = list(step) or [None] * len(begin)
    for b, e, s, n in zip(begin, end, step, shape):
        s = 1 if s in (None, 0) else int(s)
        b = (0 if s > 0 else n - 1) if b is None else int(b)
        e = (n if s > 0 else -n - 1) if e is None else int(e)
        out.append(slice(b, e, s))
    return tuple(out)


@register("_scatter_set_nd", num_inputs=3, no_grad=True)
def _scatter_set_nd(lhs, rhs, indices, shape=None):
    """ref: src/operator/tensor/indexing_op.cc _scatter_set_nd —
    lhs with lhs[indices] = rhs (gather_nd-style indices [M, N])."""
    idx = tuple(indices.astype(jnp.int32))
    return lhs.at[idx].set(rhs)


@register("_identity_with_attr_like_rhs", num_inputs=2)
def _identity_with_attr_like_rhs(lhs, rhs):
    """ref: src/operator/tensor/elemwise_unary_op_basic.cc."""
    return lhs


@register("cast_storage", num_inputs=1)
def cast_storage(data, stype="default"):
    """Storage casts are identity on TPU: XLA tensors are always dense
    (ref: src/operator/tensor/cast_storage.cc; sparse storage formats are
    API-level here, see ndarray/sparse.py)."""
    return data


@register("_sparse_retain", num_inputs=2, aliases=("sparse_retain",),
          input_names=("data", "indices"))
def _sparse_retain(data, indices):
    """Dense emulation of row_sparse retain: rows not in `indices` zeroed
    (ref: src/operator/tensor/sparse_retain.cc)."""
    keep = jnp.zeros((data.shape[0],), jnp.bool_)
    keep = keep.at[indices.astype(jnp.int32)].set(True)
    return jnp.where(keep.reshape((-1,) + (1,) * (data.ndim - 1)), data, 0)


@register("choose_element_0index", num_inputs=2)
def choose_element_0index(lhs, rhs):
    """out[i] = lhs[i, rhs[i]] (ref: src/operator/tensor/matrix_op.cc)."""
    return jnp.take_along_axis(
        lhs, rhs.astype(jnp.int32)[:, None], axis=1)[:, 0]


@register("fill_element_0index", num_inputs=3)
def fill_element_0index(lhs, mhs, rhs):
    """lhs with lhs[i, rhs[i]] = mhs[i] (ref: matrix_op.cc)."""
    i = jnp.arange(lhs.shape[0])
    return lhs.at[i, rhs.astype(jnp.int32)].set(mhs)


# ---------------------------------------------------------------------------
# statistics
# ---------------------------------------------------------------------------

@register("_histogram", num_inputs=1, no_grad=True, aliases=("histogram",))
def _histogram(data, bin_cnt=10, range=None):
    """ref: src/operator/tensor/histogram.cc. jit-safe: traced min/max
    drive the bin edges when no explicit range is given."""
    bins = int(bin_cnt)
    flat = data.reshape(-1).astype(jnp.float32)
    if range is not None:
        lo = jnp.float32(range[0])
        hi = jnp.float32(range[1])
    else:
        lo = jnp.min(flat)
        hi = jnp.max(flat)
    width = jnp.maximum(hi - lo, jnp.finfo(jnp.float32).tiny)
    edges = lo + (hi - lo) * jnp.arange(bins + 1, dtype=jnp.float32) / bins
    idx = jnp.clip(jnp.floor((flat - lo) / width * bins).astype(jnp.int32),
                   0, bins - 1)
    inside = jnp.logical_and(flat >= lo, flat <= hi)
    counts = jnp.zeros((bins,), jnp.int32).at[idx].add(
        inside.astype(jnp.int32))
    return counts, edges.astype(data.dtype)


@register("moments", num_inputs=1)
def moments(data, axes=None, keepdims=False):
    """(mean, var) over axes (ref: src/operator/nn/moments.cc)."""
    ax = tuple(int(a) for a in axes) if axes is not None else None
    mean = jnp.mean(data, axis=ax, keepdims=bool(keepdims))
    var = jnp.var(data, axis=ax, keepdims=bool(keepdims))
    return mean, var


@register("all_finite", num_inputs=1, no_grad=True)
def all_finite(data, init_output=True):
    """Scalar 1.0/0.0 whether all entries are finite
    (ref: src/operator/contrib/all_finite.cc)."""
    return jnp.isfinite(data.astype(jnp.float32)).all()[None].astype(
        jnp.float32)


@register("multi_all_finite", no_grad=True)
def multi_all_finite(*arrays, num_arrays=1, init_output=True):
    """ref: src/operator/contrib/all_finite.cc multi_all_finite."""
    ok = jnp.asarray(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.isfinite(a.astype(jnp.float32)).all())
    return ok[None].astype(jnp.float32)


@register("multi_sum_sq", no_grad=True)
def multi_sum_sq(*arrays, num_arrays=1):
    """Per-array sum of squares (ref: src/operator/contrib/multi_sum_sq.cc;
    the LARS trust-ratio building block)."""
    return tuple(jnp.sum(jnp.square(a.astype(jnp.float32))) for a in arrays)


@register("amp_multicast")
def amp_multicast(*arrays, num_outputs=1, cast_narrow=False):
    """Cast all inputs to a common width (ref: src/operator/tensor/
    amp_cast.cc amp_multicast): widest wins unless cast_narrow."""
    dts = [a.dtype for a in arrays]
    target = min(dts, key=lambda d: jnp.dtype(d).itemsize) if cast_narrow \
        else max(dts, key=lambda d: jnp.dtype(d).itemsize)
    return tuple(a.astype(target) for a in arrays)


# ---------------------------------------------------------------------------
# FFT (ref layout: interleaved re/im pairs on the last axis)
# ---------------------------------------------------------------------------

@register("fft", num_inputs=1, aliases=("_contrib_fft",))
def fft(data, compute_size=128):
    """Real input [..., d] -> interleaved complex [..., 2d]
    (ref: src/operator/contrib/fft.cc)."""
    out = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    inter = jnp.stack([out.real, out.imag], axis=-1)
    return inter.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(
        data.dtype)


@register("ifft", num_inputs=1, aliases=("_contrib_ifft",))
def ifft(data, compute_size=128):
    """Interleaved complex [..., 2d] -> real [..., d], unnormalized (x d)
    like cuFFT (ref: src/operator/contrib/ifft.cc; numerics pinned by
    tests/python/gpu/test_operator_gpu.py:103 check_ifft)."""
    d = data.shape[-1] // 2
    pairs = data.astype(jnp.float32).reshape(data.shape[:-1] + (d, 2))
    comp = lax.complex(pairs[..., 0], pairs[..., 1])
    out = jnp.fft.ifft(comp, axis=-1).real * d
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# linalg extras
# ---------------------------------------------------------------------------

@register("_linalg_syevd", num_inputs=1, aliases=("linalg_syevd", "syevd"))
def _linalg_syevd(a):
    """Symmetric eigendecomposition U, Lambda with A = U^T diag(L) U
    (ref: src/operator/tensor/la_op.cc _linalg_syevd)."""
    w, v = jnp.linalg.eigh(a)
    return jnp.swapaxes(v, -1, -2), w


@register("_linalg_extracttrian", num_inputs=1,
          aliases=("linalg_extracttrian", "extracttrian"))
def _linalg_extracttrian(a, offset=0, lower=True):
    """Triangle of square matrices packed into vectors
    (ref: src/operator/tensor/la_op.cc _linalg_extracttrian)."""
    n = a.shape[-1]
    off = int(offset)
    ii, jj = jnp.tril_indices(n, k=off) if lower \
        else jnp.triu_indices(n, k=off)
    return a[..., ii, jj]


@register("_linalg_maketrian", num_inputs=1,
          aliases=("linalg_maketrian", "maketrian"))
def _linalg_maketrian(a, offset=0, lower=True):
    """Inverse of extracttrian (ref: la_op.cc _linalg_maketrian)."""
    m = a.shape[-1]
    off = int(offset)
    # m = n(n+1)/2 + |off| adjustments; solve n from packed length
    k = abs(off)
    n = int((-1 + (1 + 8 * m) ** 0.5) / 2) + k
    ii, jj = jnp.tril_indices(n, k=off) if lower \
        else jnp.triu_indices(n, k=off)
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    return out.at[..., ii, jj].set(a)


# ---------------------------------------------------------------------------
# bounding boxes / anchors / ROI
# ---------------------------------------------------------------------------

def _corner(boxes, fmt):
    if fmt == "center":
        x, y, w, h = jnp.split(boxes, 4, axis=-1)
        return jnp.concatenate(
            [x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)
    return boxes


def _iou_corner(a, b):
    """a: [..., N, 4], b: [..., M, 4] corner boxes -> [..., N, M]."""
    ax1, ay1, ax2, ay2 = [a[..., i] for i in range(4)]
    bx1, by1, bx2, by2 = [b[..., i] for i in range(4)]
    ix1 = jnp.maximum(ax1[..., :, None], bx1[..., None, :])
    iy1 = jnp.maximum(ay1[..., :, None], by1[..., None, :])
    ix2 = jnp.minimum(ax2[..., :, None], bx2[..., None, :])
    iy2 = jnp.minimum(ay2[..., :, None], by2[..., None, :])
    inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
    area_a = jnp.clip(ax2 - ax1, 0) * jnp.clip(ay2 - ay1, 0)
    area_b = jnp.clip(bx2 - bx1, 0) * jnp.clip(by2 - by1, 0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("box_iou", num_inputs=2, aliases=("_contrib_box_iou",))
def box_iou(lhs, rhs, format="corner"):
    """IoU of two box arrays (ref: src/operator/contrib/bounding_box.cc
    _contrib_box_iou)."""
    return _iou_corner(_corner(lhs, format), _corner(rhs, format))


@register("box_nms", num_inputs=1, aliases=("_contrib_box_nms", "box_non_maximum_suppression"))
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner", out_format="corner"):
    """Greedy NMS; suppressed/invalid records become -1 rows, survivors
    sorted by score descending (ref: src/operator/contrib/bounding_box.cc
    _contrib_box_nms)."""
    cs, si = int(coord_start), int(score_index)
    elems = data.shape[-1]
    flat = data.reshape((-1,) + data.shape[-2:])  # [B, N, E]

    def one(batch):
        scores = batch[:, si]
        boxes = _corner(batch[:, cs:cs + 4], in_format)
        valid = scores > valid_thresh
        if int(id_index) >= 0 and int(background_id) >= 0:
            valid = jnp.logical_and(
                valid, batch[:, int(id_index)] != background_id)
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
        sboxes = boxes[order]
        svalid = valid[order]
        if int(topk) > 0:
            svalid = jnp.logical_and(
                svalid, jnp.arange(svalid.shape[0]) < int(topk))
        iou = _iou_corner(sboxes, sboxes)
        same_class = None
        if not force_suppress and int(id_index) >= 0:
            ids = batch[order, int(id_index)]
            same_class = ids[:, None] == ids[None, :]

        n = sboxes.shape[0]

        def step(keep, i):
            sup = jnp.logical_and(iou[i] > overlap_thresh,
                                  jnp.arange(n) > i)
            if same_class is not None:
                sup = jnp.logical_and(sup, same_class[i])
            sup = jnp.logical_and(sup, keep[i])  # only live boxes suppress
            return jnp.logical_and(keep, ~sup), None

        keep, _ = lax.scan(step, svalid, jnp.arange(n))
        rows = batch[order]
        if out_format != in_format:
            conv = _corner(rows[:, cs:cs + 4], in_format) \
                if out_format == "corner" else _center(rows[:, cs:cs + 4])
            rows = rows.at[:, cs:cs + 4].set(conv)
        rows = jnp.where(keep[:, None], rows,
                         jnp.full((elems,), -1.0, rows.dtype))
        # survivors first, -1 rows after (stable by score order)
        order2 = jnp.argsort(~keep, stable=True)
        return rows[order2]

    out = jax.vmap(one)(flat)
    return out.reshape(data.shape)


def _center(boxes):
    x1, y1, x2, y2 = jnp.split(boxes, 4, axis=-1)
    return jnp.concatenate([(x1 + x2) / 2, (y1 + y2) / 2,
                            x2 - x1, y2 - y1], axis=-1)


@register("bipartite_matching", num_inputs=1, no_grad=True,
          aliases=("_contrib_bipartite_matching",))
def bipartite_matching(data, threshold=1e-12, is_ascend=False, topk=-1):
    """Greedy bipartite matching of a score matrix [..., N, M]
    (ref: src/operator/contrib/bounding_box.cc _contrib_bipartite_matching)."""
    flat = data.reshape((-1,) + data.shape[-2:])

    def one(scores):
        n, m = scores.shape
        sign = 1.0 if is_ascend else -1.0
        order = jnp.argsort((sign * scores).reshape(-1), stable=True)
        max_matches = n if int(topk) <= 0 else min(int(topk), n)

        def step(state, t):
            row_match, col_used, n_matched = state
            flat_i = order[t]
            i, j = flat_i // m, flat_i % m
            ok = jnp.logical_and(row_match[i] < 0, ~col_used[j])
            val = scores[i, j]
            ok = jnp.logical_and(ok, val >= threshold if is_ascend
                                 else val > threshold)
            # topk caps the NUMBER OF MATCHES (ref: bounding_box.cc
            # _contrib_bipartite_matching topk semantics)
            ok = jnp.logical_and(ok, n_matched < max_matches)
            row_match = row_match.at[i].set(
                jnp.where(ok, j, row_match[i]))
            col_used = col_used.at[j].set(jnp.logical_or(col_used[j], ok))
            return (row_match, col_used,
                    n_matched + ok.astype(jnp.int32)), None

        init = (jnp.full((n,), -1, jnp.int32), jnp.zeros((m,), jnp.bool_),
                jnp.int32(0))
        (row_match, col_used, _), _ = lax.scan(
            step, init, jnp.arange(n * m))
        valid = row_match >= 0
        col_match = jnp.full((m,), -1, jnp.int32).at[
            jnp.where(valid, row_match, m)].set(
            jnp.arange(n, dtype=jnp.int32), mode="drop")
        return row_match.astype(data.dtype), col_match.astype(data.dtype)

    rows, cols = jax.vmap(one)(flat)
    return (rows.reshape(data.shape[:-1][:-1] + (data.shape[-2],)),
            cols.reshape(data.shape[:-2] + (data.shape[-1],)))


@register("MultiBoxPrior", num_inputs=1, no_grad=True,
          aliases=("_contrib_MultiBoxPrior", "multibox_prior"))
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor boxes from a feature map [B, C, H, W] -> [1, H*W*A, 4]
    (ref: src/operator/contrib/multibox_prior.cc MultiBoxPriorForward)."""
    in_h, in_w = data.shape[-2], data.shape[-1]
    sizes = [float(s) for s in (sizes if isinstance(sizes, (tuple, list))
                                else (sizes,))]
    ratios = [float(r) for r in (ratios if isinstance(ratios, (tuple, list))
                                 else (ratios,))]
    step_y = float(steps[0]) if float(steps[0]) > 0 else 1.0 / in_h
    step_x = float(steps[1]) if float(steps[1]) > 0 else 1.0 / in_w
    r = jnp.arange(in_h, dtype=jnp.float32)
    c = jnp.arange(in_w, dtype=jnp.float32)
    cy = (r + float(offsets[0])) * step_y                       # [H]
    cx = (c + float(offsets[1])) * step_x                       # [W]
    cxg, cyg = jnp.meshgrid(cx, cy)                             # [H, W]
    whs = []
    r0 = (ratios[0] ** 0.5) if ratios else 1.0
    for s in sizes:
        whs.append((s * in_h / in_w * r0 / 2, s / r0 / 2))
    for rr in ratios[1:]:
        rt = rr ** 0.5
        whs.append((sizes[0] * in_h / in_w * rt / 2, sizes[0] / rt / 2))
    anchors = []
    for (w, h) in whs:
        anchors.append(jnp.stack(
            [cxg - w, cyg - h, cxg + w, cyg + h], axis=-1))     # [H, W, 4]
    out = jnp.stack(anchors, axis=2).reshape(-1, 4)             # [H*W*A, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out[None].astype(jnp.float32)


@register("MultiBoxDetection", num_inputs=3, no_grad=True,
          aliases=("_contrib_MultiBoxDetection", "multibox_detection"))
def multibox_detection(cls_pred, loc_pred, anchors, clip=True,
                       threshold=0.01, background_id=0, nms_threshold=0.5,
                       force_suppress=False, variances=(0.1, 0.1, 0.2, 0.2),
                       nms_topk=-1):
    """Decode SSD predictions into [B, N, 6] (id, score, 4 corners)
    (ref: src/operator/contrib/multibox_detection.cc)."""
    B = cls_pred.shape[0]
    N = anchors.shape[1]
    probs = cls_pred                                            # [B, Cls, N]
    scores = jnp.max(probs[:, 1:, :], axis=1)
    cls_id = jnp.argmax(probs[:, 1:, :], axis=1).astype(jnp.float32)
    a = anchors[0]                                              # [N, 4]
    acx, acy = (a[:, 0] + a[:, 2]) / 2, (a[:, 1] + a[:, 3]) / 2
    aw, ah = a[:, 2] - a[:, 0], a[:, 3] - a[:, 1]
    loc = loc_pred.reshape(B, N, 4)
    v = [float(x) for x in variances]
    cx = loc[..., 0] * v[0] * aw + acx
    cy = loc[..., 1] * v[1] * ah + acy
    w = jnp.exp(loc[..., 2] * v[2]) * aw / 2
    h = jnp.exp(loc[..., 3] * v[3]) * ah / 2
    boxes = jnp.stack([cx - w, cy - h, cx + w, cy + h], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    keep = scores > threshold
    recs = jnp.concatenate(
        [jnp.where(keep, cls_id, -1.0)[..., None],
         jnp.where(keep, scores, -1.0)[..., None], boxes], axis=-1)
    return box_nms(recs, overlap_thresh=float(nms_threshold),
                   valid_thresh=0.0, topk=int(nms_topk), coord_start=2,
                   score_index=1, id_index=0, background_id=-1,
                   force_suppress=bool(force_suppress))


def _bilinear_at(img, y, x):
    """img: [C, H, W]; y/x: [...] float coords. Bilinear sample."""
    H, W = img.shape[-2], img.shape[-1]
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy = y - y0
    wx = x - x0
    y0i = jnp.clip(y0.astype(jnp.int32), 0, H - 1)
    y1i = jnp.clip(y0i + 1, 0, H - 1)
    x0i = jnp.clip(x0.astype(jnp.int32), 0, W - 1)
    x1i = jnp.clip(x0i + 1, 0, W - 1)
    v00 = img[:, y0i, x0i]
    v01 = img[:, y0i, x1i]
    v10 = img[:, y1i, x0i]
    v11 = img[:, y1i, x1i]
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


@register("ROIAlign", num_inputs=2, aliases=("_contrib_ROIAlign",
                                             "roi_align"))
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False, aligned=False):
    """ROI Align with bilinear sampling (ref: src/operator/contrib/
    roi_align.cc). rois: [R, 5] (batch_idx, x1, y1, x2, y2)."""
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    ns = 2 if int(sample_ratio) <= 0 else int(sample_ratio)
    off = 0.5 if aligned else 0.0

    def one(roi):
        b = roi[0].astype(jnp.int32)
        img = data[b]                                   # [C, H, W]
        x1, y1, x2, y2 = roi[1] * spatial_scale - off, \
            roi[2] * spatial_scale - off, roi[3] * spatial_scale - off, \
            roi[4] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-5)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-5)
        bw, bh = rw / pw, rh / ph
        iy = jnp.arange(ph, dtype=jnp.float32)
        ix = jnp.arange(pw, dtype=jnp.float32)
        sy = jnp.arange(ns, dtype=jnp.float32)
        ys = y1 + (iy[:, None] + (sy[None, :] + 0.5) / ns) * bh  # [ph, ns]
        xs = x1 + (ix[:, None] + (sy[None, :] + 0.5) / ns) * bw  # [pw, ns]
        yy = ys.reshape(-1)                                      # [ph*ns]
        xx = xs.reshape(-1)                                      # [pw*ns]
        grid_y = jnp.repeat(yy, xx.shape[0])
        grid_x = jnp.tile(xx, yy.shape[0])
        vals = _bilinear_at(img, grid_y, grid_x)                 # [C, ...]
        vals = vals.reshape(img.shape[0], ph, ns, pw, ns)
        return jnp.mean(vals, axis=(2, 4))                       # [C,ph,pw]

    return jax.vmap(one)(rois)


@register("ROIPooling", num_inputs=2, aliases=("roi_pooling",))
def roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """Max pooling over quantized ROI bins (ref: src/operator/
    roi_pooling.cc). rois: [R, 5] (batch_idx, x1, y1, x2, y2)."""
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    H, W = data.shape[-2], data.shape[-1]

    def one(roi):
        b = roi[0].astype(jnp.int32)
        img = data[b]
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bw, bh = rw / pw, rh / ph
        yy = jnp.arange(H, dtype=jnp.float32)
        xx = jnp.arange(W, dtype=jnp.float32)
        # bin index of every pixel, -1 outside the roi
        by = jnp.floor((yy - y1) / bh)
        bx = jnp.floor((xx - x1) / bw)
        by = jnp.where((yy >= y1) & (yy <= y2), by, -1.0)
        bx = jnp.where((xx >= x1) & (xx <= x2), bx, -1.0)
        onehot_y = (by[None, :] == jnp.arange(ph,
                                              dtype=jnp.float32)[:, None])
        onehot_x = (bx[None, :] == jnp.arange(pw,
                                              dtype=jnp.float32)[:, None])
        mask = onehot_y[:, None, :, None] & onehot_x[None, :, None, :]
        big = jnp.where(mask[None], img[:, None, None, :, :], -jnp.inf)
        out = jnp.max(big, axis=(-1, -2))
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one)(rois)


# ---------------------------------------------------------------------------
# spatial transform / resize
# ---------------------------------------------------------------------------

@register("SpatialTransformer", num_inputs=2,
          aliases=("spatial_transformer",))
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=None):
    """Affine grid + bilinear sampling (ref: src/operator/
    spatial_transformer.cc)."""
    th, tw = int(target_shape[0]), int(target_shape[1])
    theta = loc.reshape(-1, 2, 3)
    ys = jnp.linspace(-1.0, 1.0, th)
    xs = jnp.linspace(-1.0, 1.0, tw)
    gx, gy = jnp.meshgrid(xs, ys)
    grid = jnp.stack([gx.reshape(-1), gy.reshape(-1),
                      jnp.ones(th * tw)], axis=0)     # [3, th*tw]
    src = jnp.einsum("bij,jk->bik", theta, grid)      # [B, 2, th*tw]

    def one(img, sxy):
        x = (sxy[0] + 1.0) * (img.shape[-1] - 1) / 2.0
        y = (sxy[1] + 1.0) * (img.shape[-2] - 1) / 2.0
        return _bilinear_at(img, y, x).reshape(img.shape[0], th, tw)

    return jax.vmap(one)(data, src)


@register("BilinearResize2D", num_inputs=1,
          aliases=("_contrib_BilinearResize2D", "bilinear_resize_2d"))
def bilinear_resize_2d(data, height=1, width=1, scale_height=None,
                       scale_width=None, mode="size"):
    """ref: src/operator/contrib/bilinear_resize.cc."""
    H, W = data.shape[-2], data.shape[-1]
    if scale_height is not None:
        height = int(round(H * float(scale_height)))
        width = int(round(W * float(scale_width or scale_height)))
    out_shape = data.shape[:-2] + (int(height), int(width))
    return jax.image.resize(data, out_shape, method="linear")


@register("AdaptiveAvgPooling2D", num_inputs=1,
          aliases=("_contrib_AdaptiveAvgPooling2D",
                   "adaptive_avg_pooling_2d"))
def adaptive_avg_pooling_2d(data, output_size=(1, 1)):
    """ref: src/operator/contrib/adaptive_avg_pooling.cc."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = int(output_size[0]), int(output_size[1])
    H, W = data.shape[-2], data.shape[-1]
    if H % oh == 0 and W % ow == 0:
        x = data.reshape(data.shape[:-2] + (oh, H // oh, ow, W // ow))
        return jnp.mean(x, axis=(-3, -1))
    return jax.image.resize(
        data, data.shape[:-2] + (oh, ow), method="linear")


# ---------------------------------------------------------------------------
# loss heads / misc contrib
# ---------------------------------------------------------------------------

@register("SVMOutput", num_inputs=2, aliases=("svm_output",))
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """Forward is identity; backward is the SVM hinge gradient
    (ref: src/operator/svm_output.cc). Matches the reference's loss-head
    pattern: the incoming cotangent is ignored."""
    @jax.custom_vjp
    def core(data, label):
        return data

    def fwd(data, label):
        return data, (data, label)

    def bwd(res, g):
        x, lbl = res
        n = x.shape[-1]
        onehot = jax.nn.one_hot(lbl.astype(jnp.int32), n, dtype=x.dtype)
        score_y = jnp.sum(x * onehot, axis=-1, keepdims=True)
        if use_linear:
            viol = ((margin - (score_y - x)) > 0).astype(x.dtype) * (
                1.0 - onehot)
            gx = viol - onehot * jnp.sum(viol, axis=-1, keepdims=True)
        else:
            # squared hinge
            m = jnp.maximum(0.0, margin - (score_y - x)) * (1.0 - onehot)
            gx = 2.0 * m - onehot * jnp.sum(2.0 * m, axis=-1,
                                            keepdims=True)
        return gx * regularization_coefficient, jnp.zeros_like(lbl)

    core.defvjp(fwd, bwd)
    return core(data, label)


@register("quadratic", num_inputs=1, aliases=("_contrib_quadratic",))
def quadratic(data, a=0.0, b=0.0, c=0.0):
    """a*x^2 + b*x + c (ref: src/operator/contrib/quadratic_op.cc — the
    reference's tutorial op)."""
    return a * data * data + b * data + c


@register("index_copy", num_inputs=3, aliases=("_contrib_index_copy",))
def index_copy(data, index, new_tensor):
    """out = data with out[index[i]] = new_tensor[i]
    (ref: src/operator/contrib/index_copy.cc)."""
    return data.at[index.astype(jnp.int32)].set(new_tensor)


# ---------------------------------------------------------------------------
# legacy aliases (v1 ops are the same computation here; the reference keeps
# them for checkpoint compat — ref: src/operator/batch_norm_v1.cc etc.)
# ---------------------------------------------------------------------------

for _new, _old in [("BatchNorm", "BatchNorm_v1"),
                   ("Convolution", "Convolution_v1"),
                   ("Pooling", "Pooling_v1"),
                   ("BatchNorm", "CuDNNBatchNorm"),
                   ("BatchNorm", "SyncBatchNorm"),
                   ("BatchNorm", "_contrib_SyncBatchNorm"),
                   ("Embedding", "_contrib_SparseEmbedding")]:
    if _new in _OPS and _old not in _OPS:
        _OPS[_old] = _OPS[_new]


@register("Correlation", num_inputs=2, aliases=("correlation",))
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer (ref: src/operator/correlation.cc
    CorrelationForward :44, shape math correlation-inl.h:99-108).
    Static python loops over the (small) displacement grid and kernel
    window unroll into one fused XLA program."""
    K = int(kernel_size)
    md = int(max_displacement)
    s1, s2, p = int(stride1), int(stride2), int(pad_size)
    kr = K // 2
    border = md + kr
    B, C, H, W = data1.shape
    pH, pW = H + 2 * p, W + 2 * p
    top_h = -(-(pH - 2 * border) // s1)     # ceil div
    top_w = -(-(pW - 2 * border) // s1)
    ngr = md // s2
    ngw = 2 * ngr + 1
    sumelems = float(K * K * C)

    # NHWC padded copies (ref AddPad)
    t1 = jnp.pad(jnp.transpose(data1, (0, 2, 3, 1)),
                 ((0, 0), (p, p), (p, p), (0, 0)))
    t2 = jnp.pad(jnp.transpose(data2, (0, 2, 3, 1)),
                 ((0, 0), (p, p), (p, p), (0, 0)))

    def block(src, ys, xs):
        # kernel anchored TOP-LEFT like the reference (tmp[y1+h][x1+w]);
        # min t2 start = md - md = 0, so starts never go negative
        return src[:, ys:ys + (top_h - 1) * s1 + 1:s1,
                   xs:xs + (top_w - 1) * s1 + 1:s1, :]

    outs = []
    for tc in range(ngw * ngw):
        s2o = (tc % ngw - ngr) * s2
        s2p = (tc // ngw - ngr) * s2
        acc = 0.0
        for h in range(K):
            for w in range(K):
                a = block(t1, md + h, md + w)
                b = block(t2, md + h + s2p, md + w + s2o)
                acc = acc + (a * b if is_multiply else jnp.abs(a - b))
        outs.append(jnp.sum(acc, axis=-1) / sumelems)
    return jnp.stack(outs, axis=1)
