"""Shape manipulation, indexing and reduction ops.

TPU-native re-design of the reference's tensor op families
(ref: src/operator/tensor/matrix_op.cc, broadcast_reduce_op_value.cc,
indexing_op.cc, ordering_op.cc, init_op.cc). MXNet reshape special codes
(0/-1/-2/-3/-4, ref: src/operator/tensor/matrix_op-inl.h InferReshapeShape)
are honoured. All shapes are static for XLA; ops with data-dependent output
shapes (boolean mask) take a static max size or fall back to host.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .registry import register


# ---------------------------------------------------------------------------
# reshape & friends
# ---------------------------------------------------------------------------

def infer_reshape(src_shape, target):
    """Implement MXNet reshape codes (ref: matrix_op-inl.h:InferReshapeShape):
    0 copy dim, -1 infer, -2 copy all remaining, -3 merge two dims,
    -4 split one dim into two (one may be -1)."""
    src = list(src_shape)
    out = []
    i = 0  # index into src
    t = list(target)
    j = 0
    while j < len(t):
        d = t[j]
        if d == 0:
            out.append(src[i]); i += 1
        elif d == -1:
            out.append(-1); i += 1 if i < len(src) else 0
        elif d == -2:
            out.extend(src[i:]); i = len(src)
        elif d == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif d == -4:
            d1, d2 = t[j + 1], t[j + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(d); i += 1
        j += 1
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in src_shape:
            total *= d
        out[out.index(-1)] = total // max(known, 1)
    return tuple(out)


@register("reshape", num_inputs=1, aliases=("Reshape",))
def reshape(x, shape=None, reverse=False):
    if reverse:
        rshape = infer_reshape(x.shape[::-1], list(shape)[::-1])[::-1]
        return jnp.reshape(x, rshape)
    return jnp.reshape(x, infer_reshape(x.shape, shape))


@register("flatten", num_inputs=1, aliases=("Flatten",))
def flatten(x):
    return jnp.reshape(x, (x.shape[0], -1))


@register("transpose", num_inputs=1)
def transpose(x, axes=None):
    if axes is None or len(axes) == 0:
        axes = tuple(range(x.ndim))[::-1]
    return jnp.transpose(x, axes)


@register("swapaxes", num_inputs=1, aliases=("SwapAxis",))
def swapaxes(x, dim1=0, dim2=0):
    return jnp.swapaxes(x, dim1, dim2)


@register("expand_dims", num_inputs=1)
def expand_dims(x, axis=0):
    return jnp.expand_dims(x, axis)


@register("squeeze", num_inputs=1)
def squeeze(x, axis=None):
    return jnp.squeeze(x, axis)


@register("broadcast_to", num_inputs=1)
def broadcast_to(x, shape=None):
    tgt = tuple(s if t == 0 else t for s, t in zip(x.shape, shape))
    return jnp.broadcast_to(x, tgt)


@register("broadcast_like", num_inputs=2)
def broadcast_like(x, like, lhs_axes=None, rhs_axes=None):
    """ref: src/operator/tensor/broadcast_reduce_op.h BroadcastLikeParam —
    with axes given, only those lhs dims take the matching rhs sizes."""
    if lhs_axes is None and rhs_axes is None:
        return jnp.broadcast_to(x, like.shape)
    if lhs_axes is None or rhs_axes is None:
        raise ValueError("broadcast_like needs both lhs_axes and rhs_axes "
                         "or neither")
    lhs_axes = (lhs_axes,) if isinstance(lhs_axes, int) else tuple(lhs_axes)
    rhs_axes = (rhs_axes,) if isinstance(rhs_axes, int) else tuple(rhs_axes)
    if len(lhs_axes) != len(rhs_axes) or not lhs_axes:
        raise ValueError("lhs_axes and rhs_axes must be equal-length and "
                         "non-empty, got %s vs %s" % (lhs_axes, rhs_axes))
    tgt = list(x.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        tgt[la % x.ndim] = like.shape[ra % like.ndim]
    return jnp.broadcast_to(x, tuple(tgt))


@register("broadcast_axis", num_inputs=1, aliases=("broadcast_axes",))
def broadcast_axis(x, axis=(), size=()):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    size = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(x.shape)
    for a, s in zip(axis, size):
        tgt[a] = s
    return jnp.broadcast_to(x, tuple(tgt))


@register("tile", num_inputs=1)
def tile(x, reps=()):
    return jnp.tile(x, reps)


@register("repeat", num_inputs=1)
def repeat(x, repeats=1, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register("reverse", num_inputs=1, aliases=("flip",))
def reverse(x, axis=()):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(x, axis)


@register("concat", aliases=("Concat", "concatenate"))
def concat(*xs, dim=1):
    return jnp.concatenate(xs, axis=dim)


@register("stack")
def stack(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


@register("split", num_inputs=1, aliases=("SliceChannel",))
def split(x, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


@register("slice", num_inputs=1, aliases=("crop",))
def slice_op(x, begin=(), end=(), step=()):
    idx = []
    step = step or (None,) * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(builtins_slice(b, e, s))
    return x[tuple(idx)]


def builtins_slice(b, e, s):
    return slice(b, e, s)


@register("slice_axis", num_inputs=1)
def slice_axis(x, axis=0, begin=0, end=None):
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register("slice_like", num_inputs=2)
def slice_like(x, like, axes=()):
    axes = tuple(axes) if axes else tuple(range(min(x.ndim, like.ndim)))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a] = slice(0, like.shape[a])
    return x[tuple(idx)]


@register("pad", num_inputs=1, aliases=("Pad",))
def pad(x, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(x.ndim)]
    if mode == "constant":
        return jnp.pad(x, pw, mode="constant", constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(x, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(x, pw, mode="reflect")
    raise ValueError("unknown pad mode %r" % (mode,))


@register("where", num_inputs=3)
def where(cond, x, y):
    return jnp.where(cond.astype(bool), x, y)


@register("diag", num_inputs=1)
def diag(x, k=0, axis1=0, axis2=1):
    if x.ndim == 1:
        return jnp.diag(x, k)
    return jnp.diagonal(x, offset=k, axis1=axis1, axis2=axis2)


@register("depth_to_space", num_inputs=1)
def depth_to_space(x, block_size=1):
    n, c, h, w = x.shape
    b = block_size
    y = x.reshape(n, b, b, c // (b * b), h, w)
    y = y.transpose(0, 3, 4, 1, 5, 2)
    return y.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth", num_inputs=1)
def space_to_depth(x, block_size=1):
    n, c, h, w = x.shape
    b = block_size
    y = x.reshape(n, c, h // b, b, w // b, b)
    y = y.transpose(0, 3, 5, 1, 2, 4)
    return y.reshape(n, c * b * b, h // b, w // b)


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------

@register("take", num_inputs=2)
def take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
        mode = "clip"
    return jnp.take(a, idx, axis=axis, mode=mode)


@register("pick", num_inputs=2)
def pick(x, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(index.astype(jnp.int32), 0, x.shape[axis] - 1)
    out = jnp.take_along_axis(x, jnp.expand_dims(idx, axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("one_hot", num_inputs=1, no_grad=True)
def one_hot(indices, depth=0, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
    return oh * (on_value - off_value) + off_value


@register("gather_nd", num_inputs=2)
def gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register("scatter_nd", num_inputs=2, no_grad=False)
def scatter_nd(data, indices, shape=None):
    out = jnp.zeros(shape, data.dtype)
    idx = tuple(indices.astype(jnp.int32))
    return out.at[idx].add(data)


@register("Embedding", num_inputs=2, aliases=("embedding",))
def embedding(data, weight, input_dim=None, output_dim=None, dtype=None,
              sparse_grad=False):
    return jnp.take(weight, data.astype(jnp.int32), axis=0, mode="clip")


@register("SequenceMask", num_inputs=2, aliases=("sequence_mask",))
def sequence_mask(data, sequence_length=None, use_sequence_length=True,
                  value=0.0, axis=0):
    # data: (T, B, ...) when axis=0, (B, T, ...) when axis=1
    # ref: src/operator/sequence_mask.cc
    if not use_sequence_length or sequence_length is None:
        return data
    T = data.shape[axis]
    pos = jnp.arange(T)
    if axis == 0:
        mask = pos[:, None] < sequence_length[None, :]
    else:
        mask = pos[None, :] < sequence_length[:, None]
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register("SequenceLast", num_inputs=2, aliases=("sequence_last",))
def sequence_last(data, sequence_length=None, use_sequence_length=True, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    if axis == 0:  # (T, B, ...)
        return jnp.take_along_axis(
            data, last.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0)[0]
    return jnp.take_along_axis(
        data, last.reshape((-1, 1) + (1,) * (data.ndim - 2)), axis=1)[:, 0]


@register("SequenceReverse", num_inputs=2, aliases=("sequence_reverse",))
def sequence_reverse(data, sequence_length=None, use_sequence_length=True, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    pos = jnp.arange(T)[:, None]
    L = sequence_length.astype(jnp.int32)[None, :]
    src = jnp.where(pos < L, L - 1 - pos, pos)  # (T, B)
    src = src.reshape(src.shape + (1,) * (data.ndim - 2))
    return jnp.take_along_axis(data, src, axis=0)


# ---------------------------------------------------------------------------
# reductions & ordering
# ---------------------------------------------------------------------------

def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, int):
        return axis
    ax = tuple(axis)
    return ax if ax else None


def _reduce(jfn):
    def _fn(x, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis)
        if exclude and ax is not None:
            ax_set = {a % x.ndim for a in (ax if isinstance(ax, tuple) else (ax,))}
            ax = tuple(i for i in range(x.ndim) if i not in ax_set)
        return jfn(x, axis=ax, keepdims=keepdims)
    return _fn


register("sum", num_inputs=1, aliases=("sum_axis",))(_reduce(jnp.sum))
register("mean", num_inputs=1)(_reduce(jnp.mean))
register("prod", num_inputs=1)(_reduce(jnp.prod))
register("nansum", num_inputs=1)(_reduce(jnp.nansum))
register("nanprod", num_inputs=1)(_reduce(jnp.nanprod))
register("max", num_inputs=1, aliases=("max_axis",))(_reduce(jnp.max))
register("min", num_inputs=1, aliases=("min_axis",))(_reduce(jnp.min))


@register("norm", num_inputs=1)
def norm(x, ord=2, axis=None, keepdims=False):
    ax = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims))


@register("argmax", num_inputs=1, no_grad=True)
def argmax(x, axis=None, keepdims=False):
    out = jnp.argmax(x, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(jnp.float32)


@register("argmin", num_inputs=1, no_grad=True)
def argmin(x, axis=None, keepdims=False):
    out = jnp.argmin(x, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(jnp.float32)


@register("argmax_channel", num_inputs=1, no_grad=True)
def argmax_channel(x):
    return jnp.argmax(x, axis=1).astype(jnp.float32)


@register("cumsum", num_inputs=1)
def cumsum(x, axis=None, dtype=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    out = jnp.cumsum(x, axis=axis)
    return out.astype(jnp.dtype(dtype)) if dtype else out


@register("sort", num_inputs=1)
def sort(x, axis=-1, is_ascend=True):
    out = jnp.sort(x, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort", num_inputs=1, no_grad=True)
def argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.dtype(dtype))


@register("topk", num_inputs=1, no_grad=True)
def topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    # ref: src/operator/tensor/ordering_op.cc TopK
    axis = axis % x.ndim
    moved = jnp.moveaxis(x, axis, -1)
    key = -moved if is_ascend else moved
    _, idxs = jax.lax.top_k(key, k)
    values = jnp.moveaxis(jnp.take_along_axis(moved, idxs, -1), -1, axis)
    indices = jnp.moveaxis(idxs, -1, axis).astype(jnp.dtype(dtype))
    if ret_typ == "indices":
        return indices
    if ret_typ == "value":
        return values
    if ret_typ == "both":
        return values, indices
    if ret_typ == "mask":
        oh = jax.nn.one_hot(idxs, x.shape[axis], dtype=jnp.dtype(dtype)).sum(-2)
        return jnp.moveaxis(oh, -1, axis)
    raise ValueError(ret_typ)


@register("shape_array", num_inputs=1, no_grad=True)
def shape_array(x):
    return jnp.asarray(x.shape, jnp.int64)


@register("size_array", num_inputs=1, no_grad=True)
def size_array(x):
    return jnp.asarray([x.size], jnp.int64)


@register("cast", num_inputs=1, aliases=("Cast",))
def cast(x, dtype="float32"):
    from ..base import canonical_dtype
    return x.astype(canonical_dtype(dtype))


@register("amp_cast", num_inputs=1)
def amp_cast(x, dtype="bfloat16"):
    from ..base import canonical_dtype
    return x.astype(canonical_dtype(dtype))


@register("zeros_like", num_inputs=1, no_grad=True)
def zeros_like(x):
    return jnp.zeros_like(x)


@register("ones_like", num_inputs=1, no_grad=True)
def ones_like(x):
    return jnp.ones_like(x)


@register("identity", num_inputs=1, aliases=("_copy", "BlockGrad_inner"))
def identity(x):
    return x


@register("stop_gradient", num_inputs=1, aliases=("BlockGrad",))
def stop_gradient(x):
    return jax.lax.stop_gradient(x)


@register("make_loss", num_inputs=1, aliases=("MakeLoss",))
def make_loss(x, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    return x


@register("_zeros", num_inputs=0, no_grad=True)
def _zeros(shape=(), dtype="float32"):
    """ref: src/operator/tensor/init_op.cc _zeros."""
    return jnp.zeros(tuple(shape), dtype or "float32")


@register("_ones", num_inputs=0, no_grad=True)
def _ones(shape=(), dtype="float32"):
    """ref: src/operator/tensor/init_op.cc _ones."""
    return jnp.ones(tuple(shape), dtype or "float32")


@register("arange", num_inputs=0, no_grad=True, aliases=("_arange",))
def arange(start=0.0, stop=None, step=1.0, repeat=1, infer_range=False,
           dtype="float32"):
    """ref: src/operator/tensor/init_op.cc _arange (RangeParam)."""
    out = jnp.arange(start, stop, step, dtype or "float32")
    if repeat and int(repeat) > 1:
        out = jnp.repeat(out, int(repeat))
    return out


@register("full", num_inputs=0, no_grad=True, aliases=("_full",))
def full(shape=(), value=0.0, dtype="float32"):
    """ref: src/operator/tensor/init_op.cc _full (InitOpWithScalarParam)."""
    return jnp.full(tuple(shape), value, dtype or "float32")


@register("_full", num_inputs=0, no_grad=True)
def _full(shape=(), dtype="float32", value=0.0):
    """ref: src/operator/tensor/init_op.cc _full."""
    return jnp.full(tuple(shape), value, dtype or "float32")
