"""Neural-network ops: conv, pooling, norm layers, softmax, dropout, FC.

TPU-native re-design of the reference's nn operator family
(ref: src/operator/nn/convolution.cc, pooling.cc, batch_norm.cc,
layer_norm.cc, softmax.cc, fully_connected.cc, dropout-inl.h, lrn.cc,
activation.cc, src/operator/leaky_relu-inl.h). The cuDNN wrapper layer
(ref: src/operator/nn/cudnn/) has no analog: XLA:TPU lowers
conv_general_dilated / reduce_window straight onto the MXU/VPU, and algorithm
selection (ref: cudnn_algoreg-inl.h) is the compiler's autotuner's job.

Layout: the reference default is NCHW and stays the public default for
parity (XLA:TPU relayouts internally either way). Conv/Deconv/Pooling
also honor the channels-last layouts (NWC/NHWC/NDHWC) for channels-last
model variants (model_zoo resnet `layout="NHWC"`); weights stay OIHW in
every layout so `.params` checkpoints are layout-independent.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .registry import register


def _ckpt_name(x, name):
    """Tag a value for remat policies (jax.ad_checkpoint.checkpoint_name).
    ShardedTrainStep(remat_policy="conv_outs") saves ONLY tagged values
    between forward and backward — normalized/activated intermediates are
    then recomputed in backward, fused into the consuming matmuls, so
    they never persist in HBM (round-4 ResNet HBM work; a no-op unless a
    surrounding jax.checkpoint policy references the name)."""
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(x, name)


def _pair(v, n=2):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    return v if len(v) == n else v * n


@register("FullyConnected", num_inputs=None, aliases=("fully_connected",))
def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True, precision=None):
    # precision=None defers to the global matmul policy
    # (mxnet_tpu/precision.py, MXTPU_MATMUL_PRECISION)
    if flatten:
        x2 = x.reshape(x.shape[0], -1)
    else:
        x2 = x
    out = jnp.matmul(x2, weight.T, precision=precision)
    if bias is not None and not no_bias:
        out = out + bias
    return out


def _channels_last(layout):
    """True for the channels-last layouts (NWC/NHWC/NDHWC)."""
    return layout is not None and layout.endswith("C")


@register("Convolution", aliases=("convolution",))
def convolution(x, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False,
                layout="NCHW", cudnn_tune=None, cudnn_off=False,
                workspace=1024, precision=None):
    """N-D convolution (1D/2D/3D by kernel length), NCHW/NCW/NCDHW layouts.
    ref: src/operator/nn/convolution-inl.h ConvolutionParam/ConvolutionCompute.
    """
    nd = len(kernel) if kernel is not None else x.ndim - 2
    if kernel is not None and tuple(weight.shape[2:]) != tuple(kernel):
        # the reference CHECKs param-vs-weight consistency at infer time
        # (ref: convolution-inl.h kernel shape checks)
        raise ValueError(
            "Convolution kernel param %s does not match weight spatial "
            "shape %s" % (tuple(kernel), tuple(weight.shape[2:])))
    stride = _pair(stride, nd)
    dilate = _pair(dilate, nd)
    pad = _pair(pad if pad is not None else 0, nd)
    padding = [(p, p) for p in pad]
    # channels-last layouts (NWC/NHWC/NDHWC) keep activations in the
    # TPU-native resident layout — XLA then tiles convs onto the MXU
    # without the relayout copies an NCHW graph needs. Weights stay
    # OIHW in both layouts for .params checkpoint compat; XLA folds the
    # transposition into the conv.
    channels_last = _channels_last(layout)
    if channels_last and num_group == 1 and nd == 2 \
            and tuple(weight.shape[2:]) == (1, 1) and pad == (0, 0):
        # 1x1 NHWC conv == one MXU matmul over [N*H*W, Cin]. Expressed
        # as a dot (not conv_general) because XLA:TPU fuses elementwise
        # PRODUCERS into dot operand loads but not into convolutions
        # (measured: benchmark/fusion_probe.py) — so a preceding
        # BN-affine+ReLU rides the operand load instead of
        # materializing. Strides become a free slice of the input.
        xs = x[:, ::stride[0], ::stride[1], :] if stride != (1, 1) else x
        n, h, w_, cin = xs.shape
        out = jnp.matmul(xs.reshape(n * h * w_, cin),
                         weight.reshape(weight.shape[0], cin).T,
                         precision=precision)
        out = out.reshape(n, h, w_, weight.shape[0])
        if bias is not None and not no_bias:
            out = out + bias.reshape((1, 1, 1, -1))
        return _ckpt_name(out, "conv_out")
    spatial = "DHW"[3 - nd:]
    act = ("N" + spatial + "C") if channels_last else ("NC" + spatial)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape, (act, "OI" + spatial, act))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding,
        lhs_dilation=(1,) * nd, rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=None, precision=precision)
    if bias is not None and not no_bias:
        bshape = ((1,) + (1,) * nd + (-1,)) if channels_last \
            else ((1, -1) + (1,) * nd)
        out = out + bias.reshape(bshape)
    return _ckpt_name(out, "conv_out")


@register("Deconvolution", aliases=("deconvolution",))
def deconvolution(x, weight, bias=None, kernel=None, stride=None, dilate=None,
                  pad=None, adj=None, target_shape=None, num_filter=None,
                  num_group=1, no_bias=True, layout="NCHW", cudnn_tune=None,
                  cudnn_off=False, workspace=512, precision=None):
    """Transposed convolution. ref: src/operator/nn/deconvolution-inl.h.
    Implemented as conv_general_dilated with lhs_dilation (fractional stride).
    """
    nd = len(kernel)
    if tuple(weight.shape[2:]) != tuple(kernel):
        raise ValueError(
            "Deconvolution kernel param %s does not match weight spatial "
            "shape %s" % (tuple(kernel), tuple(weight.shape[2:])))
    stride = _pair(stride, nd)
    dilate = _pair(dilate, nd)
    pad = _pair(pad if pad is not None else 0, nd)
    adj = _pair(adj if adj is not None else 0, nd)
    # effective kernel
    k_eff = [dilate[i] * (kernel[i] - 1) + 1 for i in range(nd)]
    padding = [(k_eff[i] - 1 - pad[i], k_eff[i] - 1 - pad[i] + adj[i])
               for i in range(nd)]
    # weight layout in reference deconv: (in_channels, out_channels/g, *k)
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    if num_group > 1:
        cin, cog = w.shape[0], w.shape[1]
        w = w.reshape(num_group, cin // num_group, cog, *w.shape[2:])
        w = jnp.swapaxes(w, 1, 2).reshape(num_group * cog, cin // num_group,
                                          *w.shape[3:])
    else:
        w = jnp.swapaxes(w, 0, 1)
    channels_last = _channels_last(layout)
    spatial = "DHW"[3 - nd:]
    act = ("N" + spatial + "C") if channels_last else ("NC" + spatial)
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                        (act, "OI" + spatial, act))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(1,) * nd, padding=padding,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group, precision=precision)
    if bias is not None and not no_bias:
        bshape = ((1,) + (1,) * nd + (-1,)) if channels_last \
            else ((1, -1) + (1,) * nd)
        out = out + bias.reshape(bshape)
    return out


@register("Pooling", num_inputs=1, aliases=("pooling",))
def pooling(x, kernel=None, pool_type="max", stride=None, pad=None,
            global_pool=False, pooling_convention="valid", cudnn_off=False,
            p_value=2, count_include_pad=True, layout=None):
    """ref: src/operator/nn/pooling-inl.h PoolingParam. Supports both
    channels-first (NCW/NCHW/NCDHW) and TPU-native channels-last
    (NWC/NHWC/NDHWC) layouts."""
    nd = x.ndim - 2
    channels_last = _channels_last(layout)
    spatial0 = 1 if channels_last else 2  # first spatial dim index
    if global_pool:
        axes = tuple(range(spatial0, spatial0 + nd))
        if pool_type == "max":
            return jnp.max(x, axis=axes, keepdims=True)
        if pool_type in ("avg", "sum"):
            red = jnp.sum if pool_type == "sum" else jnp.mean
            return red(x, axis=axes, keepdims=True)
        if pool_type == "lp":
            return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p_value), axis=axes,
                                     keepdims=True), 1.0 / p_value)
    kernel = _pair(kernel, nd)
    stride = _pair(stride, nd)
    pad = _pair(pad if pad is not None else 0, nd)
    if channels_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil division output size (ref: pooling-inl.h kFull)
        spad = []
        for i in range(nd):
            in_sz = x.shape[spatial0 + i] + 2 * pad[i]
            out_sz = -(-(in_sz - kernel[i]) // stride[i]) + 1
            needed = (out_sz - 1) * stride[i] + kernel[i] - in_sz
            spad.append((pad[i], pad[i] + max(needed, 0)))
    else:
        spad = [(p, p) for p in pad]
    padding = ([(0, 0)] + spad + [(0, 0)]) if channels_last \
        else [(0, 0), (0, 0)] + spad

    if pool_type == "max":
        # NB: init must stay a weak-typed Python scalar — an array init value
        # breaks reverse-mode linearization of reduce_window under jit
        init = -float("inf") if jnp.issubdtype(x.dtype, jnp.floating) else \
            int(jnp.iinfo(x.dtype).min)
        return _ckpt_name(jax.lax.reduce_window(x, init, jax.lax.max,
                                                window, strides, padding),
                          "pool_out")
    if pool_type in ("avg", "sum"):
        s = jax.lax.reduce_window(x, 0.0 if jnp.issubdtype(
            x.dtype, jnp.floating) else 0, jax.lax.add,
                                  window, strides, padding)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return s / denom
        ones = jnp.ones_like(x)
        cnt = jax.lax.reduce_window(ones, 0.0 if jnp.issubdtype(
            x.dtype, jnp.floating) else 0, jax.lax.add,
                                    window, strides, padding)
        return s / cnt
    if pool_type == "lp":
        s = jax.lax.reduce_window(jnp.power(jnp.abs(x), p_value),
                                  0.0, jax.lax.add,
                                  window, strides, padding)
        return jnp.power(s, 1.0 / p_value)
    raise ValueError("unknown pool_type %r" % (pool_type,))


@register("Activation", num_inputs=1, aliases=("activation",))
def activation(x, act_type="relu"):
    # ref: src/operator/nn/activation-inl.h
    if act_type == "relu":
        return jnp.maximum(x, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(x)
    if act_type == "tanh":
        return jnp.tanh(x)
    if act_type == "softrelu":
        return jax.nn.softplus(x)
    if act_type == "softsign":
        return x / (1 + jnp.abs(x))
    raise ValueError("unknown act_type %r" % (act_type,))


@register("LeakyReLU", aliases=("leaky_relu",))
def leaky_relu(x, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334):
    # ref: src/operator/leaky_relu-inl.h
    if act_type == "leaky":
        return jnp.where(x > 0, x, slope * x)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (x.ndim - 2)) if gamma.ndim == 1 \
            else gamma
        return jnp.where(x > 0, x, g * x)
    if act_type == "elu":
        return jnp.where(x > 0, x, slope * jnp.expm1(x))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))
    if act_type == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(x > 0, x, mid * x)
    raise ValueError("unknown act_type %r" % (act_type,))


@register("softmax", num_inputs=1)
def softmax(x, axis=-1, temperature=None, length=None, use_length=False,
            dtype=None):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if use_length and length is not None:
        T = x.shape[axis]
        pos = jnp.arange(T)
        shp = [1] * x.ndim
        shp[axis] = T
        mask = pos.reshape(shp) < length.reshape(
            length.shape + (1,) * (x.ndim - length.ndim))
        x = jnp.where(mask, x, -jnp.inf)
        out = jax.nn.softmax(x, axis=axis)
        return jnp.where(mask, out, 0.0)
    out = jax.nn.softmax(x, axis=axis)
    return out.astype(jnp.dtype(dtype)) if dtype else out


@register("log_softmax", num_inputs=1)
def log_softmax(x, axis=-1, temperature=None, dtype=None):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    out = jax.nn.log_softmax(x, axis=axis)
    return out.astype(jnp.dtype(dtype)) if dtype else out


@register("softmin", num_inputs=1)
def softmin(x, axis=-1):
    return jax.nn.softmax(-x, axis=axis)


@register("softmax_cross_entropy", num_inputs=2)
def softmax_cross_entropy(data, label):
    # ref: src/operator/loss_binary_op.cc
    logp = jax.nn.log_softmax(data, axis=-1)
    nll = -jnp.take_along_axis(logp, label.astype(jnp.int32)[:, None], -1)
    return jnp.sum(nll)


@register("SoftmaxOutput", num_inputs=2, aliases=("softmax_output",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    """Forward = softmax. Backward is the reference's custom gradient
    (softmax - onehot(label)) * grad_scale, which IGNORES the incoming
    cotangent unless out_grad=True — this is what makes bare
    ``backward()`` on a SoftmaxOutput head train the net.
    ref: src/operator/softmax_output-inl.h (SoftmaxOutputGrad).
    """
    axis = 1 if multi_output else -1

    @jax.custom_vjp
    def core(data, label):
        return jax.nn.softmax(data, axis=axis)

    def core_fwd(data, label):
        out = jax.nn.softmax(data, axis=axis)
        return out, (out, label)

    def core_bwd(res, g):
        out, label = res
        num_classes = out.shape[axis]
        lbl = label.astype(jnp.int32)
        onehot = jax.nn.one_hot(lbl, num_classes, axis=axis, dtype=out.dtype)
        if smooth_alpha:
            onehot = onehot * (1.0 - smooth_alpha) \
                + smooth_alpha / (num_classes - 1) * (1.0 - onehot)
        grad = out - onehot
        valid = None
        if use_ignore:
            keep = (label != ignore_label)
            grad = grad * jnp.expand_dims(keep, axis).astype(grad.dtype)
            valid = jnp.sum(keep)
        if normalization == "batch":
            grad = grad / out.shape[0]
        elif normalization == "valid":
            n = valid if valid is not None else label.size
            grad = grad / jnp.maximum(n, 1).astype(grad.dtype)
        grad = grad * grad_scale
        if out_grad:
            grad = grad * g
        return grad, jnp.zeros_like(label)

    core.defvjp(core_fwd, core_bwd)
    return core(data, label)


def _regression_head(fwd, grad_fn):
    """Loss-head pattern shared by the *RegressionOutput ops: forward is the
    prediction, backward is a fixed (pred, label) -> grad rule scaled by
    grad_scale / num-outputs-per-example, ignoring the incoming cotangent
    (ref: src/operator/regression_output-inl.h RegressionBackward,
    num_output = label.Size()/label.shape_[0])."""
    def op(data, label, grad_scale=1.0):
        @jax.custom_vjp
        def core(data, label):
            return fwd(data)

        def core_fwd(data, label):
            out = fwd(data)
            return out, (out, label)

        def core_bwd(res, g):
            pred, lbl = res
            lbl = jnp.reshape(lbl, pred.shape)
            batch = pred.shape[0] if pred.ndim else 1
            num_output = max(pred.size // max(batch, 1), 1)
            grad = grad_fn(pred, lbl) * (grad_scale / num_output)
            return grad.astype(pred.dtype), jnp.zeros_like(res[1])

        core.defvjp(core_fwd, core_bwd)
        return core(data, label)
    return op


@register("LinearRegressionOutput", num_inputs=2,
          aliases=("linear_regression_output",))
def linear_regression_output(data, label, grad_scale=1.0):
    """ref: src/operator/regression_output.cc LinearRegressionOutput."""
    return _regression_head(lambda x: x, lambda p, l: p - l)(
        data, label, grad_scale)


@register("MAERegressionOutput", num_inputs=2,
          aliases=("mae_regression_output",))
def mae_regression_output(data, label, grad_scale=1.0):
    """ref: src/operator/regression_output.cc MAERegressionOutput."""
    return _regression_head(lambda x: x, lambda p, l: jnp.sign(p - l))(
        data, label, grad_scale)


@register("LogisticRegressionOutput", num_inputs=2,
          aliases=("logistic_regression_output",))
def logistic_regression_output(data, label, grad_scale=1.0):
    """ref: src/operator/regression_output.cc LogisticRegressionOutput."""
    return _regression_head(jax.nn.sigmoid, lambda p, l: p - l)(
        data, label, grad_scale)


import functools as _functools


# mxlint: disable=MX005 (shape-keyed by jax's own cache, bounded by the
#         distinct normalization shapes a model contains; the ONE stable
#         jit object keeps the ~50-eqn deterministic reduction a single
#         call eqn inside every enclosing trace, so record-mode
#         per-call linearization does not re-walk it)
@_functools.partial(jax.jit, static_argnames=("single_pass",))
def _moments_core(x2, single_pass):
    """(R, C) f32 -> (mean32, var32): the deterministic stat math of
    batch_moments (see its docstring for the numerics contract)."""
    from ..pallas_kernels.batchnorm_fused import exact_sq, tree_fold_rows
    n = x2.shape[0]
    mean32 = tree_fold_rows(x2)[0] / n
    if single_pass:
        var32 = tree_fold_rows(exact_sq(x2))[0] / n - exact_sq(mean32)
        var32 = jnp.maximum(var32, 0.0)
    else:
        var32 = tree_fold_rows(exact_sq(x2 - mean32))[0] / n
    return mean32, var32


# mxlint: disable=MX005 (same bounded shape-keyed contract as
#         _moments_core above: one stable jit object per process)
@_functools.partial(jax.jit, static_argnames=("cax",))
def _bn_apply_core(x, mean32, var32, g, beta, eps, cax):
    """The BN normalize chain over f32 stats. ``exact_mul`` + a
    trailing add of already-rounded values: every op is
    correctly-rounded over deterministic inputs, so no fusion context
    or backend can move the output by a bit (the per-op ULP gate's
    BatchNorm<=64 relies on this — last-bit noise here gets amplified
    without bound in ULP terms wherever the output crosses zero)."""
    from ..pallas_kernels.batchnorm_fused import exact_mul
    shape = [1] * x.ndim
    shape[cax] = x.shape[cax]
    inv = 1.0 / jnp.sqrt(var32 + eps)
    out = exact_mul(
        x.astype(jnp.float32) - mean32.reshape(shape),
        (inv * g.astype(jnp.float32)).reshape(shape)) \
        + beta.astype(jnp.float32).reshape(shape)
    return out.astype(x.dtype)


def batch_moments(x, axes, axis=None, fp32_out=False):
    """Batch mean/var for normalization — the ONE definition of this
    framework's BN stat semantics (the fused-conv BN fold in
    gluon/model_zoo/vision/resnet.py must stay bit-identical to the
    BatchNorm op, so both call here). Returns stats cast to x.dtype,
    or raw f32 with ``fp32_out=True`` (the BatchNorm op normalizes
    with the f32 stats and casts only the values it RETURNS, so
    half-precision inputs never round the stats before the rsqrt).

    Both stats accumulate in f32 through the deterministic reduction
    of ``pallas_kernels/batchnorm_fused``: a fixed block-structured
    pairwise tree (``tree_fold_rows``) of pure correctly-rounded f32
    adds, with squares produced by ``exact_sq`` (exact-product
    splitting, so FMA contraction — which differs per compiled
    program — cannot perturb a single bit). The same statistic is
    therefore bitwise-identical across platforms, fusion contexts,
    and the Pallas kernel's tiling. That is the lever behind the
    BatchNorm entry of the per-op ULP gate (budget 64, down from the
    11,482 BENCH_r05 measured): the big outlier was free-order
    ``jnp.mean`` noise amplified by the ``x - mean`` cancellation.
    Half-precision inputs: single-pass E[x^2]-E[x]^2 (the cancellation
    term ~mean^2 * 2^-24 is ~256x smaller than the bf16
    input-quantization noise). Full-precision inputs: two-pass
    E[(x-mean)^2], where single-pass cancellation WOULD dominate for
    |mean| >> std.
    """
    keep = (axis % x.ndim) if axis is not None else [
        i for i in range(x.ndim) if i not in axes][0]
    c = x.shape[keep]
    x2 = jnp.moveaxis(x.astype(jnp.float32), keep, -1).reshape(-1, c)
    mean32, var32 = _moments_core(
        x2, jnp.dtype(x.dtype).itemsize <= 2)
    out_dt = jnp.float32 if fp32_out else x.dtype
    # tagged so conv-outs remat policies keep the (tiny) stat vectors
    # instead of re-reducing the activation in backward
    return (_ckpt_name(mean32.astype(out_dt), "bn_stat"),
            _ckpt_name(var32.astype(out_dt), "bn_stat"))


@register("BatchNorm", aliases=("batch_norm",))
def batch_norm(x, gamma, beta, moving_mean, moving_var, eps=1e-3, momentum=0.9,
               fix_gamma=True, use_global_stats=False, output_mean_var=False,
               axis=1, cudnn_off=False, min_calib_range=None,
               max_calib_range=None, _training=True):
    """Returns (out, batch_mean, batch_var). Moving-stat update is done by the
    caller (gluon layer / stateful executor) — functional purity for XLA.
    ref: src/operator/nn/batch_norm-inl.h.

    Numerics: stats accumulate in f32 (batch_moments' deterministic
    tree) and the normalize chain runs in f32 off f32 stats —
    ``1/sqrt`` (correctly rounded on every backend) instead of the
    approximate ``lax.rsqrt`` — casting only the returned values, so
    half-precision inputs no longer round mean/var before the inverse
    and the per-op ULP gate holds BatchNorm at <=64. Training-mode
    channels-last calls route through the fused Pallas kernel
    (pallas_kernels/batchnorm_fused.py, ``MXTPU_FUSED_BN``) on TPU;
    identical stat semantics, moving-stat contract unchanged.
    """
    cax = axis % x.ndim
    axes = tuple(i for i in range(x.ndim) if i != cax)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    use_batch = _training and not use_global_stats
    if use_batch:
        from ..pallas_kernels import batchnorm_fused as _bnf
        if _bnf.engaged(x, cax):
            out, mean32, var32 = _bnf.fused_batch_norm(
                x, g, beta, eps=eps)
            return (out, _ckpt_name(mean32.astype(x.dtype), "bn_stat"),
                    _ckpt_name(var32.astype(x.dtype), "bn_stat"))
        mean32, var32 = batch_moments(x, axes, axis, fp32_out=True)
        mean, var = mean32.astype(x.dtype), var32.astype(x.dtype)
    else:
        mean, var = moving_mean, moving_var
        mean32 = mean.astype(jnp.float32)
        var32 = var.astype(jnp.float32)
    return (_bn_apply_core(x, mean32, var32, g, beta,
                           jnp.float32(eps), cax), mean, var)


@register("LayerNorm", num_inputs=3, aliases=("layer_norm",))
def layer_norm(x, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    # ref: src/operator/nn/layer_norm.cc
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    shape[axis % x.ndim] = x.shape[axis % x.ndim]
    out = (x - mean) * inv * gamma.reshape(shape) + beta.reshape(shape)
    if output_mean_var:
        return out, jnp.squeeze(mean, axis), jnp.squeeze(var, axis)
    return out


@register("InstanceNorm", num_inputs=3, aliases=("instance_norm",))
def instance_norm(x, gamma, beta, eps=1e-3):
    # ref: src/operator/instance_norm-inl.h (normalize over spatial dims)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean) * jax.lax.rsqrt(var + eps) * gamma.reshape(shape) \
        + beta.reshape(shape)


@register("GroupNorm", num_inputs=3, aliases=("group_norm",))
def group_norm(x, gamma, beta, num_groups=1, eps=1e-5):
    # ref: src/operator/nn/group_norm.cc
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, num_groups, c // num_groups) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return out * gamma.reshape(shape) + beta.reshape(shape)


@register("L2Normalization", num_inputs=1, aliases=("l2_normalization",))
def l2_normalization(x, eps=1e-10, mode="instance"):
    # ref: src/operator/l2_normalization-inl.h
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, x.ndim))
    else:
        raise ValueError(mode)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    return x / norm


@register("LRN", num_inputs=1, aliases=("lrn",))
def lrn(x, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    # cross-channel local response norm, ref: src/operator/nn/lrn.cc
    half = nsize // 2
    sq = jnp.square(x)
    padded = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) * (x.ndim - 2))
    acc = jnp.zeros_like(x)
    for i in range(nsize):
        acc = acc + jax.lax.dynamic_slice_in_dim(padded, i, x.shape[1], axis=1)
    return x / jnp.power(knorm + alpha * acc / nsize, beta)


@register("Dropout", num_inputs=None, aliases=("dropout",))
def dropout(x, key=None, p=0.5, mode="training", axes=(), _training=True,
            cudnn_off=False):
    """ref: src/operator/nn/dropout-inl.h. ``key`` is a jax PRNG key threaded
    by the wrapper (global RNG eagerly; trace key under jit)."""
    if not _training and mode != "always":
        return x
    if p <= 0.0:
        return x
    shape = x.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(x.shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape)
    return jnp.where(mask, x / keep, jnp.zeros((), x.dtype))


@register("UpSampling", aliases=("upsampling",))
def upsampling(*data, scale=1, sample_type="nearest", num_args=1,
               num_filter=0, multi_input_mode="concat", workspace=512):
    # ref: src/operator/nn/upsampling-inl.h (nearest only; bilinear via deconv)
    x = data[0]
    out = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
    if len(data) > 1 and multi_input_mode == "concat":
        outs = [out]
        for d in data[1:]:
            s = x.shape[2] * scale // d.shape[2]
            outs.append(jnp.repeat(jnp.repeat(d, s, axis=2), s, axis=3))
        return jnp.concatenate(outs, axis=1)
    return out


@register("BilinearSampler", num_inputs=2, aliases=("bilinear_sampler",))
def bilinear_sampler(data, grid, cudnn_off=False):
    # ref: src/operator/bilinear_sampler.cc — grid channels (x, y) in [-1, 1]
    n, c, h, w = data.shape

    def one(img, gxy):  # img (c,h,w), gxy (2,ho,wo)
        gx = (gxy[0] + 1) * (w - 1) / 2.0
        gy = (gxy[1] + 1) * (h - 1) / 2.0
        x0 = jnp.floor(gx).astype(jnp.int32)
        y0 = jnp.floor(gy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx1, wy1 = gx - x0, gy - y0
        wx0, wy0 = 1 - wx1, 1 - wy1

        def sample(yi, xi):
            yc = jnp.clip(yi, 0, h - 1)
            xc = jnp.clip(xi, 0, w - 1)
            valid = ((yi >= 0) & (yi < h) & (xi >= 0) & (xi < w))
            return img[:, yc, xc] * valid.astype(img.dtype)  # (c,ho,wo)

        return (sample(y0, x0) * (wy0 * wx0) + sample(y0, x1) * (wy0 * wx1)
                + sample(y1, x0) * (wy1 * wx0) + sample(y1, x1) * (wy1 * wx1))

    return jax.vmap(one)(data, grid)


@register("GridGenerator", num_inputs=1, aliases=("grid_generator",))
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    # ref: src/operator/grid_generator-inl.h
    h, w = target_shape
    if transform_type == "affine":
        n = data.shape[0]
        theta = data.reshape(n, 2, 3)
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()], axis=0)
        grid = jnp.einsum("nij,jk->nik", theta, coords)
        return grid.reshape(n, 2, h, w)
    if transform_type == "warp":
        n, _, hh, ww = data.shape
        ys = jnp.arange(hh, dtype=data.dtype)
        xs = jnp.arange(ww, dtype=data.dtype)
        gx, gy = jnp.meshgrid(xs, ys)
        fx = (data[:, 0] + gx) * 2 / max(ww - 1, 1) - 1
        fy = (data[:, 1] + gy) * 2 / max(hh - 1, 1) - 1
        return jnp.stack([fx, fy], axis=1)
    raise ValueError(transform_type)


# -- fused RNN (ref: src/operator/rnn-inl.h, rnn.cc) -------------------------

_RNN_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}


def rnn_packed_param_size(mode, input_size, state_size, num_layers, ndir):
    """Length of the packed `RNN` parameter vector (ref: rnn-inl.h
    GetRnnParamSize). Single source of truth for the packing arithmetic —
    symbol/infer.py and initializer.FusedRNN derive from this."""
    g = _RNN_GATES[mode]
    h = state_size
    return ndir * g * h * (input_size + h + 2) \
        + (num_layers - 1) * ndir * g * h * (h * ndir + h + 2)


def rnn_packed_input_size(total, mode, state_size, num_layers, ndir):
    """Recover the layer-0 input size from a packed vector's length
    (inverse of rnn_packed_param_size; ref: rnn_cell.py unpack_weights)."""
    g = _RNN_GATES[mode]
    h = state_size
    return total // ndir // g // h - (num_layers - 1) * (h + ndir * h + 2) \
        - h - 2


def _rnn_unpack_params(parameters, mode, input_size, state_size, num_layers,
                       ndir):
    """Split the packed 1-D parameter vector into per-(layer, direction)
    (w_i2h, w_h2h, b_i2h, b_h2h). Packing order matches the reference /
    cuDNN: all weights layer-major (direction inner), then all biases
    (ref: rnn-inl.h GetRnnParamSize)."""
    g = _RNN_GATES[mode]
    h = state_size
    shapes_w, shapes_b = [], []
    for layer in range(num_layers):
        isz = input_size if layer == 0 else h * ndir
        for _ in range(ndir):
            shapes_w.append((g * h, isz))
            shapes_w.append((g * h, h))
            shapes_b.append((g * h,))
            shapes_b.append((g * h,))
    out, off = [], 0
    for shp in shapes_w + shapes_b:
        n = int(_np.prod(shp))
        out.append(parameters[off:off + n].reshape(shp))
        off += n
    nw = len(shapes_w)
    per = []
    for i in range(0, nw, 2):
        per.append((out[i], out[i + 1], out[nw + i], out[nw + i + 1]))
    return per  # index = layer * ndir + direction


def _rnn_cell_step(mode, h_prev, c_prev, i2h, h2h, clip=None):
    """One timestep's gate math given precomputed i2h and h2h projections.
    Gate order matches the reference cells: LSTM [i, f, g, o], GRU
    [r, z, n] with n = tanh(i2h_n + r * h2h_n)
    (ref: gluon/rnn/rnn_cell.py:487 LSTMCell, :606 GRUCell).

    ``clip`` = (min, max, nan) applies cuDNN CUDNN_RNN_CLIP semantics to the
    LSTM cell state at EVERY step (ref: rnn-inl.h lstm_state_clip_*)."""
    hsz = h_prev.shape[-1]
    if mode in ("rnn_relu", "rnn_tanh"):
        pre = i2h + h2h
        h = jax.nn.relu(pre) if mode == "rnn_relu" else jnp.tanh(pre)
        return h, c_prev
    if mode == "gru":
        ir, iz, inn = (i2h[..., :hsz], i2h[..., hsz:2 * hsz],
                       i2h[..., 2 * hsz:])
        hr, hz, hn = (h2h[..., :hsz], h2h[..., hsz:2 * hsz],
                      h2h[..., 2 * hsz:])
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        n = jnp.tanh(inn + r * hn)
        h = (1.0 - z) * n + z * h_prev
        return h, c_prev
    if mode == "lstm":
        pre = i2h + h2h
        i = jax.nn.sigmoid(pre[..., :hsz])
        f = jax.nn.sigmoid(pre[..., hsz:2 * hsz])
        gg = jnp.tanh(pre[..., 2 * hsz:3 * hsz])
        o = jax.nn.sigmoid(pre[..., 3 * hsz:])
        c = f * c_prev + i * gg
        if clip is not None:
            cmin, cmax, cnan = clip
            c = jnp.clip(c, cmin, cmax)
            if cnan:
                c = jnp.where(jnp.isnan(c),
                              jnp.clip(jnp.zeros_like(c), cmin, cmax), c)
        h = o * jnp.tanh(c)
        return h, c
    raise ValueError("unknown RNN mode %r" % (mode,))


def _sequence_reverse(x, lengths):
    """Reverse (T, N, C) within each sample's valid prefix, padding kept in
    place (ref: src/operator/sequence_reverse.cc SequenceReverse)."""
    T = x.shape[0]
    t = jnp.arange(T)[:, None]
    lens = lengths.astype(jnp.int32)[None, :]
    idx = jnp.where(t < lens, lens - 1 - t, t)
    return jnp.take_along_axis(x, idx[..., None], axis=0)


def _rnn_layer_scan(mode, x, h0, c0, w_i2h, w_h2h, b_i2h, b_h2h, reverse,
                    lengths=None, clip=None):
    """Run one direction of one layer over the whole sequence: the i2h
    projection for ALL timesteps is one large (T*N, I)x(I, G*H) matmul on
    the MXU; the lax.scan carries only the (N, H) state and does the
    (N, H)x(H, G*H) h2h matmul per step.

    With ``lengths`` (N,), steps past each sample's valid length freeze the
    recurrent state and emit zeros; the reverse direction reverses within
    the valid prefix (SequenceReverse semantics), so final states are taken
    at each sample's own boundary — matching cuDNN variable-length RNNs."""
    if reverse:
        x = _sequence_reverse(x, lengths) if lengths is not None \
            else jnp.flip(x, axis=0)
    i2h_all = jnp.einsum("tni,gi->tng", x, w_i2h) + b_i2h
    T = x.shape[0]

    def step(carry, xs):
        h_prev, c_prev = carry
        i2h_t, t = xs
        h2h_t = h_prev @ w_h2h.T + b_h2h
        h, c = _rnn_cell_step(mode, h_prev, c_prev, i2h_t, h2h_t, clip=clip)
        if lengths is None:
            return (h, c), h
        valid = (t < lengths.astype(jnp.int32))[:, None]
        h = jnp.where(valid, h, h_prev)
        c = jnp.where(valid, c, c_prev)
        out = jnp.where(valid, h, jnp.zeros((), h.dtype))
        return (h, c), out

    (h_last, c_last), hs = jax.lax.scan(
        step, (h0, c0), (i2h_all, jnp.arange(T)))
    if reverse:
        hs = _sequence_reverse(hs, lengths) if lengths is not None \
            else jnp.flip(hs, axis=0)
    return hs, h_last, c_last


@register("RNN", aliases=("rnn",))
def rnn_fused(data, parameters, state, state_cell=None, sequence_length=None,
              key=None, *, mode="lstm", state_size=None, num_layers=1,
              bidirectional=False, p=0.0, state_outputs=False,
              projection_size=None, lstm_state_clip_min=None,
              lstm_state_clip_max=None, lstm_state_clip_nan=False,
              use_sequence_length=False, _training=True):
    """Fused multi-layer (bi)directional RNN (ref: src/operator/rnn-inl.h,
    the cuDNN-RNN-backed `RNN` op). Layout TNC: data (T, N, I); state
    (L*D, N, H); packed 1-D `parameters`. Between-layer dropout `p` applies
    to inputs of layers > 0 during training (ref: rnn-inl.h p semantics).
    With ``use_sequence_length`` the trailing input is per-sample valid
    lengths (N,), matching the reference's variable-length cuDNN path.

    TPU mapping: per layer+direction, i2h for the whole sequence is one
    MXU matmul; a lax.scan carries the recurrent state (compiles to one
    XLA while loop — no per-step dispatch)."""
    if projection_size:
        raise NotImplementedError("LSTMP projection is not supported")
    if state_size is None:
        raise ValueError("state_size required")
    if use_sequence_length:
        # Positional binding matches the reference op: sequence_length is
        # the input right after the states, which for non-LSTM modes (no
        # state_cell input) arrives in the state_cell slot.
        if mode != "lstm" and sequence_length is None:
            state_cell, sequence_length = None, state_cell
        if sequence_length is None:
            raise ValueError("use_sequence_length=True requires a "
                             "sequence_length input")
    else:
        sequence_length = None
    clip = None
    if mode == "lstm" and lstm_state_clip_min is not None:
        clip = (lstm_state_clip_min, lstm_state_clip_max,
                lstm_state_clip_nan)
    ndir = 2 if bidirectional else 1
    per = _rnn_unpack_params(parameters, mode, data.shape[-1], state_size,
                             num_layers, ndir)
    x = data
    h_lasts, c_lasts = [], []
    for layer in range(num_layers):
        if layer > 0 and p > 0.0 and _training and key is not None:
            key, sub = jax.random.split(key)
            keep = 1.0 - p
            mask = jax.random.bernoulli(sub, keep, x.shape)
            x = jnp.where(mask, x / keep, jnp.zeros((), x.dtype))
        outs = []
        for d in range(ndir):
            idx = layer * ndir + d
            w_i2h, w_h2h, b_i2h, b_h2h = per[idx]
            h0 = state[idx]
            c0 = state_cell[idx] if state_cell is not None \
                else jnp.zeros_like(h0)
            hs, h_last, c_last = _rnn_layer_scan(
                mode, x, h0, c0, w_i2h, w_h2h, b_i2h, b_h2h,
                reverse=(d == 1), lengths=sequence_length, clip=clip)
            outs.append(hs)
            h_lasts.append(h_last)
            c_lasts.append(c_last)
        x = outs[0] if ndir == 1 else jnp.concatenate(outs, axis=-1)
    out_h = jnp.stack(h_lasts, axis=0)
    if mode == "lstm":
        out_c = jnp.stack(c_lasts, axis=0)
        return (x, out_h, out_c) if state_outputs else x
    return (x, out_h) if state_outputs else x
