"""Linear algebra ops — the MXU hot path.

TPU-native re-design of the reference's dot/batch_dot and linalg families
(ref: src/operator/tensor/dot-inl.h, src/operator/tensor/la_op.cc). All
products lower to XLA dot_general which tiles onto the MXU; there is no BLAS
dispatch layer (ref: 3rdparty/mshadow/mshadow/dot_engine-inl.h is replaced by
the compiler).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("dot", num_inputs=2)
def dot(a, b, transpose_a=False, transpose_b=False, precision=None):
    # MXNet dot: contract last axis of a with first axis of b.
    # precision=None defers to the global policy (mxnet_tpu/precision.py);
    # "float32"/"highest" buy reference-parity fp32 at extra MXU passes.
    if transpose_a:
        a = jnp.moveaxis(a, 0, -1) if a.ndim > 1 else a
    if transpose_b:
        b = jnp.moveaxis(b, -1, 0) if b.ndim > 1 else b
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b, precision=precision)
    return jnp.tensordot(a, b, axes=1, precision=precision)


@register("batch_dot", num_inputs=2)
def batch_dot(a, b, transpose_a=False, transpose_b=False, precision=None):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b, precision=precision)


@register("khatri_rao")
def khatri_rao(*mats):
    out = mats[0]
    for m in mats[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(
            out.shape[0] * m.shape[0], *out.shape[1:])
    return out


# -- linalg_* family (ref: src/operator/tensor/la_op.cc) --------------------

@register("linalg_gemm", num_inputs=3)
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2, precision=None):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b, precision=precision) + beta * C


@register("linalg_gemm2", num_inputs=2)
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0,
                 axis=-2, precision=None):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b, precision=precision)


@register("linalg_potrf", num_inputs=1)
def linalg_potrf(A, lower=True):
    L = jnp.linalg.cholesky(A)
    return L if lower else jnp.swapaxes(L, -1, -2)


@register("linalg_potri", num_inputs=1)
def linalg_potri(A, lower=True):
    L = A if lower else jnp.swapaxes(A, -1, -2)
    n = L.shape[-1]
    eye = jnp.broadcast_to(jnp.eye(n, dtype=L.dtype), L.shape)
    Linv = jax.scipy.linalg.solve_triangular(L, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(Linv, -1, -2), Linv)


@register("linalg_trmm", num_inputs=2)
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0,
                precision=None):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * (jnp.matmul(B, a, precision=precision) if rightside
                    else jnp.matmul(a, B, precision=precision))


@register("linalg_trsm", num_inputs=2)
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    if rightside:
        # solve X A = alpha B  ->  A^T X^T = alpha B^T
        sol = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(A, -1, -2), jnp.swapaxes(alpha * B, -1, -2),
            lower=not lower, trans=1 if transpose else 0)
        return jnp.swapaxes(sol, -1, -2)
    return jax.scipy.linalg.solve_triangular(
        A, alpha * B, lower=lower, trans=1 if transpose else 0)


@register("linalg_sumlogdiag", num_inputs=1)
def linalg_sumlogdiag(A):
    d = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(d), axis=-1)


@register("linalg_extractdiag", num_inputs=1)
def linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("linalg_makediag", num_inputs=1)
def linalg_makediag(d, offset=0):
    return jax.vmap(lambda v: jnp.diag(v, k=offset))(d.reshape(-1, d.shape[-1])) \
        .reshape(d.shape[:-1] + (d.shape[-1] + abs(offset),) * 2) \
        if d.ndim > 1 else jnp.diag(d, k=offset)


@register("linalg_syrk", num_inputs=1)
def linalg_syrk(A, transpose=False, alpha=1.0, precision=None):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * jnp.matmul(a, jnp.swapaxes(a, -1, -2),
                              precision=precision)


@register("linalg_gelqf", num_inputs=1)
def linalg_gelqf(A):
    # LQ factorization: A = L Q. Via QR of A^T.
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("linalg_inverse", num_inputs=1)
def linalg_inverse(A):
    return jnp.linalg.inv(A)


@register("linalg_det", num_inputs=1)
def linalg_det(A):
    return jnp.linalg.det(A)


@register("linalg_slogdet", num_inputs=1)
def linalg_slogdet(A):
    sign, logdet = jnp.linalg.slogdet(A)
    return sign, logdet


@register("norm_fro", num_inputs=1)
def norm_fro(A):
    return jnp.sqrt(jnp.sum(jnp.square(A)))
