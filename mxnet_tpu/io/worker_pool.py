"""Multi-worker decode pool — PR 5's restart-or-die contract from one
worker to N (ISSUE 11 tentpole b).

``DecodePool`` pulls items from a source iterator, decodes them on N
worker threads, and yields results **in source order** regardless of
worker count or interleaving: every claimed item carries a sequence
number and lands in its numbered result slot; the consumer only ever
takes the next expected sequence. Worker count is a throughput knob,
never a semantics knob — the property the determinism suite pins.

Failure ladder (each rung counted in ``profiler.metrics()['io']``):

1. **decode raises** → that is a *worker death* (the thread exits; even
   an abrupt ``SystemExit`` — the thread-world SIGKILL — takes this
   path). The claimed item is requeued so no sample is lost, and the
   pool restarts the worker with a fresh thread. Restarts are bounded
   per worker by the ``_retry`` budget (``MXTPU_IO_WORKER_RESTARTS``,
   default ``MXTPU_PS_RETRY_MAX``) counted over *consecutive* deaths —
   a success resets the meter, so a transient 15% chaos rate recovers
   while a persistently-broken worker cannot death-loop.
2. **budget exhausted** → the worker is *retired*: the pool degrades
   to fewer workers (``io.pool_workers`` gauge drops) and keeps
   serving — graceful degradation before death.
3. **an item keeps failing** (``MXTPU_IO_ITEM_RETRIES`` attempts
   across any workers) → the item is poison, not the workers: its
   slot carries the exception, which the consumer sees EXACTLY once at
   the item's ordered position; afterwards the pool reads exhausted
   (``StopIteration``) until ``reset()`` — the single-worker
   restart-or-die surface, scaled to N.
4. **all workers retired** → pool-level death: whatever completed in
   order is still delivered, then the same raise-once surface.

Observability: per-worker deaths/restarts in ``metrics()['io']``, a
span per decode in a **per-worker trace lane** (``io.w<k>``, allocated
via ``profiler.ensure_lane``), and a live per-worker state blob in the
flight recorder's dump context (``io_workers:<name>``) — a starved-step
watchdog dump therefore names WHICH worker was wedged on WHAT sequence
number at the instant of the stall.
"""
from __future__ import annotations

import os
import threading
import time as _time

from .. import _retry
from .. import profiler as _profiler
from .._debug import faultpoint as _faultpoint
from .._debug import flightrec as _flightrec
from .._debug import goodput as _goodput
from .._debug import locktrace as _locktrace
from . import _stats
from ..base import getenv as _getenv

__all__ = ["DecodePool"]

# distinguishes "no slot delivered" from a legitimate None item in the
# consumer's post-lock telemetry hand-off
_NO_RESULT = object()


def _env_int(name, default):
    try:
        return int(_getenv(name, "") or default)
    except ValueError:
        return default


class DecodePool:
    """Order-preserving N-worker decode over ``source``.

    Parameters
    ----------
    source : iterable (restartable via ``reset()`` for pool resets)
    decode_fn : callable(item) -> result, runs on worker threads
    workers : int, default ``MXTPU_IO_DECODE_WORKERS`` (2)
    depth : int, default ``2 * workers``
        Max undelivered sequence numbers in flight (backpressure).
    restarts_per_worker : int, default ``MXTPU_IO_WORKER_RESTARTS``
        (falls back to the ``_retry`` budget, ``MXTPU_PS_RETRY_MAX``).
        Consecutive-death budget per worker before retirement.
    item_retries : int, default ``MXTPU_IO_ITEM_RETRIES`` (4)
        Decode attempts per item before it is declared poison.
    name : str, labels the flight-recorder context blob.
    """

    def __init__(self, source, decode_fn, workers=None, depth=None,
                 restarts_per_worker=None, item_retries=None,
                 name="decode"):
        self._source = source
        self._decode = decode_fn
        self._nworkers = int(workers) if workers is not None \
            else _env_int("MXTPU_IO_DECODE_WORKERS", 2)
        if self._nworkers < 1:
            raise ValueError("DecodePool needs >= 1 worker")
        self._depth = int(depth) if depth is not None \
            else 2 * self._nworkers
        if restarts_per_worker is None:
            restarts_per_worker = _env_int(
                "MXTPU_IO_WORKER_RESTARTS",
                _retry.RetryPolicy().max_retries)
        self._budget = int(restarts_per_worker)
        self._item_retries = int(item_retries) if item_retries \
            is not None else _env_int("MXTPU_IO_ITEM_RETRIES", 4)
        self._name = name
        self._cond = _locktrace.named_condition("io.pool.slots")
        self._start()

    # -- lifecycle ----------------------------------------------------------
    def _start(self):
        with self._cond:
            self._it = iter(self._source)
            self._claim = 0        # next sequence number to hand out
            self._expect = 0       # next sequence the consumer takes
            self._slots = {}       # seq -> ("ok", result) | ("err", exc)
            self._retryq = []      # [(seq, item, attempts)] redo first
            self._decoding = {}    # seq -> worker id, claimed not filled
            self._exhausted = False
            self._last = None      # exclusive end seq once exhausted
            self._failed = None    # pool-terminal exception
            self._dead = False     # terminal raised once; now exhausted
            self._stopping = False
            self._deaths = {}      # worker -> total deaths
            self._consec = {}      # worker -> consecutive deaths
            self._live = set(range(self._nworkers))
            self._threads = []
            # fixed-key per-worker blobs, mutated in place: the flight
            # recorder serializes this at dump time, so a watchdog dump
            # of a starved step names the wedged worker and its seq
            self._ctx = {str(i): {"state": "idle", "seq": -1,
                                  "deaths": 0, "live": True}
                         for i in range(self._nworkers)}
        _flightrec.set_context("io_workers:%s" % self._name, self._ctx)
        _stats.set_gauge("pool_workers", self._nworkers)
        for i in range(self._nworkers):
            self._spawn(i)

    def _spawn(self, wid):
        _profiler.ensure_lane("io.w%d" % wid)
        t = threading.Thread(
            target=self._worker, args=(wid,), daemon=True,
            name="decode-pool-%s-w%d" % (self._name, wid))
        with self._cond:
            if self._stopping:
                return
            self._threads.append(t)
        t.start()

    def close(self):
        """Stop and JOIN every worker without restarting — the
        abandon-mid-stream path (a consumer breaking out of an epoch
        early must not leave N threads polling the condition for the
        life of the process). Idempotent; the pool reads exhausted
        afterwards until ``reset()`` rebuilds it."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        for t in list(self._threads):
            t.join()

    def __del__(self):
        try:
            self.close()
        except Exception:  # mxlint: disable=MX009 (interpreter teardown — threading may already be gone)
            pass

    def reset(self):
        """Join every worker, restart the source, and rebuild the pool
        with fresh budgets — recovery after a poison item or pool
        death, mirroring the single-worker iterators' ``reset()``.
        Requires a restartable source."""
        self.close()
        if hasattr(self._source, "reset"):
            self._source.reset()
        _stats.bump("pool_resets")
        self._start()

    # -- worker side --------------------------------------------------------
    def _claim_one(self, wid):
        """Take the next work unit under the condition: a requeued
        item first (its slot is already owed), else a fresh pull from
        the source (serialized here — this lock IS the ordering
        point). Returns (seq, item, attempts) or None to exit."""
        with self._cond:
            while True:
                if self._stopping or wid not in self._live:
                    return None
                if self._retryq:
                    claim = self._retryq.pop(0)
                    break
                if self._exhausted:
                    if not self._decoding:
                        # no future work can appear: retire quietly
                        return None
                    self._cond.wait(0.05)
                    continue
                if self._claim - self._expect < self._depth:
                    try:
                        item = next(self._it)
                    except StopIteration:
                        self._exhausted = True
                        self._last = self._claim
                        self._cond.notify_all()
                        continue
                    except Exception as e:  # mxlint: disable=MX009 (not swallowed: the error lands in an ordered result slot and re-raises at the consumer's __next__)
                        # a broken SOURCE is not a decode failure: it
                        # surfaces once, ordered, at the current seq
                        self._slots[self._claim] = ("err", e)
                        self._claim += 1
                        self._exhausted = True
                        self._last = self._claim
                        self._cond.notify_all()
                        continue
                    claim = (self._claim, item, 0)
                    self._claim += 1
                    break
                self._cond.wait(0.05)
            seq = claim[0]
            self._decoding[seq] = wid
            ctx = self._ctx[str(wid)]
            ctx["state"], ctx["seq"] = "decoding", seq
            return claim

    def _on_death(self, wid, seq, item, attempts, exc):
        """The restart-or-die ladder: requeue (or poison) the item,
        then restart or retire the worker."""
        with self._cond:
            self._deaths[wid] = self._deaths.get(wid, 0) + 1
            self._consec[wid] = self._consec.get(wid, 0) + 1
            self._decoding.pop(seq, None)
            ctx = self._ctx[str(wid)]
            ctx["deaths"] = self._deaths[wid]
            if attempts + 1 >= self._item_retries:
                # this was the item's item_retries-th attempt (so
                # MXTPU_IO_ITEM_RETRIES=1 means one attempt, no retry,
                # matching docs/ENV_VARS.md): poison — ITS slot
                # carries the error so the consumer sees it exactly
                # once, in order
                self._slots[seq] = ("err", exc)
            else:
                self._retryq.append((seq, item, attempts + 1))
            respawn = self._consec[wid] <= self._budget
            if not respawn:
                self._live.discard(wid)
                ctx["state"], ctx["live"] = "retired", False
                if not self._live and self._failed is None:
                    self._failed = RuntimeError(
                        "DecodePool %r: all %d workers retired "
                        "(consecutive-death budget %d each); last "
                        "error: %r" % (self._name, self._nworkers,
                                       self._budget, exc))
            self._cond.notify_all()
            nlive = len(self._live)
        _stats.bump("worker_deaths.%d" % wid)
        if respawn:
            _stats.bump("worker_restarts.%d" % wid)
            self._spawn(wid)
        else:
            _stats.bump("workers_retired")
            _stats.set_gauge("pool_workers", nlive)

    def _worker(self, wid):
        while True:
            claim = self._claim_one(wid)
            if claim is None:
                return
            seq, item, attempts = claim
            t0 = _time.perf_counter() if _profiler._LIVE else None
            try:
                if _faultpoint.ACTIVE:
                    _faultpoint.check("io.worker.decode")
                result = self._decode(item)
            except BaseException as e:  # mxlint: disable=MX009 (death is counted: _on_death -> _stats.bump -> profiler.account; abrupt SystemExit = the thread-world SIGKILL must take the same requeue path)
                self._on_death(wid, seq, item, attempts, e)
                return  # this incarnation is dead; _spawn made the next
            with self._cond:
                self._slots[seq] = ("ok", result)
                self._decoding.pop(seq, None)
                self._consec[wid] = 0
                ctx = self._ctx[str(wid)]
                ctx["state"], ctx["seq"] = "idle", seq
                self._cond.notify_all()
            if t0 is not None:
                _profiler.record_op(
                    "io.worker.decode",
                    (_time.perf_counter() - t0) * 1e6,
                    category="io", lane="io.w%d" % wid,
                    args={"seq": seq, "worker": wid})

    # -- consumer side ------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        # consumer-stall timing (the input-wait half of the goodput
        # ledger + the shared io.prefetch_wait histogram): measured
        # over the whole ordered-slot wait, recorded AFTER the pool
        # condition is released so the telemetry locks never nest
        # under it. goodput.OPEN joins the guard so input_wait
        # attribution survives a flightrec-off deployment
        t0 = _time.perf_counter() \
            if _profiler._LIVE or _goodput.OPEN else None
        err = None
        result = _NO_RESULT
        with self._cond:
            if self._dead:
                # terminal error already surfaced once: the pool reads
                # exhausted until reset() (restart-or-die, N-worker)
                raise StopIteration
            while err is None:
                if self._expect in self._slots:
                    kind, val = self._slots.pop(self._expect)
                    self._expect += 1
                    self._cond.notify_all()
                    if kind == "err":
                        self._dead = True
                        err = val
                        break
                    result = val
                    break
                if self._exhausted and self._last is not None \
                        and self._expect >= self._last:
                    # everything owed was delivered — a pool that
                    # degraded to zero AFTER finishing still ends
                    # cleanly
                    raise StopIteration
                if self._failed is not None:
                    self._dead = True
                    err = self._failed
                    break
                self._cond.wait(0.05)
        if result is not _NO_RESULT:
            if t0 is not None:
                wait_us = (_time.perf_counter() - t0) * 1e6
                _profiler.record_latency("io.prefetch_wait", wait_us)
                if _goodput.OPEN:
                    _goodput.note_input_wait(wait_us)
            return result
        _stats.bump("pool_failures")
        raise err

    next = __next__

    # -- introspection -------------------------------------------------------
    @property
    def live_workers(self):
        with self._cond:
            return sorted(self._live)

    def deaths(self):
        with self._cond:
            return dict(self._deaths)
