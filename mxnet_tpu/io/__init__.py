"""Data IO: iterators feeding the training loop.

TPU-native redesign of the reference's two-layer IO stack (ref: src/io/
C++ iterators + python/mxnet/io/io.py wrappers): here there is one Python
layer; heavy decode work runs in a thread pool (the C++ ThreadedParser
analog, ref: src/io/iter_image_recordio_2.cc:79) and batches are prefetched
on a background thread (ref: src/io/iter_prefetcher.h) so the accelerator
never waits on the host. Host->HBM transfer is the jax device_put double
buffer in PrefetchingIter.
"""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, CSVIter,
                 LibSVMIter, ResizeIter, PrefetchingIter, MNISTIter)
from .image_iter import ImageRecordIter
from .prefetch import DevicePrefetchIter, DevicePrefetcher

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "LibSVMIter", "ResizeIter", "PrefetchingIter", "MNISTIter",
           "ImageRecordIter", "DevicePrefetchIter", "DevicePrefetcher"]
