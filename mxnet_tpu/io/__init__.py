"""Data IO: iterators feeding the training loop.

TPU-native redesign of the reference's two-layer IO stack (ref: src/io/
C++ iterators + python/mxnet/io/io.py wrappers): here there is one Python
layer; heavy decode work runs in a thread pool (the C++ ThreadedParser
analog, ref: src/io/iter_image_recordio_2.cc:79) and batches are prefetched
on a background thread (ref: src/io/iter_prefetcher.h) so the accelerator
never waits on the host. Host->HBM transfer is the jax device_put double
buffer in PrefetchingIter.

The scale-out half (ISSUE 11, docs/DATA.md) is the fault-tolerant
sharded streaming service: deterministic global shard assignment with a
committed sample cursor (``shard_service``), an N-worker restart-or-die
decode pool (``worker_pool``), and a range-read RecordIO reader with
retry + corrupt-record budgets (``range_reader``). Accounting for the
whole plane surfaces as ``profiler.metrics()['io']`` (``_stats``).
"""
from . import _stats  # registers the metrics()['io'] provider
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, CSVIter,
                 LibSVMIter, ResizeIter, PrefetchingIter, MNISTIter)
from .image_iter import ImageRecordIter
from .prefetch import DevicePrefetchIter, DevicePrefetcher
from .range_reader import (RecordIORangeReader, CorruptRecordError,
                           build_crc_sidecar)
from .worker_pool import DecodePool
from .shard_service import (ShardService, epoch_order, assign_shards,
                            reassign_shards, unconsumed_shards,
                            batch_slices)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "LibSVMIter", "ResizeIter", "PrefetchingIter", "MNISTIter",
           "ImageRecordIter", "DevicePrefetchIter", "DevicePrefetcher",
           "RecordIORangeReader", "CorruptRecordError",
           "build_crc_sidecar", "DecodePool", "ShardService",
           "epoch_order", "assign_shards", "reassign_shards",
           "unconsumed_shards", "batch_slices"]
