"""Data-plane accounting: the ``profiler.metrics()['io']`` provider.

One module owns every input-pipeline counter and gauge (worker deaths
and restarts per worker, corrupt records skipped, shard/cursor
progress, the live prefetch queue depth) so the flight recorder, the
``/metrics`` exporter, and the ``BENCH_MODEL=input_pipeline`` gate all
read the same numbers. Counters accumulate unconditionally — the
``profiler.account`` contract — because the restart diagnostic must be
trustworthy in production, not just while a profile run is active.

Gauges are *live values*, not accumulators: ``prefetch_queue_depth``
is re-seeded from the actual queue size whenever a prefetch worker
restarts, so a death with items still queued can never leave the gauge
stale (or, for a delta-tracked implementation, negative) — the ISSUE
11 satellite regression ``tests/test_prefetch.py`` pins.

Like the PR 2 ``io.prefetch_queue_depth`` trace-counter series it
mirrors, the gauge is ONE series per process: every prefetcher
publishes to it, so its value is the most recent sample across them —
the consumer-stall story for "the" training feed. A process running
several concurrent pipelines should read the per-pool
``io_workers:<name>`` flight-recorder context (and per-worker lanes)
for disambiguation.
"""
from __future__ import annotations

from .. import profiler as _profiler
from .._debug import locktrace as _locktrace

__all__ = ["bump", "set_gauge", "get", "snapshot", "reset"]

_lock = _locktrace.named_lock("io.stats")
_counters = {}  # cumulative (worker_deaths.<i>, corrupt_records, ...)
_gauges = {}    # live values (prefetch_queue_depth, pool_workers, ...)


def bump(name, delta=1, args=None):
    """Accumulate a cumulative io counter (unconditionally) and mirror
    it into the profiler's counter ledger so the trace timeline shows
    it when a run is active."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + delta
    _profiler.account("io.%s" % name, delta, lane="io")


def set_gauge(name, value):
    """Publish a live gauge value (replaces, never accumulates)."""
    with _lock:
        _gauges[name] = value


def get(name, default=0):
    with _lock:
        if name in _counters:
            return _counters[name]
        return _gauges.get(name, default)


def snapshot():
    """JSON-safe merged view — the ``io`` section of
    ``profiler.metrics()``."""
    with _lock:
        out = dict(_counters)
        out.update(_gauges)
        return out


def reset():
    with _lock:
        _counters.clear()
        _gauges.clear()


_profiler.register_stats_provider("io", snapshot, reset)
