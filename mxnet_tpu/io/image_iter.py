"""ImageRecordIter: threaded JPEG-decode pipeline over RecordIO.

TPU-native redesign of the reference's v2 threaded image pipeline
(ref: src/io/iter_image_recordio_2.cc:79 ThreadedParser::ParseChunk — OMP
decode threads feeding dmlc::ThreadedIter double buffers). Here a
ThreadPoolExecutor decodes/augments records concurrently (cv2 releases the
GIL) and PrefetchingIter overlaps batch assembly with device compute.
"""
from __future__ import annotations

import concurrent.futures as _fut
import random as _pyrandom

import numpy as np

from .io import DataIter, DataBatch, DataDesc
from ..ndarray import array as nd_array
from ..recordio import MXRecordIO, MXIndexedRecordIO, unpack

__all__ = ["ImageRecordIter"]


def _decode_and_augment(raw, data_shape, rand_crop, rand_mirror, resize,
                        mean, std, rng_seed):
    import cv2
    header, img_bytes = unpack(raw)
    label = header.label
    img = cv2.imdecode(np.frombuffer(img_bytes, np.uint8), cv2.IMREAD_COLOR)
    if img is None:
        raise IOError("failed to decode image record")
    rng = _pyrandom.Random(rng_seed)
    if resize:
        h, w = img.shape[:2]
        scale = resize / min(h, w)
        img = cv2.resize(img, (int(w * scale + 0.5), int(h * scale + 0.5)))
    ch, cw = data_shape[1], data_shape[2]
    h, w = img.shape[:2]
    if h < ch or w < cw:
        img = cv2.resize(img, (max(w, cw), max(h, ch)))
        h, w = img.shape[:2]
    if rand_crop:
        y0 = rng.randint(0, h - ch) if h > ch else 0
        x0 = rng.randint(0, w - cw) if w > cw else 0
    else:
        y0, x0 = (h - ch) // 2, (w - cw) // 2
    img = img[y0:y0 + ch, x0:x0 + cw]
    if rand_mirror and rng.random() < 0.5:
        img = img[:, ::-1]
    img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB).astype(np.float32)
    if mean is not None:
        img -= mean
    if std is not None:
        img /= std
    return img.transpose(2, 0, 1), np.float32(
        label if np.isscalar(label) or getattr(label, "ndim", 0) == 0
        else label[0])


class ImageRecordIter(DataIter):
    """ref: ImageRecordIter params (src/io/image_iter_common.h
    ImageRecParserParam/ImageRecordParam + normalize/augment params)."""

    def __init__(self, path_imgrec, data_shape, batch_size, path_imgidx=None,
                 shuffle=False, rand_crop=False, rand_mirror=False, resize=0,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, preprocess_threads=4, label_width=1, seed=0,
                 round_batch=True, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        assert len(self.data_shape) == 3, "data_shape must be (C, H, W)"
        self._shuffle = shuffle
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._resize = resize
        mean = np.array([mean_r, mean_g, mean_b], np.float32)
        std = np.array([std_r, std_g, std_b], np.float32)
        self._mean = mean if mean.any() else None
        self._std = std if (std != 1.0).any() else None
        self._seed = seed
        self._epoch = 0
        self._round_batch = round_batch
        self._pool = _fut.ThreadPoolExecutor(max_workers=preprocess_threads)

        if path_imgidx:
            self._rec = MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
            self._keys = list(self._rec.keys)
        else:
            self._rec = MXRecordIO(path_imgrec, "r")
            # scan once to collect record offsets for shuffling
            self._keys = None
            self._offsets = []
            while True:
                pos = self._rec.tell()
                if self._rec.read() is None:
                    break
                self._offsets.append(pos)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self._epoch += 1
        order = list(self._keys if self._keys is not None
                     else range(len(self._offsets)))
        if self._shuffle:
            _pyrandom.Random(self._seed + self._epoch).shuffle(order)
        self._order = order
        self._cursor = 0

    def _read_raw(self, key):
        if self._keys is not None:
            return self._rec.read_idx(key)
        self._rec.seek_pos(self._offsets[key])
        return self._rec.read()

    def next(self):
        n = len(self._order)
        if self._cursor >= n:
            raise StopIteration
        end = self._cursor + self.batch_size
        idxs = [self._order[i % n] for i in range(self._cursor, end)]
        pad = max(0, end - n)
        if pad and not self._round_batch:
            raise StopIteration
        self._cursor = end
        raws = [self._read_raw(k) for k in idxs]  # sequential file reads
        futs = [self._pool.submit(
            _decode_and_augment, raw, self.data_shape, self._rand_crop,
            self._rand_mirror, self._resize, self._mean, self._std,
            self._seed + self._epoch * 1000003 + i)
            for i, raw in enumerate(raws)]       # parallel decode/augment
        imgs, labels = zip(*[f.result() for f in futs])
        data = nd_array(np.stack(imgs))
        label = nd_array(np.asarray(labels, np.float32))
        return DataBatch(data=[data], label=[label], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
