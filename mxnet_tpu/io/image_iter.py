"""ImageRecordIter: threaded JPEG-decode pipeline over RecordIO.

TPU-native redesign of the reference's v2 threaded image pipeline
(ref: src/io/iter_image_recordio_2.cc:79 ThreadedParser::ParseChunk — OMP
decode threads feeding dmlc::ThreadedIter double buffers). Two design
rules keep the Python pipeline fast enough to feed a TPU chip:

1. Workers touch ONLY GIL-releasing C code: cv2 decode/resize/crop/flip
   on uint8. No per-image numpy float math (numpy ufuncs hold the GIL,
   which is what caps a naive thread pool at a few hundred img/s).
2. Float conversion + mean/std + NCHW transpose happen ONCE per batch
   as vectorized numpy ops, and batches are assembled ahead of the
   consumer by a prefetch thread (the dmlc::ThreadedIter double-buffer
   analog).

Measured (synthetic 256x256 JPEG .rec, 224x224 rand-crop+mirror train
transform, one host): 430 img/s before this layout -> see
benchmark/input_pipeline.py for the current number.
"""
from __future__ import annotations

import concurrent.futures as _fut
import queue as _queue
import random as _pyrandom
import threading

import numpy as np

from .io import DataIter, DataBatch, DataDesc
from .._debug import locktrace as _locktrace
from ..context import cpu as _cpu
from ..ndarray import NDArray
from ..recordio import MXRecordIO, MXIndexedRecordIO, unpack

__all__ = ["ImageRecordIter"]


def _decode_and_augment(raw, data_shape, rand_crop, rand_mirror, resize,
                        rng_seed):
    """Record bytes -> (uint8 HWC RGB image, label). cv2 ops release the
    GIL; everything else here is O(1) Python. Pre-decoded raw-pixel
    records (recordio.pack_raw_img) skip cv2.imdecode entirely — the
    fast path for hosts whose cores cannot keep up with JPEG decode."""
    import cv2
    from ..recordio import decode_raw_img
    header, img_bytes = unpack(raw)
    label = header.label
    img = decode_raw_img(img_bytes)
    if img is None:
        img = cv2.imdecode(np.frombuffer(img_bytes, np.uint8),
                           cv2.IMREAD_COLOR)
    if img is None:
        raise IOError("failed to decode image record")
    rng = _pyrandom.Random(rng_seed)
    if resize:
        h, w = img.shape[:2]
        scale = resize / min(h, w)
        img = cv2.resize(img, (int(w * scale + 0.5), int(h * scale + 0.5)))
    ch, cw = data_shape[1], data_shape[2]
    h, w = img.shape[:2]
    if h < ch or w < cw:
        img = cv2.resize(img, (max(w, cw), max(h, ch)))
        h, w = img.shape[:2]
    if rand_crop:
        y0 = rng.randint(0, h - ch) if h > ch else 0
        x0 = rng.randint(0, w - cw) if w > cw else 0
    else:
        y0, x0 = (h - ch) // 2, (w - cw) // 2
    img = img[y0:y0 + ch, x0:x0 + cw]
    if rand_mirror and rng.random() < 0.5:
        img = cv2.flip(img, 1)
    img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)  # uint8 HWC
    return img, np.float32(
        label if np.isscalar(label) or getattr(label, "ndim", 0) == 0
        else label[0])


class ImageRecordIter(DataIter):
    """ref: ImageRecordIter params (src/io/image_iter_common.h
    ImageRecParserParam/ImageRecordParam + normalize/augment params).

    `prefetch_buffer` batches are assembled ahead by a background
    thread (ref: iter_prefetcher.h); `dtype="uint8"` skips host-side
    normalization entirely (do it on-device) and shrinks host->HBM
    transfers 4x — the TPU-idiomatic feed."""

    def __init__(self, path_imgrec, data_shape, batch_size, path_imgidx=None,
                 shuffle=False, rand_crop=False, rand_mirror=False, resize=0,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, preprocess_threads=4, label_width=1, seed=0,
                 round_batch=True, prefetch_buffer=2, dtype="float32",
                 **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        assert len(self.data_shape) == 3, "data_shape must be (C, H, W)"
        self._shuffle = shuffle
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._resize = resize
        mean = np.array([mean_r, mean_g, mean_b], np.float32)
        std = np.array([std_r, std_g, std_b], np.float32)
        self._mean = mean if mean.any() else None
        self._std = std if (std != 1.0).any() else None
        self._dtype = np.dtype(dtype)
        self._seed = seed
        self._epoch = 0
        self._round_batch = round_batch
        self._pool = _fut.ThreadPoolExecutor(max_workers=preprocess_threads)
        self._nprefetch = max(0, int(prefetch_buffer))

        if path_imgidx:
            self._rec = MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
            self._keys = list(self._rec.keys)
        else:
            self._rec = MXRecordIO(path_imgrec, "r")
            # scan once to collect record offsets for shuffling
            self._keys = None
            self._offsets = []
            while True:
                pos = self._rec.tell()
                if self._rec.read() is None:
                    break
                self._offsets.append(pos)
        self._prefetcher = None
        self._read_lock = _locktrace.named_lock("io.image_read")
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape,
                         dtype=self._dtype)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        # stop (and JOIN) the old producer FIRST — it must not observe
        # the new epoch's cursor/order and steal its first batch
        if self._prefetcher is not None:
            self._prefetcher.stop()
            self._prefetcher = None
        self._epoch += 1
        order = list(self._keys if self._keys is not None
                     else range(len(self._offsets)))
        if self._shuffle:
            _pyrandom.Random(self._seed + self._epoch).shuffle(order)
        self._order = order
        self._cursor = 0
        self._prefetcher = _Prefetcher(self, self._nprefetch) \
            if self._nprefetch > 0 else None

    def _read_raw(self, key):
        # the record file handle is shared between the consumer and the
        # prefetch thread; seek+read must be atomic
        with self._read_lock:
            if self._keys is not None:
                return self._rec.read_idx(key)
            self._rec.seek_pos(self._offsets[key])
            return self._rec.read()

    def _assemble_next(self):
        """Produce the next batch synchronously (called by the prefetch
        thread, or directly when prefetch is disabled)."""
        n = len(self._order)
        if self._cursor >= n:
            raise StopIteration
        end = self._cursor + self.batch_size
        idxs = [self._order[i % n] for i in range(self._cursor, end)]
        pad = max(0, end - n)
        if pad and not self._round_batch:
            raise StopIteration
        start = self._cursor
        self._cursor = end
        raws = [self._read_raw(k) for k in idxs]  # sequential file reads
        futs = [self._pool.submit(
            _decode_and_augment, raw, self.data_shape, self._rand_crop,
            self._rand_mirror, self._resize,
            # seed varies per (epoch, global sample index) — per-slot
            # seeding would repeat the same crop/mirror stream every batch
            self._seed + self._epoch * 1000003 + start + i)
            for i, raw in enumerate(raws)]       # parallel, GIL-free decode
        imgs, labels = zip(*[f.result() for f in futs])
        batch_hwc = np.stack(imgs)               # [N, H, W, C] uint8
        if self._dtype == np.uint8:
            data = np.ascontiguousarray(batch_hwc.transpose(0, 3, 1, 2))
        else:
            # ONE vectorized normalize pass per batch (not per image —
            # numpy holds the GIL, so per-image math serializes workers)
            x = batch_hwc.astype(self._dtype)
            if self._mean is not None:
                x -= self._mean.astype(self._dtype)
            if self._std is not None:
                x /= self._std.astype(self._dtype)
            data = np.ascontiguousarray(x.transpose(0, 3, 1, 2))
        # batches live on the HOST as plain numpy (reference iterators
        # yield CPU NDArrays; the consumer moves them to the
        # accelerator). NDArray(np, ctx=cpu) keeps them off the device:
        # a jax placement here would round-trip every batch over the
        # TPU interconnect before training even starts (and under the
        # axon runtime there is no jax CPU backend to target at all)
        data = NDArray(data, ctx=_cpu())
        label = NDArray(np.asarray(labels, np.float32), ctx=_cpu())
        return DataBatch(data=[data], label=[label], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def next(self):
        if self._prefetcher is not None:
            return self._prefetcher.next()
        return self._assemble_next()


class _Prefetcher:
    """Background batch assembly (ref: src/io/iter_prefetcher.h — the
    consumer overlaps device compute with host decode)."""

    def __init__(self, it, depth):
        self._q = _queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._terminal = None  # True after StopIteration, or the Exception

        def run():
            while not self._stop.is_set():
                try:
                    item = it._assemble_next()
                except StopIteration:
                    item = None
                except Exception as e:  # mxlint: disable=MX009 (forwarded to the consumer's next() and counted via _stats.bump -> profiler.account)
                    from . import _stats
                    # counted with profiling off too: _stats.bump feeds
                    # both metrics()['io'] and the unconditional
                    # profiler.account ledger
                    _stats.bump("prefetch_worker_deaths")
                    item = e
                # bounded put that keeps observing the stop flag, so
                # stop() never deadlocks against a full queue
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except _queue.Full:
                        continue
                if item is None or isinstance(item, Exception):
                    return

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def next(self):
        if self._terminal is not None:
            # producer already finished — keep re-raising (matching the
            # non-prefetch path) instead of blocking on a dead queue
            if isinstance(self._terminal, Exception):
                raise self._terminal
            raise StopIteration
        item = self._q.get()
        if item is None:
            self._terminal = True
            raise StopIteration
        if isinstance(item, Exception):
            self._terminal = item
            raise item
        return item

    def stop(self):
        """Stop the producer and JOIN it — a reset() must not start a
        new producer while the old one still holds the record reader."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except _queue.Empty:
            pass
        self._thread.join(timeout=10)
