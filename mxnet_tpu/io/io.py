"""Core data iterators (ref: python/mxnet/io/io.py)."""
from __future__ import annotations

import collections
import threading
import time as _time
import queue as _queue

import numpy as np

from ..ndarray import NDArray, array as nd_array
from .. import ndarray as nd
from .. import profiler as _profiler
from .._debug import goodput as _goodput
from . import _stats

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "LibSVMIter", "ResizeIter", "PrefetchingIter", "MNISTIter"]


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape"])):
    """Name/shape/type descriptor (ref: io.py DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One mini-batch (ref: io.py DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        shapes = [d.shape for d in self.data] if self.data else []
        lshapes = [l.shape for l in self.label] if self.label else []
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, shapes, lshapes)


class DataIter:
    """Iterator base (ref: io.py DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize to list of (name, numpy) (ref: io/utils.py _init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if len(data) <= 1:
            data = collections.OrderedDict(
                [(default_name, d) for d in data])
        else:
            data = collections.OrderedDict(
                [("_%d_%s" % (i, default_name), d)
                 for i, d in enumerate(data)])
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    out = collections.OrderedDict()
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out[k] = np.asarray(v)
    return list(out.items())


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays with shuffle/pad/discard batch handling
    (ref: io.py:491 NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.num_data = self.idx.shape[0]
        self.cursor = -batch_size
        self._cache_data = None
        self._cache_label = None
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and \
                0 < self.cursor < self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) \
                % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _slice(self, arrays):
        start = self.cursor
        end = min(start + self.batch_size, self.num_data)
        out = []
        for _, v in arrays:
            part = v[self.idx[start:end]]
            if end - start < self.batch_size:
                if self.last_batch_handle == "discard":
                    return None
                # pad by wrapping from the start
                padn = self.batch_size - (end - start)
                part = np.concatenate([part, v[self.idx[:padn]]], axis=0)
            out.append(nd_array(part))
        return out

    def next(self):
        if not self.iter_next():
            raise StopIteration
        data = self._slice(self.data)
        if data is None:  # discard
            raise StopIteration
        label = self._slice(self.label) if self.label else []
        return DataBatch(data=data, label=label, pad=self.getpad(),
                         index=None, provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class CSVIter(DataIter):
    """CSV reader (ref: src/io/iter_csv.cc CSVIter). Loads host-side with
    numpy, slices batches; shapes given by data_shape/label_shape."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32,
                               ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
        else:
            label = np.zeros((data.shape[0],) + tuple(label_shape),
                             np.float32)
        self._iter = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="pad" if round_batch else "discard")

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def reset(self):
        self._iter.reset()

    def next(self):
        return self._iter.next()


class LibSVMIter(DataIter):
    """LibSVM sparse text format -> dense batches (ref: src/io/iter_libsvm.cc;
    sparse storage is emulated densely on TPU, SURVEY §7 hard part c)."""

    def __init__(self, data_libsvm, data_shape, batch_size=1,
                 label_libsvm=None, label_shape=None, **kwargs):
        super().__init__(batch_size)
        feat_dim = int(np.prod(data_shape))
        rows, labels = [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                row = np.zeros(feat_dim, np.float32)
                for kv in parts[1:]:
                    k, v = kv.split(":")
                    row[int(k)] = float(v)
                rows.append(row)
        data = np.stack(rows).reshape((-1,) + tuple(data_shape))
        label = np.asarray(labels, np.float32)
        self._iter = NDArrayIter(data, label, batch_size,
                                 last_batch_handle="pad")

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def reset(self):
        self._iter.reset()

    def next(self):
        return self._iter.next()


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (ref: io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration


class PrefetchingIter(DataIter):
    """Background-thread prefetch (ref: io.py:347 PrefetchingIter; C++
    analog src/io/iter_prefetcher.h). Overlaps host batch prep with device
    compute — the double-buffer the reference implements with
    dmlc::ThreadedIter."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        assert len(iters) == 1, "composite prefetch not needed on TPU"
        self.iter = iters[0]
        self._depth = prefetch_depth
        self._queue = _queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self._epoch = 0  # generation tag: stale pre-reset batches discarded
        self._start()

    def _start(self):
        epoch = self._epoch

        def worker():
            while not self._stop.is_set():
                try:
                    batch = self.iter.next()
                except StopIteration:
                    self._queue.put((epoch, None))
                    return
                self._queue.put((epoch, batch))
        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label

    def reset(self):
        # drain AND JOIN the old worker before anything else touches
        # the underlying iterator (ISSUE 11 satellite): a worker merely
        # observed as not-alive could in principle still be between its
        # last put() and thread exit — after join() it provably cannot
        # place a stale batch into the queue the fresh epoch reads, and
        # it cannot race self.iter.reset() on the shared source
        self._stop.set()
        # drain so a worker blocked in put() can finish and observe _stop
        while self._thread is not None and self._thread.is_alive():
            try:
                self._queue.get(timeout=0.05)
            except _queue.Empty:
                pass
        if self._thread is not None:
            self._thread.join()
        while not self._queue.empty():
            self._queue.get_nowait()
        # gauge re-seed from the LIVE (drained) queue: pre-reset samples
        # must not linger as the published depth
        _stats.set_gauge("prefetch_queue_depth", self._queue.qsize())
        self._stop.clear()
        self._epoch += 1
        self.iter.reset()
        self._start()

    def next(self):
        # goodput.OPEN joins the guard: with the recorder AND profiler
        # off but a goodput run open, input stalls must still book
        # under input_wait, not silently land in host_overhead
        t0 = _time.perf_counter() \
            if _profiler._LIVE or _goodput.OPEN else None
        batch = self._next_impl()
        _stats.set_gauge("prefetch_queue_depth", self._queue.qsize())
        if t0 is not None:
            wait_us = (_time.perf_counter() - t0) * 1e6
            _profiler.record_op(
                "io.prefetch_next", wait_us,
                category="io", lane="io",
                args={"queue_depth": self._queue.qsize()})
            # same consumer-stall histogram DevicePrefetchIter feeds:
            # one series for "how long did the step wait on input"
            _profiler.record_latency("io.prefetch_wait", wait_us)
            _profiler.record_counter("io.prefetch_queue_depth",
                                     self._queue.qsize(), lane="io")
            if _goodput.OPEN:
                # the run ledger's input_wait category rides the SAME
                # wait_us this guard already measured — no new clocks
                _goodput.note_input_wait(wait_us)
        return batch

    def _next_impl(self):
        while True:
            epoch, batch = self._queue.get()
            if epoch != self._epoch:
                continue  # stale batch from before a reset
            if batch is None:
                raise StopIteration
            return batch

    def __del__(self):
        self._stop.set()


class MNISTIter(DataIter):
    """MNIST idx-format reader (ref: src/io/iter_mnist.cc). Reads the
    classic ubyte files; flat or (1,28,28) image layout."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=False, seed=0, **kwargs):
        super().__init__(batch_size)
        with open(image, "rb") as f:
            magic, n, h, w = np.frombuffer(f.read(16), ">i4")
            data = np.frombuffer(f.read(), np.uint8).reshape(n, h, w)
        with open(label, "rb") as f:
            magic, n2 = np.frombuffer(f.read(8), ">i4")
            lab = np.frombuffer(f.read(), np.uint8).astype(np.float32)
        data = data.astype(np.float32) / 255.0
        data = data.reshape(n, h * w) if flat else data.reshape(n, 1, h, w)
        self._iter = NDArrayIter(data, lab, batch_size, shuffle=shuffle,
                                 last_batch_handle="pad")

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def reset(self):
        self._iter.reset()

    def next(self):
        return self._iter.next()
