"""Fault-tolerant sharded streaming input service (ISSUE 11 tentpole).

The scale-out answer to ROADMAP item 5: at 256 chips the input
pipeline, not the TPU, is the ceiling — and a data plane feeding a
preemption-tolerant trainer (PR 7) must itself survive worker crashes,
corrupt records, and elastic resizes *without breaking epoch
determinism*. The design splits into pure math and thin state, in the
tf.data-service spirit (Audibert et al., 2023: the "distributed epoch"
is a function, not a conversation):

**Pure assignment math** (free functions — survivors agree without
talking, because every input is either committed state or a constant):

- :func:`epoch_order` — THE global sample sequence for ``(seed,
  epoch)``; a pure permutation, identical at every world size (the
  determinism contract the test suite pins across worlds 1/2/4).
- :func:`assign_shards` / :func:`reassign_shards` — which contiguous
  slices ("shards") of that sequence each live rank streams;
  round-robin over the *sorted* live world, rotated by epoch. After a
  rank dies, every survivor computes the same reassignment of the
  dead rank's **unconsumed** shards from ``(epoch, survivors,
  committed offset)`` alone.
- :func:`batch_slices` — the per-step split of a global batch
  ``[offset, offset+B)`` over the live world (contiguous, in sorted
  rank order — the reduction-order convention ``HostGradReducer``
  already fixed), so the *training-side* consumption is also a pure
  function of committed state.

**Committed sample cursor**: ``(epoch, offset)`` — how far the global
sequence has been consumed — is published through
``parallel.elastic.CheckpointManager`` and therefore inherits PR 7's
whole crash-consistency contract (temp+rename publication, ``_COMMIT``
markers, truncated-pickle probes, walk-past-corrupt restore). Restore
+ replay from the cursor is bitwise-identical to an uninterrupted run
because the sequence itself never depended on who was alive.

**The service object** composes these with the hardened io plane:
:class:`~mxnet_tpu.io.range_reader.RecordIORangeReader` for the bytes
(retry + corrupt-budget), :class:`~mxnet_tpu.io.worker_pool.DecodePool`
for decode (restart-or-die × N), and ``parallel/elastic.py`` for the
death signal (``elastic_train_loop(data_service=...)`` commits the
cursor beside every checkpoint and calls :meth:`ShardService.resize`
after every reshard).

Faultpoints woven here: ``io.service.fetch`` (the service RPC seam);
the reader and pool carry ``io.shard.read`` / ``io.record.corrupt`` /
``io.worker.decode``. Accounting: ``profiler.metrics()['io']``.
"""
from __future__ import annotations

import os

import numpy as np

from .. import profiler as _profiler
from .._debug import faultpoint as _faultpoint
from .._debug import flightrec as _flightrec
from . import _stats
from .worker_pool import DecodePool
from ..base import getenv as _getenv

__all__ = ["epoch_order", "num_shards", "shard_positions",
           "assign_shards", "reassign_shards", "unconsumed_shards",
           "batch_slices", "ShardService"]


# -- pure assignment math ----------------------------------------------------

def epoch_order(n_samples, epoch, seed=0):
    """THE global sample sequence for ``(seed, epoch)`` — a permutation
    of ``range(n_samples)`` that depends on NOTHING else. Identical at
    every world size, before and after any reshard: elasticity changes
    who fetches a sample, never which sample comes next."""
    n = int(n_samples)
    # fold (seed, epoch) into one 32-bit stream key; RandomState's
    # MT19937 permutation is platform-stable, so every host computes
    # the identical order without communicating
    key = (int(seed) * 1000003 + int(epoch) * 7919) % (2 ** 32)
    return np.random.RandomState(key).permutation(n)


def num_shards(n_samples, shard_size):
    """Shards per epoch: contiguous ``shard_size`` slices of the
    global sequence (last one ragged)."""
    if shard_size <= 0:
        raise ValueError("shard_size must be positive, got %r"
                         % (shard_size,))
    return -(-int(n_samples) // int(shard_size))


def shard_positions(shard, n_samples, shard_size):
    """Global positions (indices INTO the epoch order) shard ``shard``
    covers: ``range(lo, hi)``."""
    lo = int(shard) * int(shard_size)
    hi = min(lo + int(shard_size), int(n_samples))
    return range(lo, hi)


def assign_shards(epoch, world, rank, n_shards, seed=0):
    """Shard ids ``rank`` owns for ``epoch`` — a pure function of its
    arguments, so survivors agree without talking. Round-robin in
    shard order over the SORTED live world, rotated by ``(epoch,
    seed)`` so the rank↔shard pairing rebalances across epochs."""
    world = sorted(int(r) for r in world)
    if int(rank) not in world:
        raise ValueError("rank %r not in world %s" % (rank, world))
    idx = world.index(int(rank))
    n = len(world)
    rot = (int(epoch) + int(seed)) % n
    return tuple(s for s in range(int(n_shards))
                 if (s + rot) % n == idx)


def reassign_shards(epoch, world, rank, shards, seed=0):
    """Deterministically redistribute an explicit shard set (the
    *unconsumed* shards at reshard time) over a new live world. Same
    round-robin discipline as :func:`assign_shards`, applied to the
    sorted survivor list and the sorted shard list — every survivor
    computes the identical split from committed state alone."""
    world = sorted(int(r) for r in world)
    if int(rank) not in world:
        raise ValueError("rank %r not in world %s" % (rank, world))
    idx = world.index(int(rank))
    n = len(world)
    rot = (int(epoch) + int(seed)) % n
    return tuple(s for i, s in enumerate(sorted(int(x) for x in shards))
                 if (i + rot) % n == idx)


def unconsumed_shards(offset, n_samples, shard_size):
    """Shard ids with at least one position >= ``offset`` (the
    committed cursor) — what a reshard must redistribute."""
    ns = num_shards(n_samples, shard_size)
    first = min(int(offset) // int(shard_size), ns)
    return tuple(range(first, ns))


def batch_slices(offset, batch_size, world):
    """Per-rank slices of the global batch ``[offset, offset+B)`` —
    contiguous split in SORTED rank order (the fixed reduction-order
    convention), ragged remainder to the lowest ranks. Returns
    ``{rank: range(lo, hi)}`` of global positions. Delegates to
    ``parallel.elastic.shard_for_rank`` so there is exactly ONE copy of
    the partition convention ``HostGradReducer`` documents."""
    from ..parallel.elastic import shard_for_rank
    off = int(offset)
    out = {}
    for r in sorted(int(x) for x in world):
        s = shard_for_rank(int(batch_size), world, r)
        out[r] = range(off + s.start, off + s.stop)
    return out


# -- the service -------------------------------------------------------------

class ShardService:
    """Per-rank view of the sharded streaming input service.

    Parameters
    ----------
    n_samples : int
        Epoch size (records in the dataset).
    shard_size : int, default ``MXTPU_IO_SHARD_SIZE`` (64)
        Samples per shard — the unit of reassignment on a resize.
    seed : int — shuffle seed (part of the pure sequence key).
    world, rank : the committed live world and this process's rank.
    reader : optional ``RecordIORangeReader``-like with ``read(i)``
        (skip-and-count: ``None`` for a corrupt record).
    decode_fn : optional callable(payload) -> sample, run in the
        decode pool by :meth:`iter_batches`.
    cursor_dir : optional directory for the committed sample cursor
        (a ``parallel.elastic.CheckpointManager`` store — the PR 7
        ``_COMMIT``/temp+rename contract). Without it the cursor is
        process-local only (tests, single-host runs).
    """

    def __init__(self, n_samples, shard_size=None, seed=0, world=(0,),
                 rank=0, reader=None, decode_fn=None, cursor_dir=None,
                 keep=3):
        self.n_samples = int(n_samples)
        if shard_size is None:
            shard_size = int(_getenv("MXTPU_IO_SHARD_SIZE",
                                            "64") or 64)
        self.shard_size = int(shard_size)
        self.seed = int(seed)
        self.rank = int(rank)
        self.world = sorted(int(r) for r in world)
        self.reader = reader
        self.decode_fn = decode_fn
        self.epoch = 0
        self.offset = 0  # committed global positions consumed
        self._ckpt = None
        if cursor_dir is not None:
            from ..parallel.elastic import CheckpointManager
            # the cursor rides the SAME crash-consistency contract as
            # the train state: temp+rename publication, completeness
            # probes, walk-past-corrupt restore
            self._ckpt = CheckpointManager(cursor_dir, keep=keep,
                                           use_orbax=False)
        self._derive_shards()
        self._publish()

    # -- pure views ----------------------------------------------------------
    @property
    def n_shards(self):
        return num_shards(self.n_samples, self.shard_size)

    def global_sequence(self, epoch=None):
        """The world-independent sample-id sequence for ``epoch``."""
        return epoch_order(self.n_samples,
                           self.epoch if epoch is None else epoch,
                           self.seed)

    @property
    def my_shards(self):
        """This rank's current shard assignment (reflects any
        mid-epoch reassignment a :meth:`resize` committed)."""
        return self._shards

    def _derive_shards(self):
        """THE canonical ownership rule: this rank's shards are always
        ``reassign_shards(epoch, world, rank, unconsumed(committed
        offset))`` — a pure function of committed state. At epoch
        start (offset 0) this reduces exactly to the full-epoch
        :func:`assign_shards` round-robin; after a resize it is the
        redistribution of the dead rank's unconsumed shards. Derivation
        happens only at the protocol's anchor points (epoch start,
        seek, resize), which every rank reaches with the same committed
        cursor — so every rank derives the identical partition."""
        remaining = unconsumed_shards(self.offset, self.n_samples,
                                      self.shard_size)
        self._shards = reassign_shards(self.epoch, self.world,
                                       self.rank, remaining, self.seed)

    def _publish(self):
        _stats.set_gauge("service_epoch", self.epoch)
        _stats.set_gauge("service_offset", self.offset)
        _stats.set_gauge("service_shards_owned", len(self._shards))
        _flightrec.set_context("io_shard_service", {
            "rank": self.rank, "world": list(self.world),
            "epoch": self.epoch, "offset": self.offset,
            "shards": list(self._shards),
        })

    # -- epoch / cursor lifecycle --------------------------------------------
    def begin_epoch(self, epoch):
        """Enter ``epoch`` at offset 0 with the full-epoch pure
        assignment over the current world."""
        self.epoch = int(epoch)
        self.offset = 0
        self._derive_shards()
        self._publish()

    def advance(self, n):
        """Move the (uncommitted) cursor: ``n`` more global positions
        consumed. Rolls into the next epoch at the boundary."""
        self.offset += int(n)
        while self.offset >= self.n_samples:
            extra = self.offset - self.n_samples
            self.begin_epoch(self.epoch + 1)
            self.offset = extra
        self._publish()

    def cursor(self):
        """The committed-state blob: everything replay needs."""
        return {"epoch": int(self.epoch), "offset": int(self.offset),
                "world": list(self.world)}

    def commit(self, step):
        """Publish the cursor for train step ``step`` through the
        service's own crash-consistent store (standalone use — a data
        plane checkpointing independently of a trainer). Trainers
        driving ``elastic_train_loop`` get a STRICTLY atomic pairing
        instead: the loop embeds :meth:`cursor_for_checkpoint` in the
        params checkpoint payload itself, so one temp+rename publishes
        (or vanishes) both. No-op without a ``cursor_dir``."""
        _stats.bump("cursor_commits")
        if self._ckpt is not None:
            self._ckpt.save(int(step), self.cursor())

    def cursor_for_checkpoint(self):
        """The cursor blob to embed in a trainer's checkpoint payload
        (counted as a commit) — ONE atomic publish covers params and
        cursor, closing the torn-pair window two separate stores would
        leave between their renames."""
        _stats.bump("cursor_commits")
        return self.cursor()

    def apply_cursor(self, cur):
        """Adopt a cursor blob recovered from a trainer's checkpoint
        (counted as a restore). Values may be checkpoint-round-tripped
        host arrays; the recorded world is informational — the CURRENT
        world stands, so applying an old cursor after a reshard cannot
        resurrect a dead rank."""
        self.epoch = int(cur["epoch"])
        self.offset = int(cur["offset"])
        self._derive_shards()
        _stats.bump("cursor_restores")
        self._publish()

    def seek(self, step=None):
        """Restore the newest committed cursor at or before ``step``
        (newest overall when ``step`` is None); fresh-epoch-0 cursor
        when nothing was ever committed. Returns the cursor dict."""
        cur = None
        if self._ckpt is not None:
            steps = self._ckpt.all_steps()
            if step is not None:
                steps = [s for s in steps if s <= int(step)]
            if steps:
                raw, _ = self._ckpt.restore(steps[-1])
                # CheckpointManager round-trips leaves as host arrays;
                # normalize back to the plain-int cursor contract
                cur = {"epoch": int(raw["epoch"]),
                       "offset": int(raw["offset"]),
                       "world": [int(r) for r in raw["world"]]}
        if cur is None:
            cur = {"epoch": 0, "offset": 0, "world": list(self.world)}
        self.epoch = int(cur["epoch"])
        self.offset = int(cur["offset"])
        # NOTE: the cursor's recorded world is informational — the
        # CURRENT world (the elastic controller's province) stands, so
        # seeking after a reshard cannot resurrect a dead rank
        self._derive_shards()
        _stats.bump("cursor_restores")
        self._publish()
        return dict(cur)

    def resize(self, world):
        """Commit an elastic resize: the new live world takes over the
        **unconsumed** shards (everything at or past the committed
        cursor), via the pure :func:`reassign_shards` — so every
        survivor lands on the identical assignment without a word on
        the wire. Positions below the cursor stay consumed; the global
        sequence is untouched."""
        self.world = sorted(int(r) for r in world)
        self._derive_shards()
        _stats.bump("service_resizes")
        _profiler.marker("io.service.resize", lane="io",
                         args={"world": list(self.world),
                               "offset": int(self.offset)})
        self._publish()

    # -- streaming -----------------------------------------------------------
    def iter_samples(self, start=None):
        """This rank's stream: ``(global_pos, sample_id)`` for every
        position in its shards at or past ``start`` (default: the
        committed cursor), in global-position order."""
        start = self.offset if start is None else int(start)
        order = self.global_sequence()
        for s in self._shards:
            span = shard_positions(s, self.n_samples, self.shard_size)
            if span.stop <= start:
                continue  # fully consumed before the cursor
            for pos in span:
                if pos < start:
                    continue
                yield pos, int(order[pos])

    def fetch_batch(self, sample_ids):
        """Fetch (and optionally decode, inline) a list of records —
        the disaggregated-service RPC seam (``io.service.fetch``).
        Corrupt records were already skip-and-counted by the reader
        (``None`` entries are dropped here, counted
        ``io.samples_dropped``)."""
        if _faultpoint.ACTIVE:
            _faultpoint.check("io.service.fetch")
        _stats.bump("samples_streamed", len(sample_ids))
        if self.reader is None:
            payloads = list(sample_ids)
        else:
            payloads = [self.reader.read(i) for i in sample_ids]
            dropped = sum(1 for p in payloads if p is None)
            if dropped:
                _stats.bump("samples_dropped", dropped)
            payloads = [p for p in payloads if p is not None]
        if self.decode_fn is not None:
            payloads = [self.decode_fn(p) for p in payloads]
        return payloads

    def iter_batches(self, batch_size, start=None, workers=0,
                     **pool_kwargs):
        """Batches of this rank's stream: yields ``(positions,
        samples)`` with ``len(samples) == len(positions)`` minus any
        corrupt-skipped records. ``workers > 0`` routes fetch+decode
        through a :class:`DecodePool` (order preserved by the pool's
        sequence slots); ``workers == 0`` stays inline."""
        batch_size = int(batch_size)

        def batched():
            buf = []
            for pos_id in self.iter_samples(start):
                buf.append(pos_id)
                if len(buf) == batch_size:
                    yield buf
                    buf = []
            if buf:
                yield buf

        if workers <= 0:
            for group in batched():
                ids = [sid for _, sid in group]
                yield [p for p, _ in group], self.fetch_batch(ids)
            return

        def fetch_one(group):
            return ([p for p, _ in group],
                    self.fetch_batch([sid for _, sid in group]))

        # fully streaming: the pool claims groups lazily from the
        # generator and its sequence slots keep batch order no matter
        # how many workers race the fetches
        pool = DecodePool(batched(), fetch_one, workers=workers,
                          name="shard_service", **pool_kwargs)
        try:
            for positions, samples in pool:
                yield positions, samples
        finally:
            # generator finalization (break / GC / .close()) must not
            # leave N workers polling forever
            pool.close()
