"""RecordIO over a range-read primitive — the object-storage reader.

The repo's ``MXRecordIO`` assumes a seekable local file handle; an
object store (GCS/S3-style) offers only *ranged GETs* that can fail
transiently and can return corrupt bytes. :class:`RecordIORangeReader`
reads the same dmlc-recordio byte format through a pluggable
``fetch(offset, nbytes) -> bytes`` primitive and hardens both failure
modes (ISSUE 11 tentpole c):

- **transient read failure** — every fetch attempt runs under the
  unified ``_retry`` policy (exponential backoff + jitter + deadline,
  the ``MXTPU_PS_RETRY_*`` knobs), counted ``io.read_retries``; the
  ``io.shard.read`` faultpoint fires per attempt, exactly where a
  dropped connection would surface.
- **corrupt record** — every record is validated (magic word, whole
  cflag, sane length, full payload, optional crc32 sidecar) before it
  is returned. A corrupt record raises :class:`CorruptRecordError`
  from :meth:`read_record`; the skip-and-count form :meth:`read`
  swallows it, counts ``io.corrupt_records``, and returns ``None`` —
  until the per-reader budget (``MXTPU_IO_CORRUPT_BUDGET``, default 8)
  is exhausted, at which point corruption stops being noise and
  becomes a hard error (a store returning garbage at scale is an
  outage, not a data-cleaning problem). The ``io.record.corrupt``
  faultpoint is woven INTO the validation seam, so injected chaos is
  indistinguishable from real bit rot.

Checksums: dmlc recordio has no payload checksum, so the write side
here grows one as a sidecar — :func:`build_crc_sidecar` walks a .rec
file and writes ``<uri>.crc`` (``offset\\tcrc32`` per record,
published via the temp+rename contract). When the sidecar exists the
reader validates every payload against it; without it, validation is
structural only (magic/length/truncation).
"""
from __future__ import annotations

import os
import struct
import zlib

from .. import _retry
from ..base import atomic_write
from .._debug import faultpoint as _faultpoint
from .._debug import locktrace as _locktrace
from . import _stats
from ..base import getenv as _getenv

__all__ = ["RecordIORangeReader", "CorruptRecordError",
           "build_crc_sidecar"]

_kMagic = 0xced7230a
_HEAD = struct.Struct("<II")
_LREC_KIND_BITS = 29
_LREC_LEN_MASK = (1 << _LREC_KIND_BITS) - 1


class CorruptRecordError(RuntimeError):
    """A record failed validation (bad magic, truncated payload, crc
    mismatch, or an injected ``io.record.corrupt`` fault). Deliberately
    NOT an ``OSError`` subclass: corruption is a *data* verdict and
    must never enter the transient-retry set — refetching corrupt
    bytes returns the same corrupt bytes."""


def _corrupt_budget():
    return int(_getenv("MXTPU_IO_CORRUPT_BUDGET", "8"))


class RecordIORangeReader:
    """Random-access recordio reads over ``fetch(offset, nbytes)``.

    Parameters
    ----------
    uri : str, optional
        Local file path (the default fetch is ``os.pread`` over it —
        the test/bench stand-in for a ranged GET).
    fetch : callable(offset, nbytes) -> bytes, optional
        The object-storage primitive; may return fewer bytes at EOF
        and may raise ``ConnectionError``/``OSError``/``TimeoutError``
        transiently (retried under ``retry_policy``).
    index : sequence of int, or path to a ``.idx`` sidecar, optional
        Record byte offsets. When omitted, the file is scanned once
        through ``fetch`` (header-hopping, no payload reads).
    crc_path : str, optional
        Checksum sidecar (default ``<uri>.crc`` when it exists).
    corrupt_budget : int, optional
        Corrupt records to skip-and-count before :meth:`read` trips to
        a hard error. Default ``MXTPU_IO_CORRUPT_BUDGET`` (8).
    retry_policy : `_retry.RetryPolicy`, optional
        Backoff budget for transient fetch failures.
    """

    def __init__(self, uri=None, fetch=None, index=None, crc_path=None,
                 corrupt_budget=None, retry_policy=None):
        if fetch is None and uri is None:
            raise ValueError("RecordIORangeReader needs a uri or a "
                             "fetch(offset, nbytes) callable")
        self.uri = uri
        self._fd = None
        if fetch is None:
            self._fd = os.open(uri, os.O_RDONLY)

            def fetch(offset, nbytes):
                return os.pread(self._fd, nbytes, offset)
        self._fetch = fetch
        self._policy = retry_policy or _retry.RetryPolicy()
        self._budget = _corrupt_budget() if corrupt_budget is None \
            else int(corrupt_budget)
        # one reader is shared across DecodePool workers
        # (ShardService.iter_batches): the budget's read-modify-write
        # must not race, or two threads can both observe budget-1 and
        # sail past the documented hard-trip threshold
        self._corrupt = 0
        self._corrupt_lock = _locktrace.named_lock("io.range_reader")
        if isinstance(index, str):
            offsets = []
            with open(index) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) == 2:
                        offsets.append(int(parts[1]))
            self._offsets = offsets
        elif index is not None:
            self._offsets = [int(o) for o in index]
        else:
            self._offsets = self._scan_offsets()
        self._crcs = None
        if crc_path is None and uri is not None \
                and os.path.exists(uri + ".crc"):
            crc_path = uri + ".crc"
        if crc_path is not None:
            self._crcs = {}
            with open(crc_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) == 2:
                        self._crcs[int(parts[0])] = int(parts[1])

    # -- transport ----------------------------------------------------------
    def _fetch_retry(self, offset, nbytes):
        """One ranged read under the unified retry policy; the
        ``io.shard.read`` faultpoint fires per ATTEMPT (like
        ``kvstore.send``), so chaos exercises the backoff loop."""

        def attempt():
            if _faultpoint.ACTIVE:
                _faultpoint.check("io.shard.read")
            return self._fetch(offset, nbytes)

        def on_retry(n, exc, delay):
            _stats.bump("read_retries")

        return _retry.call(
            attempt, retryable=(ConnectionError, OSError, TimeoutError),
            policy=self._policy, on_retry=on_retry)

    def _scan_offsets(self):
        """Header-hop the file once: offsets of every record without
        reading payloads (the index build for index-less uris)."""
        offsets, off = [], 0
        while True:
            head = self._fetch_retry(off, _HEAD.size)
            if len(head) < _HEAD.size:
                return offsets
            magic, lrec = _HEAD.unpack(head)
            if magic != _kMagic:
                raise CorruptRecordError(
                    "bad RecordIO magic 0x%08x at offset %d while "
                    "scanning %r" % (magic, off, self.uri))
            length = lrec & _LREC_LEN_MASK
            offsets.append(off)
            off += _HEAD.size + length + (4 - length % 4) % 4

    # -- records ------------------------------------------------------------
    def __len__(self):
        return len(self._offsets)

    @property
    def corrupt_skipped(self):
        return self._corrupt

    def read_record(self, i):
        """Record ``i``'s payload bytes, fully validated. Raises
        :class:`CorruptRecordError` on any validation failure —
        callers that prefer skip-and-count use :meth:`read`."""
        off = self._offsets[i]
        head = self._fetch_retry(off, _HEAD.size)
        if len(head) < _HEAD.size:
            raise CorruptRecordError(
                "truncated header at offset %d (record %d)" % (off, i))
        magic, lrec = _HEAD.unpack(head)
        if magic != _kMagic:
            raise CorruptRecordError(
                "bad magic 0x%08x at offset %d (record %d)"
                % (magic, off, i))
        cflag = lrec >> _LREC_KIND_BITS
        if cflag != 0:
            # range reads address records independently; dmlc split
            # records (payload contained the magic word) would need the
            # writer-side split protocol — our writers write whole
            raise CorruptRecordError(
                "split record (cflag=%d) at offset %d — the range "
                "reader only addresses whole records" % (cflag, off))
        length = lrec & _LREC_LEN_MASK
        payload = self._fetch_retry(off + _HEAD.size, length)
        if len(payload) < length:
            raise CorruptRecordError(
                "truncated payload at offset %d: wanted %d got %d"
                % (off, length, len(payload)))
        if _faultpoint.ACTIVE:
            # woven INTO the validation seam: an injected raise here is
            # handled exactly like real bit rot (skip-and-count budget)
            try:
                _faultpoint.check("io.record.corrupt")
            except Exception as e:
                raise CorruptRecordError(
                    "injected corrupt record %d: %s" % (i, e))
        if self._crcs is not None:
            want = self._crcs.get(off)
            got = zlib.crc32(payload) & 0xffffffff
            if want is not None and got != want:
                raise CorruptRecordError(
                    "crc mismatch at offset %d (record %d): sidecar "
                    "%08x, payload %08x" % (off, i, want, got))
        return payload

    def read(self, i):
        """Skip-and-count form: a corrupt record returns ``None`` (the
        caller drops the sample) and counts ``io.corrupt_records`` —
        until the budget trips, after which the error is hard: past
        ``MXTPU_IO_CORRUPT_BUDGET`` corruptions this store is broken,
        not noisy."""
        try:
            return self.read_record(i)
        except CorruptRecordError as e:
            with self._corrupt_lock:
                self._corrupt += 1
                tripped = self._corrupt > self._budget
                count = self._corrupt
            _stats.bump("corrupt_records")
            if tripped:
                raise CorruptRecordError(
                    "corrupt-record budget exhausted: %d corrupt "
                    "records > MXTPU_IO_CORRUPT_BUDGET=%d (last: %s)"
                    % (count, self._budget, e))
            return None

    def close(self):
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # mxlint: disable=MX009 (interpreter teardown — os may already be gone)
            pass


def build_crc_sidecar(rec_path, out_path=None):
    """Walk ``rec_path`` and publish ``<rec_path>.crc`` — one
    ``offset\\tcrc32`` line per record, written through the temp+rename
    contract so a crash mid-build never leaves a half sidecar that
    silently validates only a prefix. Returns the sidecar path."""
    out_path = out_path or rec_path + ".crc"
    lines = []
    with open(rec_path, "rb") as f:
        off = 0
        while True:
            head = f.read(_HEAD.size)
            if len(head) < _HEAD.size:
                break
            magic, lrec = _HEAD.unpack(head)
            if magic != _kMagic:
                raise IOError("bad RecordIO magic at offset %d in %r"
                              % (off, rec_path))
            length = lrec & _LREC_LEN_MASK
            payload = f.read(length)
            if len(payload) < length:
                raise IOError("truncated record at offset %d in %r"
                              % (off, rec_path))
            f.read((4 - length % 4) % 4)
            lines.append("%d\t%d" % (off, zlib.crc32(payload)
                                     & 0xffffffff))
            off += _HEAD.size + length + (4 - length % 4) % 4
    with atomic_write(out_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return out_path
