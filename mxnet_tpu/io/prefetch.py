"""Device-feed double buffering (VERDICT r3 item 4; SURVEY §7.5
"double-buffered host→HBM copies"; the host-side analog in the reference
is src/io/iter_prefetcher.h PrefetcherIter — this is its DEVICE-side
completion).

A background thread walks the underlying iterator and issues
``jax.device_put`` for batch N+1 while the consumer computes on batch N,
so the host→HBM copy overlaps compute instead of serializing with it.
``jax.device_put`` is async (returns immediately with an on-the-way
buffer) and thread-safe, so the queue depth of 2 gives classic double
buffering without any device-side synchronization.
"""
from __future__ import annotations

import queue
import threading
import time as _time

from .. import profiler as _profiler
from .._debug import faultpoint as _faultpoint
from .._debug import goodput as _goodput
from . import _stats

__all__ = ["DevicePrefetchIter", "DevicePrefetcher"]

_SENTINEL = object()


class DevicePrefetchIter:
    """Wrap any iterable of batches; yields device-placed batches.

    Parameters
    ----------
    it : iterable (restartable via ``reset()`` if it has one)
    place_fn : callable(batch) -> placed batch, default
        ``jax.device_put`` of the batch as-is. Runs on the background
        thread — keep it host-side (decode/normalize-on-host) or a plain
        device_put; jitted work belongs on the consumer side.
    depth : int, default 2
        Max in-flight placed batches (2 = double buffering).
    sharding : optional jax sharding passed to the default place_fn.
    """

    def __init__(self, it, place_fn=None, depth=2, sharding=None):
        if place_fn is None:
            import jax
            from ..ndarray import NDArray

            from .. import storage as _storage_mod

            def place_one(a):
                if isinstance(a, NDArray):
                    placed = jax.device_put(
                        a._data, sharding) if sharding is not None \
                        else jax.device_put(a._data)
                else:
                    placed = jax.device_put(a, sharding) \
                        if sharding is not None else jax.device_put(a)
                # allocation-ledger choke point (ISSUE 13a): host->HBM
                # input batches are the 'io' tag
                _storage_mod.ledger_register(placed, "io",
                                             site="io.prefetch")
                return NDArray(placed) if isinstance(a, NDArray) \
                    else placed

            def place_fn(batch):
                return jax.tree_util.tree_map(
                    place_one, batch,
                    is_leaf=lambda l: isinstance(l, NDArray))
        self._it = it
        self._place = place_fn
        self._depth = depth
        self._q = None
        self._thread = None
        self._start()

    def _start(self):
        self._q = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        # restart-or-die bookkeeping: once the worker dies on an
        # exception the consumer sees it EXACTLY ONCE; afterwards the
        # iterator is exhausted (StopIteration, like any finished
        # iterator) until reset() launches a fresh worker
        self._worker_failed = False
        # gauge re-seed (ISSUE 11 satellite): a restart discards
        # whatever sat in the old queue, so the published depth must
        # come from the LIVE queue — never a stale pre-death sample,
        # never a negative from delta bookkeeping over dropped items
        _stats.set_gauge("prefetch_queue_depth", self._q.qsize())
        q, stop = self._q, self._stop

        def put(item):
            # bounded put that observes cancellation so reset() never
            # waits on a full epoch being produced just to discard it
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for batch in self._it:
                    t0 = _time.perf_counter() if _profiler._LIVE \
                        else None
                    if _faultpoint.ACTIVE:
                        _faultpoint.check("io.prefetch.place")
                    placed = self._place(batch)
                    if t0 is not None:
                        _profiler.record_op(
                            "io.batch_place",
                            (_time.perf_counter() - t0) * 1e6,
                            category="io", lane="io",
                            args={"queue_depth": q.qsize()})
                    if stop.is_set() or not put(placed):
                        return
                    _stats.set_gauge("prefetch_queue_depth", q.qsize())
                    if t0 is not None:
                        _profiler.record_counter(
                            "io.prefetch_queue_depth", q.qsize(),
                            lane="io")
            except BaseException as e:  # mxlint: disable=MX009 (queued to the consumer — raised once at __next__ — and counted via _stats.bump -> profiler.account)
                # a worker death is a counted event, not just a raised
                # exception: io.prefetch_worker_deaths is the restart
                # diagnostic (how often did reset() have to recover?) —
                # counted even with profiling off (_stats.bump feeds
                # both metrics()['io'] and the unconditional
                # profiler.account ledger)
                _stats.bump("prefetch_worker_deaths")
                put(e)
                return
            put(_SENTINEL)

        self._thread = threading.Thread(target=worker, daemon=True,
                                        name="device-prefetch")
        self._thread.start()

    def reset(self):
        """Cancel the in-flight producer and restart the underlying
        iterator — including after a worker death: the exception was
        raised once, the iterator then reads exhausted, and reset()
        starts a FRESH worker (restart-or-die recovery). Requires a
        restartable source (one with ``reset()``, or a re-iterable like
        a DataLoader); a plain generator cannot be rewound — batches
        consumed before reset are lost."""
        self._stop.set()
        while self._thread.is_alive():
            try:  # unblock a worker stuck on a full queue
                self._q.get(timeout=0.05)
            except queue.Empty:
                pass
        self._thread.join()
        if hasattr(self._it, "reset"):
            self._it.reset()
        self._start()

    def __iter__(self):
        return self

    def __next__(self):
        # a dead worker queued its exception ONCE (already raised): the
        # iterator is exhausted now — StopIteration, not a block-forever
        # q.get() and not the same exception replayed, so `for` loops
        # terminate and reset() is the documented way back
        if self._worker_failed:
            raise StopIteration
        # batch-fetch span: how long the consumer stalled waiting on the
        # producer (queue-empty time = the pipeline is io-bound).
        # goodput.OPEN joins the guard so input_wait attribution
        # survives a flightrec-off deployment
        t0 = _time.perf_counter() \
            if _profiler._LIVE or _goodput.OPEN else None
        item = self._q.get()
        _stats.set_gauge("prefetch_queue_depth", self._q.qsize())
        if t0 is not None:
            wait_us = (_time.perf_counter() - t0) * 1e6
            _profiler.record_op(
                "io.batch_fetch", wait_us,
                category="io", lane="io",
                args={"queue_depth": self._q.qsize()})
            # consumer-stall histogram: p95/p99 here >> 0 means the
            # input pipeline, not the step, is the ceiling
            _profiler.record_latency("io.prefetch_wait", wait_us)
            _profiler.record_counter("io.prefetch_queue_depth",
                                     self._q.qsize(), lane="io")
            if _goodput.OPEN:
                # goodput input_wait rides the already-measured stall
                _goodput.note_input_wait(wait_us)
        if item is _SENTINEL:
            raise StopIteration
        if isinstance(item, BaseException):
            self._worker_failed = True
            raise item
        return item

    next = __next__


class DevicePrefetcher(DevicePrefetchIter):
    """Gluon DataLoader adapter: yields (data, label) already on device,
    h2d overlapped with compute. ``len()`` forwards to the loader.

        loader = gluon.data.DataLoader(dataset, batch_size)
        for x, y in DevicePrefetcher(loader):
            ...train on device arrays...
    """

    def __init__(self, loader, depth=2, sharding=None):
        self._loader = loader
        super().__init__(loader, depth=depth, sharding=sharding)

    def __len__(self):
        return len(self._loader)

    def __iter__(self):
        self.reset()
        return self
