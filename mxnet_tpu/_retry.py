"""Unified transport retry: exponential backoff + jitter + deadline.

One policy replaces the scattered ``except (ConnectionError, OSError)``
paths in the async parameter-server client (``kvstore_async.py``): every
retry loop in the framework backs off the same way, is bounded the same
way, and is tuned by the same ``MXTPU_PS_RETRY_*`` env knobs
(docs/RESILIENCE.md has the full catalog):

===========================  =======  =====================================
``MXTPU_PS_RETRY_MAX``       ``8``    max retry attempts after the first
                                      try (0 disables retrying)
``MXTPU_PS_RETRY_BASE``      ``0.05`` first backoff in seconds; doubles
                                      each attempt
``MXTPU_PS_RETRY_CAP``       ``2.0``  per-sleep ceiling in seconds
``MXTPU_PS_RETRY_DEADLINE``  ``30``   total seconds across all attempts;
                                      when the next sleep would cross it,
                                      the last error re-raises instead
===========================  =======  =====================================

Jitter is the classic decorrelation trick (up to +50% of each sleep) so
N workers retrying against one recovering server do not thundering-herd
in lockstep; it perturbs only *when* a retry happens, never *what* it
does, so chaos-run results stay deterministic. Under a configured
``MXNET_FAULTPOINTS_SEED`` the jitter stream itself is seeded per policy
(the faultpoint ``(seed, name)`` idiom), so a seeded chaos run's backoff
schedule replays identically run-to-run; unset keeps production
decorrelation.
"""
from __future__ import annotations

import os
import random
import time

from .base import getenv as _getenv

__all__ = ["RetryPolicy", "call"]


class RetryPolicy:
    """Backoff schedule: ``base * 2**attempt`` capped at ``cap``, plus
    0-50% jitter, bounded by both ``max_retries`` and ``deadline``
    seconds of total elapsed time. Env knobs supply the defaults at
    construction time (so tests can monkeypatch them per case)."""

    def __init__(self, max_retries=None, base=None, cap=None,
                 deadline=None):
        self.max_retries = int(_getenv("MXTPU_PS_RETRY_MAX", "8")) \
            if max_retries is None else int(max_retries)
        self.base = float(_getenv("MXTPU_PS_RETRY_BASE", "0.05")) \
            if base is None else float(base)
        self.cap = float(_getenv("MXTPU_PS_RETRY_CAP", "2.0")) \
            if cap is None else float(cap)
        self.deadline = float(_getenv("MXTPU_PS_RETRY_DEADLINE", "30")) \
            if deadline is None else float(deadline)
        # chaos determinism (ISSUE 20 satellite): with a faultpoint seed
        # configured, this policy's jitter draws from its own seeded
        # stream — two policies built under the same seed replay the
        # same backoff sequence. Unset (production) keeps the shared
        # unseeded RNG's decorrelation across workers.
        seed = _getenv("MXNET_FAULTPOINTS_SEED", "")
        self._rng = random.Random("%s:retry" % seed) if seed else None

    def backoff(self, attempt):
        """Sleep before retry ``attempt`` (1-based), jittered."""
        raw = min(self.cap, self.base * (2.0 ** (attempt - 1)))
        return raw * (1.0 + 0.5 * (self._rng or random).random())


def call(fn, retryable=(ConnectionError, OSError), policy=None,
         on_retry=None):
    """Run ``fn()`` with retries on ``retryable`` exceptions.

    ``on_retry(attempt, exc, delay)`` fires before each backoff sleep —
    the hook where callers count retries distinctly per subsystem and
    drop broken sockets. Exhausting ``max_retries`` or the deadline
    re-raises the last error unchanged, so callers' exception contracts
    are the same as the unretried call's."""
    if policy is None:
        policy = RetryPolicy()
    start = time.monotonic()
    attempt = 0
    while True:
        try:
            return fn()
        except retryable as e:
            attempt += 1
            if attempt > policy.max_retries:
                raise
            delay = policy.backoff(attempt)
            if time.monotonic() + delay - start > policy.deadline:
                raise
            if on_retry is not None:
                on_retry(attempt, e, delay)
            time.sleep(delay)
