"""Flagship distributed model: decoder-only transformer LM, mesh-native.

The reference's largest-scale story is ResNet-152 data-parallel on 256 GPUs
(ref: example/image-classification/README.md:309); its sequence story is
bucketed RNNs. This module is the modern capability equivalent: one
transformer whose training step composes EVERY parallelism axis —

  dp    batch                       (≙ kvstore data parallel)
  fsdp  sharded params/optimizer    (≙ server-held state, ZeRO)
  tp    Megatron column/row splits  (psum on row-parallel outputs)
  sp    ring attention over ICI     (context parallelism)
  pp    GPipe stages over 'pp'      (≙ group2ctx model parallelism)
  ep    MoE experts                 (GShard-style dense dispatch)

Two execution modes:
- GSPMD mode (pp=1): params carry PartitionSpecs, jit compiles, XLA inserts
  collectives. Attention can be 'local', 'ring' (shard_map ppermute ring)
  or 'ulysses' (all-to-all head swap).
- Explicit mode (pp>1): the whole step runs in one shard_map over
  (pp, dp, sp, tp) with hand-written psum/ppermute — the scaling-book
  recipe, stage-homogeneous GPipe with microbatching.

RoPE positions, RMSNorm, SwiGLU FFN: bf16-friendly, static shapes, scan
over layers (single compiled layer body, MXU-sized matmuls).
"""
from __future__ import annotations

import dataclasses
import functools
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import jax.random as jr
from jax import lax

from jax.ad_checkpoint import checkpoint_name as _ckpt_name

from ..base import getenv as _getenv
from .compat import NamedSharding, PartitionSpec as P

from .ring_attention import ring_attention, blockwise_attention
from .ulysses import ulysses_attention_local
from .expert import moe_ffn

__all__ = ["TransformerConfig", "init_params", "apply", "loss_fn",
           "make_train_step", "param_specs", "ce_local_accum_active"]


@dataclasses.dataclass
class TransformerConfig:
    vocab_size: int = 32000
    dim: int = 512
    n_layers: int = 4
    n_heads: int = 8
    ffn_hidden: int = 1376
    max_seq_len: int = 2048
    dtype: str = "float32"
    # parallelism
    attn_mode: str = "local"          # 'local' | 'ring' | 'ulysses' | 'blockwise'
    pp: int = 1                        # pipeline stages (>1 = explicit mode)
    n_microbatch: int = 1
    # MoE: every `moe_every`-th layer is an expert layer when num_experts > 0
    num_experts: int = 0
    moe_k: int = 2
    causal: bool = True
    # rematerialize each layer in backward (activation recompute): trades
    # ~1/3 more FLOPs for O(n_layers) less activation HBM, the standard
    # TPU trade (SURVEY §7: jax.checkpoint)
    remat: bool = True
    # selective remat: names of intermediates the backward may KEEP
    # instead of recomputing (jax save_only_these_names policy).
    # "ffn_prod" saves the gated-FFN product [B,S,ffn_hidden] — skips
    # recomputing the two up-projections (the biggest matmuls);
    # "attn_o" saves the attention output [B,S,D] — skips re-running
    # the flash forward kernel inside the backward. Empty = full remat.
    remat_save: tuple = ()
    # >1: compute the final projection + cross-entropy in this many
    # sequence chunks (sequential lax.map + per-chunk remat), so the
    # [B, S, vocab] f32 logits tensor never materializes — at 32k vocab
    # that saves GBs of HBM and is what lets batch 8 fit on one chip
    loss_chunks: int = 1
    # accumulate the chunked-CE unembedding gradient LOCALLY (shard_map
    # over the batch axes) and reduce it ONCE, instead of letting GSPMD
    # keep the all-reduce inside the chunk scan (the SCALING_r05
    # finding: AR-per-chunk adds (loss_chunks-1)*vocab*dim*4 wire bytes
    # per step, ~36% extra transformer bytes at 256 chips). Needs the
    # mesh passed to loss_fn/make_train_step; covers dp x sp x tp
    # layouts (tp-sharded vocab handled with a distributed logsumexp).
    # None = AUTO: on whenever the mesh shards the batch (dp*sp > 1),
    # loss_chunks > 1 and the shapes divide; True forces it (indivisible
    # shapes raise); False pins the plain chunked CE. The
    # MXTPU_CE_LOCAL_ACCUM env var ('auto'/'1'/'0', a compile-signature
    # token) overrides the auto default process-wide.
    ce_local_accum: Optional[bool] = None

    @property
    def head_dim(self):
        return self.dim // self.n_heads


def _rms_norm(x, scale, eps=1e-6):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * lax.rsqrt(var + eps)).astype(x.dtype) * scale


def _rope(x, positions):
    """Rotary position embedding. x: [B, H, S, D_h], positions: [S]."""
    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [S, half]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return rot.astype(x.dtype)


def init_params(key, cfg: TransformerConfig):
    """Param pytree. Layer params are STACKED on a leading axis: [L, ...]
    in GSPMD mode, [pp, L/pp, ...] in explicit pipeline mode — the leading
    axis is scanned (one compiled layer body) and, for pp, mesh-sharded."""
    dt = jnp.dtype(cfg.dtype)
    D, H, Dh, F = cfg.dim, cfg.n_heads, cfg.head_dim, cfg.ffn_hidden
    L = cfg.n_layers
    keys = jr.split(key, 8)

    def norm(k, shape, fan_in):
        return (jr.normal(k, shape) * (fan_in ** -0.5)).astype(dt)

    layer = {
        "ln1": jnp.ones((L, D), dt),
        "wq": norm(keys[0], (L, D, H, Dh), D),
        "wk": norm(keys[1], (L, D, H, Dh), D),
        "wv": norm(keys[2], (L, D, H, Dh), D),
        "wo": norm(keys[3], (L, H, Dh, D), H * Dh),
        "ln2": jnp.ones((L, D), dt),
        "w_gate": norm(keys[4], (L, D, F), D),
        "w_up": norm(keys[5], (L, D, F), D),
        "w_down": norm(keys[6], (L, F, D), F),
    }
    if cfg.num_experts > 0:
        E = cfg.num_experts
        ek = jr.split(keys[7], 4)
        layer["moe_router"] = norm(ek[0], (L, D, E), D)
        layer["moe_w1"] = norm(ek[1], (L, E, D, F), D)
        layer["moe_w2"] = norm(ek[2], (L, E, F, D), F)
    if cfg.pp > 1:
        assert L % cfg.pp == 0, "n_layers must divide pp"
        layer = {k: v.reshape((cfg.pp, L // cfg.pp) + v.shape[1:])
                 for k, v in layer.items()}
    emb_key, out_key = jr.split(jr.fold_in(key, 99))
    return {
        "embed": norm(emb_key, (cfg.vocab_size, D), D) * (D ** 0.5),
        "layers": layer,
        "ln_f": jnp.ones((D,), dt),
        "w_out": norm(out_key, (D, cfg.vocab_size), D),
    }


def param_specs(cfg: TransformerConfig):
    """PartitionSpecs matching init_params structure (GSPMD mode).
    Column-parallel on heads/ffn over 'tp'; fsdp composes by sharding the
    layer-stack axis? No — fsdp shards the largest non-tp dim via
    sharding.fsdp rules; here we give the Megatron TP layout."""
    lead = ("pp",) if cfg.pp > 1 else (None,)
    lead = lead + ((None,) if cfg.pp > 1 else ())

    def ls(*rest):  # layer-stacked spec
        return P(*(lead + rest))

    layer = {
        "ln1": ls(None),
        "wq": ls(None, "tp", None),
        "wk": ls(None, "tp", None),
        "wv": ls(None, "tp", None),
        "wo": ls("tp", None, None),
        "ln2": ls(None),
        "w_gate": ls(None, "tp"),
        "w_up": ls(None, "tp"),
        "w_down": ls("tp", None),
    }
    if cfg.num_experts > 0:
        layer["moe_router"] = ls(None, None)
        layer["moe_w1"] = ls("ep", None, "tp")
        layer["moe_w2"] = ls("ep", "tp", None)
    if cfg.pp > 1:
        # explicit mode indexes embed/w_out with global token ids inside the
        # shard_map body, so they stay replicated across tp
        embed_spec, out_spec = P(None, None), P(None, None)
    else:
        embed_spec, out_spec = P("tp", None), P(None, "tp")
    return {
        "embed": embed_spec,
        "layers": layer,
        "ln_f": P(None),
        "w_out": out_spec,
    }


# --------------------------------------------------------------------------
# GSPMD mode forward (pp == 1)
# --------------------------------------------------------------------------

def _attention(cfg, mesh, q, k, v, positions):
    """q/k/v: [B, S, H, Dh] -> [B, S, H, Dh]. Global arrays (GSPMD mode)."""
    qt = jnp.transpose(q, (0, 2, 1, 3))  # [B, H, S, Dh]
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    if cfg.attn_mode == "ring_flash" and mesh is not None:
        # inter-chip ppermute ring x intra-chip Pallas flash blocks,
        # differentiable both directions (parallel/ring_flash.py)
        from .ring_flash import ring_flash_self_attention
        ot = ring_flash_self_attention(qt, kt, vt, mesh, axis_name="sp",
                                       causal=cfg.causal)
    elif cfg.attn_mode == "ring" and mesh is not None:
        from .ring_attention import ring_self_attention
        ot = ring_self_attention(qt, kt, vt, mesh, axis_name="sp",
                                 causal=cfg.causal)
    elif cfg.attn_mode == "ulysses" and mesh is not None:
        from .ulysses import ulysses_attention
        ot = ulysses_attention(qt, kt, vt, mesh, axis_name="sp",
                               causal=cfg.causal)
    elif cfg.attn_mode == "blockwise":
        ot = blockwise_attention(qt, kt, vt, causal=cfg.causal)
    else:
        # local full attention: Pallas flash kernel on TPU (O(S·D) HBM
        # traffic), jnp reference elsewhere — see pallas_kernels/
        from ..pallas_kernels import flash_attention
        S = qt.shape[2]
        if S % 128 == 0:
            ot = flash_attention(qt, kt, vt, causal=cfg.causal)
        else:
            from ..pallas_kernels.flash_attention import attention_reference
            ot = attention_reference(qt, kt, vt, causal=cfg.causal)
    return jnp.transpose(ot, (0, 2, 1, 3))


def _layer_body(cfg, mesh, positions, x, lp):
    """One transformer layer. x: [B, S, D]; lp: this layer's params."""
    h = _rms_norm(x, lp["ln1"])
    q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"])
    q = jnp.transpose(_rope(jnp.transpose(q, (0, 2, 1, 3)), positions),
                      (0, 2, 1, 3))
    k = jnp.transpose(_rope(jnp.transpose(k, (0, 2, 1, 3)), positions),
                      (0, 2, 1, 3))
    o = _ckpt_name(_attention(cfg, mesh, q, k, v, positions), "attn_o")
    x = x + jnp.einsum("bshk,hkd->bsd", o, lp["wo"])
    h = _rms_norm(x, lp["ln2"])
    if cfg.num_experts > 0:
        y, aux = moe_ffn(h, lp["moe_router"], lp["moe_w1"], lp["moe_w2"],
                         k=cfg.moe_k)
        return x + y, aux
    g = jax.nn.silu(jnp.einsum("bsd,df->bsf", h, lp["w_gate"]))
    u = jnp.einsum("bsd,df->bsf", h, lp["w_up"])
    prod = _ckpt_name(g * u, "ffn_prod")
    return x + jnp.einsum("bsf,fd->bsd", prod, lp["w_down"]), 0.0


def apply(params, tokens, cfg: TransformerConfig, mesh=None,
          return_aux=False):
    """Forward: tokens [B, S] int32 -> logits [B, S, V]. GSPMD mode.
    With return_aux, also returns the summed MoE load-balance loss."""
    x, aux = _hidden(params, tokens, cfg, mesh)
    logits = jnp.einsum("bsd,dv->bsv", x, params["w_out"])
    if return_aux:
        return logits, aux
    return logits


def _remat_policy(cfg):
    """None = recompute everything; with cfg.remat_save, keep the named
    intermediates (save_only_these_names) so the backward skips their
    producers — selective remat, the memory/recompute dial."""
    if not cfg.remat_save:
        return None
    return jax.checkpoint_policies.save_only_these_names(*cfg.remat_save)


def _hidden(params, tokens, cfg, mesh):
    """Trunk forward up to (but excluding) the output projection;
    returns (x [B,S,D], summed aux)."""
    x = jnp.take(params["embed"], tokens, axis=0)
    positions = jnp.arange(tokens.shape[1])

    def body(x, lp):
        x, aux = _layer_body(cfg, mesh, positions, x, lp)
        return x, aux

    if cfg.remat:
        body = jax.checkpoint(body, policy=_remat_policy(cfg))
    x, auxs = lax.scan(body, x, params["layers"])
    return _rms_norm(x, params["ln_f"]), jnp.sum(auxs)


def _chunked_ce(x, w_out, targets, n_chunks):
    """Mean token NLL with the vocab projection done per sequence chunk.

    lax.map runs chunks sequentially, and jax.checkpoint makes the
    backward recompute each chunk's logits instead of saving them, so
    peak HBM holds ONE [B, S/n, V] f32 tile instead of the full
    [B, S, V] logits (2+ GB at 32k vocab, batch 8, seq 2048)."""
    B, S, D = x.shape
    C = S // n_chunks
    xc = jnp.swapaxes(x.reshape(B, n_chunks, C, D), 0, 1)
    tc = jnp.swapaxes(targets.reshape(B, n_chunks, C), 0, 1)

    @jax.checkpoint
    def chunk_nll(args):
        xi, ti = args
        logits = jnp.einsum("bcd,dv->bcv", xi, w_out,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, ti[..., None], axis=-1)[..., 0]
        return jnp.sum(lse - tgt)

    return jnp.sum(lax.map(chunk_nll, (xc, tc))) / (B * S)


def _chunked_ce_local(x, w_out, targets, n_chunks, mesh):
    """Chunked CE with LOCAL unembedding-gradient accumulation — the
    SCALING_r05 fix. The plain ``_chunked_ce`` under GSPMD keeps the
    ``dw_out`` all-reduce INSIDE the chunk loop (scan carries must hold
    a concrete sharding, so every chunk's batch-sharded partial sum is
    reduced before the add): (loss_chunks-1) extra vocab*dim reductions
    per step. Running the loop inside ``shard_map`` makes the partial
    sums per-device values no sharding rule touches; the chunk scan
    accumulates ``dw_out`` locally and the ONE reduction happens at the
    shard_map boundary (the transpose of w_out's replicated-over-dp/sp
    in_spec). With vocab sharded over 'tp', logsumexp and the target
    gather run distributed (pmax/psum over 'tp')."""
    from .compat import shard_map
    raw = getattr(mesh, "mesh", mesh)
    sizes = {a: int(s) for a, s in dict(raw.shape).items()}
    sp, tp = sizes.get("sp", 1), sizes.get("tp", 1)
    B, S, _ = x.shape
    if (S // sp) % n_chunks != 0:
        raise ValueError(
            "loss_chunks=%d does not divide the local sequence length "
            "%d (seq %d / sp %d)" % (n_chunks, S // sp, S, sp))

    def body(xl, wl, tl):
        b, s_l, d = xl.shape
        C = s_l // n_chunks
        xc = jnp.swapaxes(xl.reshape(b, n_chunks, C, d), 0, 1)
        tc = jnp.swapaxes(tl.reshape(b, n_chunks, C), 0, 1)
        Vl = wl.shape[-1]

        @jax.checkpoint
        def chunk_nll(args):
            xi, ti = args
            logits = jnp.einsum("bcd,dv->bcv", xi, wl,
                                preferred_element_type=jnp.float32)
            if tp > 1:
                # distributed logsumexp over the tp-sharded vocab; the
                # max shift is numerics-only (its gradient contribution
                # is exactly zero), so stop_gradient keeps it out of the
                # backward — pmax has no differentiation rule anyway
                m = lax.pmax(
                    lax.stop_gradient(jnp.max(logits, axis=-1)), "tp")
                s = lax.psum(
                    jnp.sum(jnp.exp(logits - m[..., None]), axis=-1),
                    "tp")
                lse = jnp.log(s) + m
                base = lax.axis_index("tp") * Vl
                loc = ti - base
                inb = (loc >= 0) & (loc < Vl)
                got = jnp.take_along_axis(
                    logits, jnp.clip(loc, 0, Vl - 1)[..., None],
                    axis=-1)[..., 0]
                tgt = lax.psum(jnp.where(inb, got, 0.0), "tp")
            else:
                lse = jax.nn.logsumexp(logits, axis=-1)
                tgt = jnp.take_along_axis(logits, ti[..., None],
                                          axis=-1)[..., 0]
            return jnp.sum(lse - tgt)

        total = jnp.sum(lax.map(chunk_nll, (xc, tc)))
        for ax in ("dp", "sp"):
            if sizes.get(ax, 1) > 1:
                total = lax.psum(total, ax)
        return total

    total = shard_map(
        body, raw,
        in_specs=(P("dp", "sp", None), P(None, "tp"), P("dp", "sp")),
        out_specs=P(), check_vma=False)(x, w_out, targets)
    return total / (B * S)


_WARNED = set()  # mxlint: disable=MX003 (warn-once dedup keys; worst case under a race is one duplicate warning)


def _warn_once(key, msg):
    if key in _WARNED:
        return
    _WARNED.add(key)
    warnings.warn(msg, RuntimeWarning, stacklevel=3)


def ce_local_accum_active(cfg, mesh, batch, seq):
    """Whether this (cfg, mesh, batch shape) runs the single-reduction
    chunked CE (``_chunked_ce_local``). ``cfg.ce_local_accum=None``
    AUTO-selects it whenever the mesh shards the batch (dp*sp > 1 — the
    only case the AR-per-chunk pattern costs wire bytes) and the shapes
    divide; an explicit ``True`` forces it (indivisible shapes keep the
    hard error from ``_chunked_ce_local``); ``False`` pins the plain
    path. ``MXTPU_CE_LOCAL_ACCUM`` ('auto' default / '1' / '0', a
    compile-signature token) is the process-wide override — before this
    auto-select, real trainer runs silently paid the +36%-at-256-chips
    wire bytes the local-accum fix already kills (SCALING_r05)."""
    if cfg.loss_chunks <= 1 or mesh is None:
        return False
    env = str(_getenv("MXTPU_CE_LOCAL_ACCUM", "auto")).lower()
    if env in ("0", "off", "false") or cfg.ce_local_accum is False:
        return False
    forced = cfg.ce_local_accum is True or env in ("1", "on", "true")
    sizes = {a: int(s)
             for a, s in dict(getattr(mesh, "mesh", mesh).shape).items()}
    dp, sp = sizes.get("dp", 1), sizes.get("sp", 1)
    if not forced and dp * sp <= 1:
        return False  # no batch-sharded partial sums -> nothing to save
    divisible = (int(batch) % max(dp, 1) == 0
                 and int(seq) % max(sp, 1) == 0
                 and (int(seq) // max(sp, 1)) % cfg.loss_chunks == 0)
    if not divisible and cfg.ce_local_accum is not True:
        # auto must not turn a shape quirk into a crash — but it also
        # must not SILENTLY hand back the AR-per-chunk bytes
        _warn_once(
            "ce-local-accum-indivisible",
            "ce_local_accum auto-select declined: batch=%d/seq=%d do "
            "not divide over dp=%d/sp=%d with loss_chunks=%d; this "
            "step pays the per-chunk unembedding-grad all-reduce "
            "(+(loss_chunks-1)*vocab*dim*4 wire bytes)"
            % (batch, seq, dp, sp, cfg.loss_chunks))
        return False
    return True


def loss_fn(params, tokens, targets, cfg, mesh=None, aux_weight=0.01):
    if cfg.loss_chunks > 1:
        if tokens.shape[1] % cfg.loss_chunks != 0:
            # a silent full-logits fallback would re-materialize the
            # [B,S,V] tensor loss_chunks exists to avoid (and OOM)
            raise ValueError(
                "loss_chunks=%d does not divide seq_len=%d; pick a "
                "divisor or set loss_chunks=1"
                % (cfg.loss_chunks, tokens.shape[1]))
        x, aux = _hidden(params, tokens, cfg, mesh)
        if ce_local_accum_active(cfg, mesh, tokens.shape[0],
                                 tokens.shape[1]):
            loss = _chunked_ce_local(x, params["w_out"], targets,
                                     cfg.loss_chunks, mesh)
        else:
            loss = _chunked_ce(x, params["w_out"], targets,
                               cfg.loss_chunks)
    else:
        logits, aux = apply(params, tokens, cfg, mesh, return_aux=True)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        loss = -jnp.mean(ll)
    if cfg.num_experts > 0:
        loss = loss + aux_weight * aux  # GShard load-balance pressure
    return loss


# --------------------------------------------------------------------------
# Explicit SPMD mode (pp > 1): whole step inside one shard_map
# --------------------------------------------------------------------------

def _layer_body_local(cfg, positions, x, lp):
    """Per-device layer body used inside shard_map: tp dims of lp are LOCAL
    shards; row-parallel outputs need psum over 'tp'. Sequence dim of x is
    the local 'sp' shard; attention uses the ppermute ring."""
    h = _rms_norm(x, lp["ln1"])
    q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"])
    q = jnp.transpose(_rope(jnp.transpose(q, (0, 2, 1, 3)), positions),
                      (0, 2, 1, 3))
    kq = jnp.transpose(_rope(jnp.transpose(k, (0, 2, 1, 3)), positions),
                       (0, 2, 1, 3))
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(kq, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    ot = ring_attention(qt, kt, vt, "sp", causal=cfg.causal,
                        q_offset=positions[0])
    o = jnp.transpose(ot, (0, 2, 1, 3))
    attn_out = lax.psum(jnp.einsum("bshk,hkd->bsd", o, lp["wo"]), "tp")
    x = x + attn_out
    h = _rms_norm(x, lp["ln2"])
    g = jax.nn.silu(jnp.einsum("bsd,df->bsf", h, lp["w_gate"]))
    u = jnp.einsum("bsd,df->bsf", h, lp["w_up"])
    ffn_out = lax.psum(jnp.einsum("bsf,fd->bsd", g * u, lp["w_down"]), "tp")
    return x + ffn_out


def _pipeline_forward_local(cfg, params, tokens):
    """Inside shard_map over (pp, dp, sp, tp). tokens: [B_local, S_local].
    GPipe fill-drain over microbatches (pipeline.gpipe_loop); activations
    rotate over 'pp'."""
    from .pipeline import gpipe_loop
    sp_idx = lax.axis_index("sp")
    B, S_local = tokens.shape
    M = cfg.n_microbatch
    assert B % M == 0
    mb = B // M
    positions = sp_idx * S_local + jnp.arange(S_local)

    x_all = jnp.take(params["embed"], tokens, axis=0)       # [B, S_l, D]
    x_mb = x_all.reshape(M, mb, S_local, cfg.dim)

    stage_params = jax.tree_util.tree_map(lambda p: p[0], params["layers"])

    def stage_fn(x):
        def body(x, lp):
            return _layer_body_local(cfg, positions, x, lp), None
        x, _ = lax.scan(body, x, stage_params)
        return x

    outs = gpipe_loop(stage_fn, x_mb, "pp")
    x = outs.reshape(B, S_local, cfg.dim)
    x = _rms_norm(x, params["ln_f"])
    logits = jnp.einsum("bsd,dv->bsv", x, params["w_out"])
    return logits


def _pipeline_loss_local(cfg, params, tokens, targets):
    logits = _pipeline_forward_local(cfg, params, tokens)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    # mean over local tokens, then over dp & sp shards
    return lax.pmean(lax.pmean(jnp.mean(ll), "dp"), "sp") * -1.0


# --------------------------------------------------------------------------
# Train-step builders
# --------------------------------------------------------------------------

def make_train_step(cfg: TransformerConfig, mesh, learning_rate=1e-3):
    """Return (init_fn, step_fn).

    init_fn(key) -> (params, opt_state) placed on the mesh.
    step_fn(state, tokens, targets) -> (state, loss): one fused SGD-momentum
    update. GSPMD mode when cfg.pp == 1, explicit shard_map mode otherwise.
    """
    raw_mesh = getattr(mesh, "mesh", mesh)
    specs = param_specs(cfg)

    def _sharding(spec_tree):
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(raw_mesh, s), spec_tree,
            is_leaf=lambda l: isinstance(l, P))

    param_sh = _sharding(specs)

    def init_fn(key):
        params = init_params(key, cfg)
        params = jax.tree_util.tree_map(
            lambda v, sh: jax.device_put(v, sh), params, param_sh)
        momentum = jax.tree_util.tree_map(jnp.zeros_like, params)
        return params, momentum

    if cfg.pp == 1:
        def loss_of(params, tokens, targets):
            return loss_fn(params, tokens, targets, cfg, mesh)

        batch_sh = NamedSharding(raw_mesh, P("dp", "sp"))

        @functools.partial(
            jax.jit,  # mxlint: disable=MX022 (benchmark/verification harness: callers AOT-compile the step and account inventories explicitly via comm_model)
            in_shardings=((param_sh, param_sh), batch_sh, batch_sh),
            out_shardings=((param_sh, param_sh), None),
            donate_argnums=(0,))
        def step_fn(state, tokens, targets):
            params, mom = state
            loss, grads = jax.value_and_grad(loss_of)(params, tokens,
                                                      targets)
            new_mom = jax.tree_util.tree_map(
                lambda m, g: 0.9 * m + g, mom, grads)
            new_params = jax.tree_util.tree_map(
                lambda p, m: p - learning_rate * m, params, new_mom)
            return (new_params, new_mom), loss
    else:
        from .compat import shard_map
        data_spec = P("dp", "sp")

        def spmd_step(params, mom, tokens, targets):
            def loss_of(ps):
                return _pipeline_loss_local(cfg, ps, tokens, targets)

            loss, grads = jax.value_and_grad(loss_of)(params)
            # grads of replicated params need reduction over dp/sp
            # (shard_map grads are per-device partials on replicated leaves)
            def reduce_grad(g, spec):
                # replicated-axis partial grads must be summed; grads of
                # leaves sharded on an axis are already that shard's grad.
                # 'pp' matters for embed/w_out/ln_f: only one stage touches
                # them, the others contribute zero
                axes = [a for a in ("dp", "sp", "tp", "pp")
                        if not _spec_mentions(spec, a)]
                for a in axes:
                    g = lax.psum(g, a)
                return g

            grads = jax.tree_util.tree_map(
                reduce_grad, grads, specs,
                is_leaf=lambda l: hasattr(l, "shape"))
            new_mom = jax.tree_util.tree_map(
                lambda m, g: 0.9 * m + g, mom, grads)
            new_params = jax.tree_util.tree_map(
                lambda p, m: p - learning_rate * m, params, new_mom)
            loss = lax.pmean(lax.pmean(loss, "dp"), "sp")
            return new_params, new_mom, loss

        smapped = shard_map(
            spmd_step, mesh=raw_mesh,
            in_specs=(specs, specs, data_spec, data_spec),
            out_specs=(specs, specs, P()), check_vma=False)

        @jax.jit  # mxlint: disable=MX005,MX022 (one pp-mode train step per make_train_step call, AOT-compiled and inventoried by the bench harness; config and mesh are frozen into the closure, single key)
        def step_fn(state, tokens, targets):
            params, mom = state
            new_params, new_mom, loss = smapped(params, mom, tokens, targets)
            return (new_params, new_mom), loss

    return init_fn, step_fn


def _spec_mentions(spec, axis):
    for part in spec:
        if part == axis:
            return True
        if isinstance(part, (tuple, list)) and axis in part:
            return True
    return False
