"""Ring x flash attention: differentiable ring attention whose per-hop
block products run on the Pallas flash kernels.

Completes the two-level scheme the kernel docstring promises
(pallas_kernels/flash_attention.py): inter-chip, K/V blocks rotate
around the mesh axis with `ppermute` (ring attention, Liu et al.
arXiv:2310.01889); intra-chip, each hop's [B,H,Sq,Sk] block product is
the Pallas flash kernel instead of a dense einsum, so per-hop HBM stays
O(S_local*D) in BOTH directions:

- forward: each hop returns its block's normalized output o_i and row
  logsumexp lse_i; partials merge as o = sum_i o_i * exp(lse_i - lse)
  with lse = logsumexp_i(lse_i) — the standard two-level flash merge.
- backward: a second ring pass. delta = sum(dO*O) and the GLOBAL lse
  are per-query quantities, so each hop can run the flash-2 dq/dk/dv
  kernels (_pallas_backward) directly with them: dq accumulates
  locally, dk/dv accumulate in buffers that ride the ring home.

Causality is per-hop block structure: a kv block from an earlier ring
position attends fully, the diagonal block attends causally, later
blocks are skipped (both compute AND, in the backward, their zero
grads).

Off-TPU the flash calls fall back to the dense reference (and this
module's tests run the Pallas kernels in interpret mode), so numerics
are verified on the CPU mesh against plain ring attention.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..pallas_kernels.flash_attention import (_pallas_forward,
                                              _pallas_backward,
                                              attention_reference,
                                              _use_pallas)

__all__ = ["ring_flash_attention"]


def _block_fwd(q, k, v, scale, causal, interpret):
    """One hop's flash forward -> (o, lse[B,H,S]) on the local block."""
    if interpret or _use_pallas():
        o, lse = _pallas_forward(q, k, v, causal, scale, 1024, 1024,
                                 interpret)
        B, H, S, D = q.shape
        return o, lse[:, :, 0].reshape(B, H, S)
    # dense fallback with an explicit lse (off-TPU path)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        S, Sk = s.shape[-2], s.shape[-1]
        row = lax.broadcasted_iota(jnp.int32, (S, Sk), 0)
        col = lax.broadcasted_iota(jnp.int32, (S, Sk), 1)
        s = jnp.where(col > row, -jnp.inf, s)
    m = jnp.max(s, axis=-1)
    msafe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(s - msafe[..., None])
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    l = p.sum(-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v) \
        / jnp.maximum(l, 1e-30)[..., None].astype(v.dtype)
    lse = jnp.where(l == 0.0, -jnp.inf, msafe + jnp.log(
        jnp.maximum(l, 1e-30)))
    return o.astype(q.dtype), lse


def _block_bwd(q, k, v, o, lse, g, scale, causal, interpret):
    """One hop's flash backward with the GLOBAL lse (and delta derived
    from the global o/do) -> (dq, dk, dv) for this block pair."""
    if interpret or _use_pallas():
        return _pallas_backward(q, k, v, o, lse.reshape(-1, lse.shape[-1]),
                                g, causal, scale, 1024, 1024, interpret)
    # dense fallback mirroring the flash-2 formulation
    B, H, S, D = q.shape
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        Sq, Sk = s.shape[-2], s.shape[-1]
        row = lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        col = lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where(col > row, -jnp.inf, s)
    lse_safe = jnp.where(jnp.isneginf(lse), 0.0, lse)
    p = jnp.exp(s - lse_safe[..., None])
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), -1)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g.astype(jnp.float32),
                    v.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, g.astype(jnp.float32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _merge(o_a, lse_a, o_b, lse_b):
    """Two-level flash merge of normalized partials. The accumulator
    side (o_a) stays f32 across hops; only the final result is cast."""
    lse = jnp.logaddexp(lse_a, lse_b)
    lse_safe = jnp.where(jnp.isneginf(lse), 0.0, lse)
    wa = jnp.where(jnp.isneginf(lse_a), 0.0,
                   jnp.exp(lse_a - lse_safe))
    wb = jnp.where(jnp.isneginf(lse_b), 0.0,
                   jnp.exp(lse_b - lse_safe))
    o = o_a.astype(jnp.float32) * wa[..., None] \
        + o_b.astype(jnp.float32) * wb[..., None]
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_flash_attention(q, k, v, axis_name, causal=False, scale=None,
                         interpret=False):
    """Inside shard_map over `axis_name`: q/k/v [B, H, S_local, D],
    sequence-sharded; exact attention over the global sequence with
    per-hop flash blocks."""
    o, _lse = _ring_fwd_impl(q, k, v, axis_name, causal, scale, interpret)
    return o


def _ring_fwd_impl(q, k, v, axis_name, causal, scale, interpret):
    B, H, S, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # f32 accumulator across hops — rounding once at the end instead of
    # per hop keeps bf16 numerics at the dense reference's error level
    o0 = jnp.zeros((B, H, S, D), jnp.float32)
    lse0 = jnp.full((B, H, S), -jnp.inf, jnp.float32)

    def hop(carry, _):
        o_acc, lse_acc, kk, vv, kv_idx = carry
        if causal:
            # earlier ring position: full; same: diagonal; later: skip
            def full_case(_):
                return _block_fwd(q, kk, vv, scale, False, interpret)

            def diag_case(_):
                return _block_fwd(q, kk, vv, scale, True, interpret)

            def skip_case(_):
                # must match the flash branches' output dtype for switch
                return (jnp.zeros((B, H, S, D), q.dtype),
                        jnp.full((B, H, S), -jnp.inf, jnp.float32))

            branch = jnp.where(kv_idx < my_idx, 0,
                               jnp.where(kv_idx == my_idx, 1, 2))
            o_i, lse_i = lax.switch(branch,
                                    [full_case, diag_case, skip_case],
                                    None)
        else:
            o_i, lse_i = _block_fwd(q, kk, vv, scale, False, interpret)
        o_acc, lse_acc = _merge(o_acc, lse_acc, o_i, lse_i)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        kv_idx = lax.ppermute(kv_idx, axis_name, perm)
        return (o_acc, lse_acc, kk, vv, kv_idx), None

    (o, lse, _, _, _), _ = lax.scan(hop, (o0, lse0, k, v, my_idx), None,
                                    length=n)
    return o.astype(q.dtype), lse


def _ring_fwd(q, k, v, axis_name, causal, scale, interpret):
    o, lse = _ring_fwd_impl(q, k, v, axis_name, causal, scale, interpret)
    return o, (q, k, v, o, lse)


def _ring_bwd(axis_name, causal, scale, interpret, res, g):
    q, k, v, o, lse = res
    B, H, S, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    dq0 = jnp.zeros_like(q, jnp.float32)
    zero_kv = jnp.zeros((B, H, S, D), jnp.float32)

    def hop(carry, _):
        dq_acc, dk_ring, dv_ring, kk, vv, kv_idx = carry
        if causal:
            def full_case(_):
                return _block_bwd(q, kk, vv, o, lse, g, scale, False,
                                  interpret)

            def diag_case(_):
                return _block_bwd(q, kk, vv, o, lse, g, scale, True,
                                  interpret)

            def skip_case(_):
                return (jnp.zeros_like(q), jnp.zeros_like(kk),
                        jnp.zeros_like(vv))

            branch = jnp.where(kv_idx < my_idx, 0,
                               jnp.where(kv_idx == my_idx, 1, 2))
            dq_i, dk_i, dv_i = lax.switch(
                branch, [full_case, diag_case, skip_case], None)
        else:
            dq_i, dk_i, dv_i = _block_bwd(q, kk, vv, o, lse, g, scale,
                                          False, interpret)
        dq_acc = dq_acc + dq_i.astype(jnp.float32)
        # dk/dv for THIS kv block accumulate into the rotating buffers;
        # after n hops each buffer has visited every device exactly once
        # and arrives back at the block's home position
        dk_ring = dk_ring + dk_i.astype(jnp.float32)
        dv_ring = dv_ring + dv_i.astype(jnp.float32)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        dk_ring = lax.ppermute(dk_ring, axis_name, perm)
        dv_ring = lax.ppermute(dv_ring, axis_name, perm)
        kv_idx = lax.ppermute(kv_idx, axis_name, perm)
        return (dq_acc, dk_ring, dv_ring, kk, vv, kv_idx), None

    carry = (dq0, zero_kv, zero_kv, k, v, my_idx)
    (dq, dk, dv, _, _, _), _ = lax.scan(hop, carry, None, length=n)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


ring_flash_attention.defvjp(_ring_fwd, _ring_bwd)


def ring_flash_self_attention(q, k, v, mesh, axis_name="sp", causal=False,
                              scale=None, batch_axis="dp", head_axis="tp",
                              interpret=False):
    """shard_map wrapper over full [B, H, S, D] arrays (mirrors
    ring_attention.ring_self_attention) — the single place that owns the
    spec/mesh wiring for the ring x flash path."""
    from .compat import shard_map
    from .compat import PartitionSpec as P
    spec = P(batch_axis, head_axis, axis_name, None)

    def fn(a, b, c):
        # custom_vjp args must be positional (nondiff_argnums)
        return ring_flash_attention(a, b, c, axis_name, causal, scale,
                                    interpret)

    return shard_map(fn, mesh=getattr(mesh, "mesh", mesh),
                     in_specs=(spec, spec, spec), out_specs=spec,
                     check_vma=False)(q, k, v)
