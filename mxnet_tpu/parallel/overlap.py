"""Bucketed, backward-overlapped gradient reduction.

SCALING_r05's headline: 256-chip efficiency is 84.5% with ZERO
comm/compute overlap and ~100% once the gradient all-reduce hides under
the backward pass — the standard the reference's own 90.1%@256 number
assumes (ref: example/image-classification/README.md:309). GSPMD's
default lowering emits one all-reduce per gradient *after* the whole
backward; this module restores the DDP overlap structure explicitly:

- ``bucket_plan(leaves)`` groups gradients into size-capped,
  dtype-homogeneous buckets (``MXTPU_ELASTIC_BUCKET_MB``, default 4).
- ``tag_gradient_buckets(leaves, axis_name)`` wraps each bucket's
  parameters in a ``custom_vjp`` identity whose backward concatenates
  the bucket's cotangents and issues ONE ``lax.psum``/``pmean`` —
  *at the point in the backward graph where the bucket's last gradient
  is produced*. The reduction is therefore data-ready mid-backward and
  XLA's async-collective machinery (``all-reduce-start``/``-done``,
  what ``benchmark/comm_model.py`` counts) can run it under the
  remaining backward compute instead of serializing after it.
- ``bucketed_reduce(leaves, axis_name)`` is the post-backward form
  (concat → one collective per bucket → split), for callers that
  already hold grads.

Both forms require an explicit mesh axis name, i.e. a ``shard_map``
context (``parallel/compat.py``); under plain GSPMD jit there is no
axis name to reduce over. ``parallel/train.py`` (ShardedTrainStep) and
``gluon/fused_step.py`` (mesh= form) wire them into the train steps.

Numerics: a bucketed reduce computes exactly ``psum(g)`` per leaf —
concatenation does not mix leaves, only batches wire messages — so
results match the unbucketed reduction bitwise on the same topology.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import profiler as _profiler
from ..base import getenv as _getenv

__all__ = ["bucket_plan", "tag_gradient_buckets", "bucketed_reduce",
           "default_bucket_bytes"]


def default_bucket_bytes():
    """Size cap per bucket, from ``MXTPU_ELASTIC_BUCKET_MB`` (default 4
    MiB — large enough to amortize collective latency, small enough
    that the first reduction launches early in the backward)."""
    mb = float(_getenv("MXTPU_ELASTIC_BUCKET_MB", "4"))
    return max(1, int(mb * (1 << 20)))


def bucket_plan(leaves, bucket_bytes=None):
    """Group leaf indices into reduction buckets.

    ``leaves``: arrays (or anything with ``.nbytes``/``.dtype``).
    Returns a list of index lists, preserving leaf order inside each
    bucket. Buckets are dtype-homogeneous (one flat concatenated wire
    message per bucket) and size-capped at ``bucket_bytes``; a single
    leaf larger than the cap gets its own bucket. Leaf order follows
    the forward traversal — the backward produces the LAST bucket's
    gradients first, so reductions fire newest-bucket-first, each as
    soon as its segment of the backward completes.
    """
    if bucket_bytes is None:
        bucket_bytes = default_bucket_bytes()
    plan = []
    cur, cur_bytes, cur_dtype = [], 0, None
    for i, leaf in enumerate(leaves):
        nbytes = int(getattr(leaf, "nbytes", 0) or
                     jnp.dtype(leaf.dtype).itemsize *
                     int(np.prod(leaf.shape)))
        dtype = jnp.dtype(leaf.dtype)
        if cur and (cur_dtype != dtype
                    or cur_bytes + nbytes > bucket_bytes):
            plan.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_dtype = dtype
    if cur:
        plan.append(cur)
    # host-side accounting (plan construction happens at build/trace
    # time, never per step): how the reduction was batched
    _profiler.account("overlap.buckets_planned", len(plan), emit=False)
    _profiler.account("overlap.leaves_planned", len(leaves), emit=False)
    return plan


def _reduce_flat(flat, axis_name, op):
    if axis_name is None:
        # GSPMD form (fused_step 3D mesh mode): there is no manual axis
        # to reduce over — the SPMD partitioner owns the collective. The
        # marker still concatenates the bucket's cotangents into ONE
        # flat segment, so the partitioner's reduction lands on the
        # bucket, not leaf-by-leaf, preserving the DDP wire batching.
        return flat
    if op == "mean":
        return lax.pmean(flat, axis_name)
    if op == "sum":
        return lax.psum(flat, axis_name)
    raise ValueError("overlap reduce op must be 'sum' or 'mean', got %r"
                     % (op,))


def _tag_group(group, axis_name, op):
    """custom_vjp identity over one bucket: forward passes the arrays
    through untouched; backward fires when EVERY cotangent in the
    bucket is available (i.e. right after the bucket's earliest-used
    parameter gets its gradient — DDP bucket semantics) and reduces
    them as one flat collective."""
    shapes = [x.shape for x in group]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]

    @jax.custom_vjp
    def ident(*xs):
        return xs

    def fwd(*xs):
        return xs, None

    def bwd(_, cts):
        flat = jnp.concatenate([jnp.ravel(c) for c in cts]) \
            if len(cts) > 1 else jnp.ravel(cts[0])
        red = _reduce_flat(flat, axis_name, op)
        out, off = [], 0
        for shape, size in zip(shapes, sizes):
            out.append(jnp.reshape(red[off:off + size], shape))
            off += size
        return tuple(out)

    ident.defvjp(fwd, bwd)
    return ident(*group)


def tag_gradient_buckets(leaves, axis_name, plan=None, bucket_bytes=None,
                         op="sum"):
    """Return ``leaves`` wrapped in per-bucket gradient-reduction
    markers (see module docstring). Use on the parameter leaves BEFORE
    the forward inside a ``shard_map``; gradients w.r.t. the original
    leaves come back fully reduced over ``axis_name``, one collective
    per bucket, placed mid-backward. ``axis_name=None`` is the GSPMD
    form (plain jit with shardings, no manual axis): the markers keep
    the bucket STRUCTURE — cotangents concatenate into flat per-bucket
    segments mid-backward — while the SPMD partitioner supplies the
    reduction itself."""
    leaves = list(leaves)
    if plan is None:
        plan = bucket_plan(leaves, bucket_bytes)
    out = list(leaves)
    for bucket in plan:
        tagged = _tag_group([leaves[i] for i in bucket], axis_name, op)
        for i, t in zip(bucket, tagged):
            out[i] = t
    return out


def bucketed_reduce(leaves, axis_name, plan=None, bucket_bytes=None,
                    op="sum"):
    """Reduce already-computed gradient leaves over ``axis_name``, one
    flat collective per bucket (the post-backward form — no overlap
    structure, but the same wire batching)."""
    leaves = list(leaves)
    if plan is None:
        plan = bucket_plan(leaves, bucket_bytes)
    out = list(leaves)
    for bucket in plan:
        group = [leaves[i] for i in bucket]
        if len(group) == 1:
            out[bucket[0]] = _reduce_flat(group[0], axis_name, op)
            continue
        shapes = [g.shape for g in group]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        red = _reduce_flat(
            jnp.concatenate([jnp.ravel(g) for g in group]),
            axis_name, op)
        off = 0
        for i, shape, size in zip(bucket, shapes, sizes):
            out[i] = jnp.reshape(red[off:off + size], shape)
            off += size
    return out
