"""Parallelism: device meshes, collectives, sharding strategies.

TPU-native replacement for the reference's entire distributed stack
(ref: src/kvstore/ comm.h/comm_tree.h/kvstore_nccl.h/kvstore_dist.h,
3rdparty/ps-lite): instead of reduction trees, NCCL calls and a ZMQ
parameter server, ONE device mesh (`jax.sharding.Mesh`) carries every
strategy as a sharding spec, and XLA inserts the ICI/DCN collectives:

- data parallel        ≙ kvstore local/device/nccl/dist_sync  → psum over 'dp'
- ZeRO/FSDP            ≙ server-held optimizer state          → shard over 'fsdp'
- tensor parallel      ≙ (not in reference)                   → shard over 'tp'
- pipeline parallel    ≙ group2ctx model parallelism          → stages over 'pp'
- sequence/context par ≙ (not in reference; BucketingModule)  → ring attention over 'sp'
- expert parallel      ≙ (not in reference)                   → MoE over 'ep'

See SURVEY.md §2.4 and §5 "distributed communication backend".
"""
from .compat import shard_map
from .mesh import (DeviceMesh, create_mesh, current_mesh, default_mesh_axes,
                   mesh_scope, surviving_devices, shrink_mesh)
from .collectives import (all_reduce, all_gather, reduce_scatter, all_to_all,
                          ppermute, ring_exchange, host_allreduce,
                          host_barrier, num_hosts, host_rank,
                          initialize_distributed)
from .sharding import (ShardingStrategy, PartitionRules, data_parallel,
                       fsdp, tensor_parallel, make_param_sharding,
                       infer_rules_for_block, host_array, relayout_params)
from .overlap import (bucket_plan, tag_gradient_buckets, bucketed_reduce,
                      default_bucket_bytes)
from .ring_attention import ring_attention, ring_self_attention, \
    blockwise_attention
from .ulysses import ulysses_attention
from .pipeline import pipeline_stages, PipelineStage
from .expert import MoELayer, top_k_routing
from .train import ShardedTrainStep, functional_call, extract_params, \
    attach_params
from .elastic import (CheckpointManager, elastic_train_loop,
                      PreemptionGuard, ElasticController, HostGradReducer,
                      ReshardRequired, shard_for_rank)
from . import transformer

__all__ = [
    "shard_map",
    "DeviceMesh", "create_mesh", "current_mesh", "default_mesh_axes",
    "mesh_scope", "surviving_devices", "shrink_mesh",
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all", "ppermute",
    "ring_exchange", "host_allreduce", "host_barrier", "num_hosts",
    "host_rank", "initialize_distributed",
    "ShardingStrategy", "PartitionRules", "data_parallel", "fsdp",
    "tensor_parallel", "make_param_sharding", "infer_rules_for_block",
    "host_array", "relayout_params",
    "bucket_plan", "tag_gradient_buckets", "bucketed_reduce",
    "default_bucket_bytes",
    "ring_attention", "ring_self_attention", "blockwise_attention",
    "ulysses_attention",
    "pipeline_stages", "PipelineStage",
    "MoELayer", "top_k_routing",
    "ShardedTrainStep", "functional_call", "extract_params", "attach_params",
    "CheckpointManager", "elastic_train_loop", "PreemptionGuard",
    "ElasticController", "HostGradReducer", "ReshardRequired",
    "shard_for_rank",
    "transformer",
]
