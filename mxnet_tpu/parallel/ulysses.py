"""Ulysses sequence parallelism: all-to-all head<->sequence resharding.

Alternative context-parallel scheme to ring attention (DeepSpeed-Ulysses,
arXiv:2309.14509): instead of rotating K/V, ONE all-to-all converts
sequence-sharded projections [B, H, S/n, D] into head-sharded full-sequence
tensors [B, H/n, S, D]; attention is then purely local per head group, and a
second all-to-all restores sequence sharding. On TPU the all-to-all lowers
to an ICI all-to-all, efficient on the torus. Requires n_heads % n == 0.
"""
from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax
from .compat import PartitionSpec as P

__all__ = ["ulysses_attention"]


def _local_attention(q, k, v, causal, scale, q_offset=0):
    B, H, S, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if q_offset == 0:
        # after the all-to-all each device holds FULL sequences for its
        # head group — plain self-attention, so the Pallas flash kernel
        # (fwd + flash-2 bwd, O(S*D) HBM) applies directly; it falls
        # back to the dense reference off-TPU. This is the two-level
        # composition (inter-chip all-to-all x intra-chip flash) that
        # makes Ulysses the preferred long-context mode on TPU.
        from ..pallas_kernels import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qp = q_offset + jnp.arange(S)
        kp = jnp.arange(k.shape[2])
        s = jnp.where(qp[:, None] >= kp[None, :], s, -jnp.inf)
    return jnp.einsum("bhqk,bhkd->bhqd", _softmax(s), v)


def _softmax(s):
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.where(jnp.isneginf(m), 0.0, m)
    e = jnp.exp(s - m)
    e = jnp.where(jnp.isneginf(s), 0.0, e)
    return e / jnp.maximum(e.sum(axis=-1, keepdims=True), 1e-30)


def ulysses_attention_local(q, k, v, axis_name, causal=False, scale=None):
    """Inside shard_map: q/k/v [B, H, S_local, D] sequence-sharded on
    `axis_name` → out [B, H, S_local, D]."""
    # [B,H,S/n,D] -> all2all over heads -> [B,H/n,S,D]
    q = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    o = _local_attention(q, k, v, causal, scale)
    # [B,H/n,S,D] -> back to sequence-sharded [B,H,S/n,D]
    return lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_attention(q, k, v, mesh, axis_name="sp", causal=False,
                      scale=None, batch_axis="dp", head_axis="tp"):
    """shard_map wrapper over full [B, H, S, D] arrays."""
    from .compat import shard_map
    spec = P(batch_axis, head_axis, axis_name, None)
    fn = functools.partial(ulysses_attention_local, axis_name=axis_name,
                           causal=causal, scale=scale)
    return shard_map(fn, mesh=getattr(mesh, "mesh", mesh),
                     in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)(q, k, v)
