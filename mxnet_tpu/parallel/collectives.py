"""Collective primitives.

TPU-native replacement for the reference's three comm backends — intra-node
reduce trees (ref: src/kvstore/comm.h:451, comm_tree.h:50), NCCL
(ref: src/kvstore/kvstore_nccl.h:285 ncclReduce / :402 ncclBcast) and the
ps-lite parameter server (ref: src/kvstore/kvstore_dist.h:209 PushPullImpl).
Every function here lowers to ONE XLA collective over a named mesh axis;
XLA routes it over ICI within a slice and DCN across slices.

These are usable both inside `shard_map` (explicit SPMD) and, for the
psum-style ones, under plain `jit` with sharded inputs (GSPMD inserts the
collective automatically — the preferred path).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax
from ..base import getenv as _getenv

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "all_to_all",
           "ppermute", "ring_exchange", "host_allreduce", "host_barrier",
           "num_hosts", "host_rank", "initialize_distributed"]


def all_reduce(x, axis_name, op="sum"):
    """psum/pmax/pmin/pmean over a mesh axis.

    ≙ the whole push+pull of kvstore sync (ref: kvstore_dist.h PushPull):
    one fused ICI allreduce instead of reduce-to-root + broadcast.
    """
    from .._debug import faultpoint as _faultpoint
    if _faultpoint.ACTIVE:
        # fires at trace/launch time (inside shard_map bodies this is
        # the trace of the program that will carry the collective) —
        # the injection seam for "a failed collective surfaces as an
        # exception" (ISSUE 7); the per-call runtime seam is
        # elastic.HostGradReducer's check of the same point
        _faultpoint.check("collective.allreduce")
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError("unknown reduce op %r" % op)


def all_gather(x, axis_name, axis=0, tiled=True):
    """Gather shards along `axis` from every member of the mesh axis."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    """Sum-reduce then scatter shards — the ZeRO gradient primitive
    (≙ server-sharded keys, ref: kvstore_dist.h:263 EncodeDefaultKey)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    """Transpose shard ownership (Ulysses seq<->head swap primitive)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def ppermute(x, axis_name, perm):
    """Point-to-point shifts along a mesh axis (ring attention primitive)."""
    return lax.ppermute(x, axis_name, perm)


def ring_exchange(x, axis_name, shift=1):
    """Shift shards around the ring by `shift` (rides neighbor ICI links)."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


# -- host-level (multi-process) coordination --------------------------------
# The reference coordinates worker processes through the ps-lite scheduler +
# tracker env vars (DMLC_ROLE/DMLC_NUM_WORKER..., ref: tools/launch.py). The
# jax.distributed runtime plays that role here; in single-process runs every
# helper degrades to the identity.

def num_hosts():
    return jax.process_count()


def host_rank():
    return jax.process_index()


def host_allreduce(arrays):
    """Cross-process sum of host numpy/NDArray values.

    ≙ dist_sync push+pull aggregation on the server
    (ref: kvstore_dist_server.h:346 ApplyUpdates waits for NumWorkers).
    Implemented as a tiny jitted psum over the global device set.
    """
    from .._debug import faultpoint as _faultpoint
    if _faultpoint.ACTIVE:
        _faultpoint.check("collective.allreduce")
    if jax.process_count() == 1:
        return arrays
    import numpy as _np
    from jax.experimental import multihost_utils
    single = not isinstance(arrays, (list, tuple))
    seq = [arrays] if single else list(arrays)
    # stage through host numpy: device arrays committed by a jitted step
    # cannot be re-staged into the global allgather array directly
    out = [multihost_utils.process_allgather(_np.asarray(a)).sum(axis=0)
           for a in seq]
    return out[0] if single else out


def host_barrier(name="mxnet_tpu_barrier"):
    """≙ ps::Postoffice::Barrier (ref: kvstore_dist.h:106)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None):
    """Bring up the multi-process runtime (≙ the DMLC_* env handshake,
    ref: src/kvstore/kvstore_dist.h:50 ps::KVWorker setup). Reads
    MXTPU_COORDINATOR / MXTPU_NUM_PROCS / MXTPU_PROC_ID when args omitted."""
    coordinator_address = coordinator_address or _getenv(
        "MXTPU_COORDINATOR")
    if coordinator_address is None:
        return False
    if num_processes is None:
        num_processes = _getenv("MXTPU_NUM_PROCS", 1)
    if process_id is None:
        process_id = _getenv("MXTPU_PROC_ID", 0)
    jax.distributed.initialize(coordinator_address, int(num_processes),
                               int(process_id))
    return True
