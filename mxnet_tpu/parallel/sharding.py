"""Sharding strategies: parameter partition rules over the mesh.

The strategy object plays the role the kvstore *type string* plays in the
reference ("local"/"device"/"nccl"/"dist_sync", ref: src/kvstore/kvstore.cc:40):
it names HOW state and compute are distributed. Here a strategy is data — a
list of (param-path regex, PartitionSpec) rules plus batch/activation specs —
and GSPMD compiles it, instead of each mode being a separate C++ backend.

``match_partition_rules`` maps a whole parameter pytree ('/'-joined key
paths, first matching regex wins, scalars replicated) to a PartitionSpec
tree — the EasyLM/levanter idiom — including stacked ``[L, ...]`` layer
trees, where a rule written for the per-layer shape applies with the
scanned leading axis replicated. Specs are always fitted to the array:
trimmed to rank, and any mesh axis that does not divide its dimension is
dropped (GSPMD would pad; the fused-step contract is divide-or-replicate
so wire bytes stay analytic).
"""
from __future__ import annotations

import re

import jax

from .compat import NamedSharding, PartitionSpec as P

__all__ = ["PartitionRules", "ShardingStrategy", "data_parallel", "fsdp",
           "tensor_parallel", "make_param_sharding", "infer_rules_for_block",
           "host_array", "relayout_params", "match_partition_rules",
           "named_shardings"]


class PartitionRules:
    """Ordered (regex, PartitionSpec) rules; first match wins.

    Analog of the reference's per-key sharding decisions in
    EncodeDefaultKey (ref: src/kvstore/kvstore_dist.h:263) — but declarative
    and per-parameter-path instead of hashed key ranges.
    """

    def __init__(self, rules=()):
        self.rules = [(re.compile(pat), P(*spec) if isinstance(spec, tuple)
                       else spec) for pat, spec in rules]

    def spec_for(self, path, shape=None, mesh=None):
        for pat, spec in self.rules:
            if pat.search(path):
                if shape is not None:
                    spec = _fit_spec(spec, shape, mesh)
                return spec
        return P()

    def describe(self):
        """[(pattern, spec)] — the rule table, for docs/fingerprints
        (the fused step folds this into its cache signature)."""
        return tuple((pat.pattern, tuple(spec)) for pat, spec in self.rules)

    def __add__(self, other):
        out = PartitionRules()
        out.rules = list(self.rules) + list(other.rules)
        return out


def _mesh_sizes(mesh):
    """{axis: size} for a DeviceMesh/Mesh, or None."""
    if mesh is None:
        return None
    raw = getattr(mesh, "mesh", mesh)
    return {a: int(s) for a, s in dict(raw.shape).items()}


def _axis_size(sizes, part):
    """Total device count behind one PartitionSpec entry (an axis name
    or a tuple of axis names)."""
    if part is None:
        return 1
    names = part if isinstance(part, (tuple, list)) else (part,)
    n = 1
    for a in names:
        n *= int(sizes.get(a, 1))
    return n


def _fit_spec(spec, shape, mesh=None):
    """Fit a PartitionSpec to one array: trim to rank, pad with None,
    and (when the mesh is known) drop axes that don't divide their
    dimension — GSPMD would silently pad the shard; the divide-or-
    replicate contract keeps the comm_model's wire-byte accounting
    exact. Scalars are always replicated."""
    if not shape:
        return P()
    parts = list(spec)[:len(shape)]
    parts += [None] * (len(shape) - len(parts))
    sizes = _mesh_sizes(mesh)
    if sizes is not None:
        parts = [None if p is not None and (
            _axis_size(sizes, p) <= 1 or dim % _axis_size(sizes, p) != 0)
            else p for p, dim in zip(parts, shape)]
    return P(*parts)


def match_partition_rules(rules, tree, mesh=None, sep="/",
                          stacked_prefixes=("layers",), strict=False):
    """Map a parameter pytree to a same-structure PartitionSpec tree.

    Each leaf's key path is '/'-joined (dict keys, sequence indices) and
    run through ``rules`` (a ``PartitionRules``, a ``ShardingStrategy``,
    or a raw ``[(regex, spec)]`` list); the FIRST matching rule's spec is
    fitted to the leaf (see ``_fit_spec``). Scalars map to ``P()``
    without consulting the rules. Leaves under a ``stacked_prefixes``
    subtree whose matched spec is one short of the leaf rank are treated
    as stacked ``[L, ...]`` layer trees: the spec is written for the
    per-layer shape and the scanned leading axis gets ``None`` prepended
    (the transformer's ``init_params`` layout). With ``strict=True`` an
    unmatched non-scalar leaf raises instead of replicating — the
    EasyLM ``match_partition_rules`` contract."""
    rules = _as_rules(rules)
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = []
    for keypath, leaf in flat:
        path = sep.join(_key_str(k) for k in keypath)
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if not shape:
            specs.append(P())
            continue
        matched = None
        for pat, spec in rules.rules:
            if pat.search(path):
                matched = spec
                break
        if matched is None:
            if strict:
                raise ValueError(
                    "no partition rule matches param path %r" % path)
            specs.append(P())
            continue
        if len(matched) == len(shape) - 1 and any(
                path.startswith(pfx + sep) or (sep + pfx + sep) in path
                for pfx in stacked_prefixes):
            matched = P(None, *matched)
        specs.append(_fit_spec(matched, shape, mesh))
    return jax.tree_util.tree_unflatten(treedef, specs)


def _as_rules(rules):
    if isinstance(rules, PartitionRules):
        return rules
    if isinstance(rules, ShardingStrategy):
        return rules.param_rules
    return PartitionRules(rules)


def _key_str(k):
    for attr in ("key", "idx", "name"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def named_shardings(mesh, spec_tree):
    """PartitionSpec tree -> NamedSharding tree over ``mesh``."""
    raw = getattr(mesh, "mesh", mesh)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(raw, s), spec_tree,
        is_leaf=lambda l: isinstance(l, P))


class ShardingStrategy:
    """Bundle of: mesh, param rules, data-batch spec, gradient-reduce axes.

    grad_reduce_axes name the mesh axes over which per-device gradients are
    summed (≙ the kvstore push reduction). With pure GSPMD jit this happens
    implicitly; the field documents and drives the shard_map paths.
    """

    def __init__(self, mesh, param_rules=None, batch_axes=("dp",),
                 grad_reduce_axes=("dp",), name="custom"):
        self.mesh = mesh
        self.param_rules = param_rules or PartitionRules()
        self.batch_axes = tuple(batch_axes)
        self.grad_reduce_axes = tuple(grad_reduce_axes)
        self.name = name

    def param_sharding(self, params):
        """Map a {path: array-or-shape} dict to NamedShardings."""
        return make_param_sharding(self.mesh, params, self.param_rules)

    def batch_spec(self, extra=()):
        return P(self.batch_axes if len(self.batch_axes) > 1
                 else self.batch_axes[0], *extra)

    def batch_sharding(self):
        return NamedSharding(getattr(self.mesh, "mesh", self.mesh),
                             self.batch_spec())

    def __repr__(self):
        return "ShardingStrategy(%s, batch=%s)" % (self.name,
                                                   self.batch_axes)


def make_param_sharding(mesh, params, rules):
    raw_mesh = getattr(mesh, "mesh", mesh)
    out = {}
    for path, v in params.items():
        shape = tuple(v.shape) if hasattr(v, "shape") else tuple(v)
        out[path] = NamedSharding(raw_mesh,
                                  rules.spec_for(path, shape, mesh))
    return out


def host_array(a):
    """Stage one (possibly sharded) array to host numpy — the transfer
    half of checkpointing and live resharding. Fully-addressable arrays
    gather directly; a non-fully-addressable array (multi-host global
    mesh) is recoverable here only when replicated (each host holds the
    whole value); genuinely host-sharded state needs the orbax
    checkpoint path instead."""
    import numpy as _np
    if hasattr(a, "is_fully_addressable") and not a.is_fully_addressable:
        shard = a.addressable_shards[0]
        if tuple(shard.data.shape) == tuple(a.shape):
            return _np.asarray(shard.data)
        raise ValueError(
            "cannot host-stage a host-sharded global array of shape %s "
            "(local shard %s); use the orbax checkpoint path for "
            "non-replicated multi-host state" % (a.shape,
                                                 shard.data.shape))
    return _np.asarray(a)


def relayout_params(params, strategy):
    """Re-place a ``{path: array}`` pytree per ``strategy`` — the
    re-layout half of live resharding (ISSUE 7): after the mesh is
    rebuilt over the survivors (``mesh.shrink_mesh``), every leaf is
    staged to host (its old sharding may reference devices that no
    longer exist) and ``device_put`` under the NamedSharding the
    strategy's partition rules assign it on the NEW mesh."""
    shardings = strategy.param_sharding(params)
    return {k: jax.device_put(host_array(v), shardings[k])
            for k, v in params.items()}


def data_parallel(mesh):
    """Pure DP: replicated params, batch sharded on 'dp'.
    ≙ kvstore 'device'/'nccl' (ref: src/kvstore/comm.h:451)."""
    return ShardingStrategy(mesh, PartitionRules(), batch_axes=("dp",),
                            grad_reduce_axes=("dp",), name="data_parallel")


def fsdp(mesh, axis="fsdp", min_size=1024):
    """ZeRO-3/FSDP: every param sharded on its largest dim over `axis`.
    ≙ dist kvstore server-held sharded state (ref: kvstore_dist_server.h:155)
    without the separate server processes."""

    raw_mesh = getattr(mesh, "mesh", mesh)

    class _FsdpRules(PartitionRules):
        def spec_for(self, path, shape=None, mesh=None):
            if shape is None or not shape:
                return P()
            import numpy as _np
            if int(_np.prod(shape)) < min_size:
                return P()
            n = int(dict(raw_mesh.shape).get(axis, 1))
            # shard the largest dim divisible by the axis size
            order = sorted(range(len(shape)), key=lambda i: -shape[i])
            for i in order:
                if n and shape[i] % max(n, 1) == 0:
                    parts = [None] * len(shape)
                    parts[i] = axis
                    return P(*parts)
            return P()

    return ShardingStrategy(mesh, _FsdpRules(), batch_axes=("dp", axis),
                            grad_reduce_axes=("dp",), name="fsdp")


def tensor_parallel(mesh, extra_rules=(), axis="tp", batch_axes=("dp",)):
    """Megatron-style TP rules for common layer shapes:
    - column-parallel then row-parallel pairs for attention/FFN
    - embedding sharded on vocab
    Dense weight layout here is (out, in) (ref FullyConnected convention),
    so column-parallel = shard dim 0, row-parallel = shard dim 1.

    Also covers the transformer's STACKED layer-tree names
    (``layers/wq`` etc., written for the per-layer shape — the scanned
    ``[L, ...]`` axis is handled by ``match_partition_rules``) and the
    tied embed/unembed pair, matching ``transformer.param_specs``.
    """
    rules = PartitionRules(list(extra_rules) + [
        # gluon Dense/attention parameter names ((out, in) layout)
        (r"(qkv|query|key|value|wq|wk|wv|w1|wi|gate|up|expand|fc1)"
         r".*weight$", (axis, None)),
        (r"(out_proj|wo|w2|down|proj|fc2|contract).*weight$", (None, axis)),
        (r"(qkv|query|key|value|wq|wk|wv|w1|wi|gate|up|expand|fc1)"
         r".*bias$", (axis,)),
        (r"embed.*weight$", (None, axis)),
        # transformer stacked layer tree (per-layer shapes; see
        # transformer.param_specs for the reference layout)
        (r"(^|/)layers/(wq|wk|wv)$", (None, axis, None)),
        (r"(^|/)layers/wo$", (axis, None, None)),
        (r"(^|/)layers/(w_gate|w_up)$", (None, axis)),
        (r"(^|/)layers/w_down$", (axis, None)),
        (r"(^|/)embed$", (axis, None)),
        (r"(^|/)w_out$", (None, axis)),
    ])
    return ShardingStrategy(mesh, rules, batch_axes=tuple(batch_axes),
                            grad_reduce_axes=("dp",), name="tensor_parallel")


def infer_rules_for_block(block, mesh, strategy="dp"):
    """Choose rules for a gluon Block by inspecting its parameter paths.

    ``strategy='auto'`` picks ``tensor_parallel`` when the mesh has a
    'tp' axis >1 AND at least one of the block's parameter paths matches
    a TP rule, else pure data-parallel — the safe default for the fused
    step's 3D-mesh mode (an unmatched tree stays replicated rather than
    guessing a layout)."""
    if strategy in ("dp", "data_parallel", "local", "device", "nccl"):
        return data_parallel(mesh)
    if strategy in ("fsdp", "zero", "dist_sync"):
        return fsdp(mesh)
    if strategy in ("tp", "tensor_parallel"):
        return tensor_parallel(mesh)
    if strategy in ("auto", "3d"):
        sizes = _mesh_sizes(mesh) or {}
        tp = tensor_parallel(mesh)
        if int(sizes.get("tp", 1)) > 1 and block is not None:
            names = [p.name for p in block._all_params_list()] \
                if hasattr(block, "_all_params_list") else []
            if any(tp.param_rules.spec_for(n) != P() for n in names):
                return tp
        return data_parallel(mesh)
    raise ValueError("unknown strategy %r" % strategy)
