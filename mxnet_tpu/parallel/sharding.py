"""Sharding strategies: parameter partition rules over the mesh.

The strategy object plays the role the kvstore *type string* plays in the
reference ("local"/"device"/"nccl"/"dist_sync", ref: src/kvstore/kvstore.cc:40):
it names HOW state and compute are distributed. Here a strategy is data — a
list of (param-path regex, PartitionSpec) rules plus batch/activation specs —
and GSPMD compiles it, instead of each mode being a separate C++ backend.
"""
from __future__ import annotations

import re

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["PartitionRules", "ShardingStrategy", "data_parallel", "fsdp",
           "tensor_parallel", "make_param_sharding", "infer_rules_for_block",
           "host_array", "relayout_params"]


class PartitionRules:
    """Ordered (regex, PartitionSpec) rules; first match wins.

    Analog of the reference's per-key sharding decisions in
    EncodeDefaultKey (ref: src/kvstore/kvstore_dist.h:263) — but declarative
    and per-parameter-path instead of hashed key ranges.
    """

    def __init__(self, rules=()):
        self.rules = [(re.compile(pat), P(*spec) if isinstance(spec, tuple)
                       else spec) for pat, spec in rules]

    def spec_for(self, path, shape=None):
        for pat, spec in self.rules:
            if pat.search(path):
                if shape is not None:
                    spec = _fit_spec(spec, shape)
                return spec
        return P()

    def __add__(self, other):
        out = PartitionRules()
        out.rules = list(self.rules) + list(other.rules)
        return out


def _fit_spec(spec, shape):
    """Trim a PartitionSpec to the array rank and drop axes that don't divide
    the dimension (GSPMD requires divisibility; replicate instead)."""
    parts = list(spec)[:len(shape)]
    parts += [None] * (len(shape) - len(parts))
    return P(*parts)


class ShardingStrategy:
    """Bundle of: mesh, param rules, data-batch spec, gradient-reduce axes.

    grad_reduce_axes name the mesh axes over which per-device gradients are
    summed (≙ the kvstore push reduction). With pure GSPMD jit this happens
    implicitly; the field documents and drives the shard_map paths.
    """

    def __init__(self, mesh, param_rules=None, batch_axes=("dp",),
                 grad_reduce_axes=("dp",), name="custom"):
        self.mesh = mesh
        self.param_rules = param_rules or PartitionRules()
        self.batch_axes = tuple(batch_axes)
        self.grad_reduce_axes = tuple(grad_reduce_axes)
        self.name = name

    def param_sharding(self, params):
        """Map a {path: array-or-shape} dict to NamedShardings."""
        return make_param_sharding(self.mesh, params, self.param_rules)

    def batch_spec(self, extra=()):
        return P(self.batch_axes if len(self.batch_axes) > 1
                 else self.batch_axes[0], *extra)

    def batch_sharding(self):
        return NamedSharding(getattr(self.mesh, "mesh", self.mesh),
                             self.batch_spec())

    def __repr__(self):
        return "ShardingStrategy(%s, batch=%s)" % (self.name,
                                                   self.batch_axes)


def make_param_sharding(mesh, params, rules):
    raw_mesh = getattr(mesh, "mesh", mesh)
    out = {}
    for path, v in params.items():
        shape = tuple(v.shape) if hasattr(v, "shape") else tuple(v)
        out[path] = NamedSharding(raw_mesh, rules.spec_for(path, shape))
    return out


def host_array(a):
    """Stage one (possibly sharded) array to host numpy — the transfer
    half of checkpointing and live resharding. Fully-addressable arrays
    gather directly; a non-fully-addressable array (multi-host global
    mesh) is recoverable here only when replicated (each host holds the
    whole value); genuinely host-sharded state needs the orbax
    checkpoint path instead."""
    import numpy as _np
    if hasattr(a, "is_fully_addressable") and not a.is_fully_addressable:
        shard = a.addressable_shards[0]
        if tuple(shard.data.shape) == tuple(a.shape):
            return _np.asarray(shard.data)
        raise ValueError(
            "cannot host-stage a host-sharded global array of shape %s "
            "(local shard %s); use the orbax checkpoint path for "
            "non-replicated multi-host state" % (a.shape,
                                                 shard.data.shape))
    return _np.asarray(a)


def relayout_params(params, strategy):
    """Re-place a ``{path: array}`` pytree per ``strategy`` — the
    re-layout half of live resharding (ISSUE 7): after the mesh is
    rebuilt over the survivors (``mesh.shrink_mesh``), every leaf is
    staged to host (its old sharding may reference devices that no
    longer exist) and ``device_put`` under the NamedSharding the
    strategy's partition rules assign it on the NEW mesh."""
    shardings = strategy.param_sharding(params)
    return {k: jax.device_put(host_array(v), shardings[k])
            for k, v in params.items()}


def data_parallel(mesh):
    """Pure DP: replicated params, batch sharded on 'dp'.
    ≙ kvstore 'device'/'nccl' (ref: src/kvstore/comm.h:451)."""
    return ShardingStrategy(mesh, PartitionRules(), batch_axes=("dp",),
                            grad_reduce_axes=("dp",), name="data_parallel")


def fsdp(mesh, axis="fsdp", min_size=1024):
    """ZeRO-3/FSDP: every param sharded on its largest dim over `axis`.
    ≙ dist kvstore server-held sharded state (ref: kvstore_dist_server.h:155)
    without the separate server processes."""

    raw_mesh = getattr(mesh, "mesh", mesh)

    class _FsdpRules(PartitionRules):
        def spec_for(self, path, shape=None):
            if shape is None or not shape:
                return P()
            import numpy as _np
            if int(_np.prod(shape)) < min_size:
                return P()
            n = int(dict(raw_mesh.shape).get(axis, 1))
            # shard the largest dim divisible by the axis size
            order = sorted(range(len(shape)), key=lambda i: -shape[i])
            for i in order:
                if n and shape[i] % max(n, 1) == 0:
                    parts = [None] * len(shape)
                    parts[i] = axis
                    return P(*parts)
            return P()

    return ShardingStrategy(mesh, _FsdpRules(), batch_axes=("dp", axis),
                            grad_reduce_axes=("dp",), name="fsdp")


def tensor_parallel(mesh, extra_rules=(), axis="tp"):
    """Megatron-style TP rules for common layer shapes:
    - column-parallel then row-parallel pairs for attention/FFN
    - embedding sharded on vocab
    Dense weight layout here is (out, in) (ref FullyConnected convention),
    so column-parallel = shard dim 0, row-parallel = shard dim 1.
    """
    rules = PartitionRules(list(extra_rules) + [
        (r"(qkv|query|key|value|wq|wk|wv|w1|wi|gate|up|expand|fc1)"
         r".*weight$", (axis, None)),
        (r"(out_proj|wo|w2|down|proj|fc2|contract).*weight$", (None, axis)),
        (r"(qkv|query|key|value|wq|wk|wv|w1|wi|gate|up|expand|fc1)"
         r".*bias$", (axis,)),
        (r"embed.*weight$", (None, axis)),
    ])
    return ShardingStrategy(mesh, rules, batch_axes=("dp",),
                            grad_reduce_axes=("dp",), name="tensor_parallel")


def infer_rules_for_block(block, mesh, strategy="dp"):
    """Choose rules for a gluon Block by inspecting its parameter paths."""
    if strategy in ("dp", "data_parallel", "local", "device", "nccl"):
        return data_parallel(mesh)
    if strategy in ("fsdp", "zero", "dist_sync"):
        return fsdp(mesh)
    if strategy in ("tp", "tensor_parallel"):
        return tensor_parallel(mesh)
    raise ValueError("unknown strategy %r" % strategy)
