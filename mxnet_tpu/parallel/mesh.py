"""Device mesh management.

One logical mesh with named axes replaces the reference's separate comm
paths (intra-node reduce trees, NCCL rings, ps-lite key sharding — ref:
src/kvstore/comm.h:451, kvstore_nccl.h:62, kvstore_dist.h:44). Axis layout
follows the ICI-torus-first rule: model axes (tp/sp) innermost so their
collectives ride the fastest links; dp outermost so gradient psum can cross
DCN between slices.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import numpy as _np
from .compat import Mesh, NamedSharding, PartitionSpec

__all__ = ["DeviceMesh", "create_mesh", "current_mesh", "default_mesh_axes",
           "mesh_scope", "surviving_devices", "shrink_mesh"]

# canonical axis order, outermost (slowest/DCN-friendly) to innermost (ICI)
default_mesh_axes = ("dp", "fsdp", "pp", "ep", "sp", "tp")

_state = threading.local()


class DeviceMesh:
    """A named-axis device mesh; thin wrapper over jax.sharding.Mesh."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    @property
    def axis_names(self):
        return tuple(self.mesh.axis_names)

    @property
    def shape(self):
        return dict(self.mesh.shape)

    def size(self, axis=None):
        if axis is None:
            return int(_np.prod(list(self.mesh.shape.values())))
        return int(self.mesh.shape[axis])

    def sharding(self, *spec):
        """NamedSharding for a PartitionSpec over this mesh."""
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def replicated(self):
        return NamedSharding(self.mesh, PartitionSpec())

    def __enter__(self):
        self.mesh.__enter__()
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return self.mesh.__exit__(*exc)

    def __repr__(self):
        return "DeviceMesh(%s)" % (dict(self.mesh.shape),)


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


def create_mesh(axes=None, devices=None, **axis_sizes):
    """Create a DeviceMesh.

    create_mesh(dp=2, tp=4)           — explicit sizes (product must divide
                                        device count; remainder goes to 'dp')
    create_mesh()                     — all devices on 'dp'

    Axes not mentioned get size 1 so PartitionSpecs referencing any canonical
    axis are always valid.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axes is None:
        axes = default_mesh_axes
    unknown = set(axis_sizes) - set(axes)
    if unknown:
        raise ValueError("unknown mesh axes %s; valid axes: %s"
                         % (sorted(unknown), list(axes)))
    sizes = {a: int(axis_sizes.get(a, 1)) for a in axes}
    explicit = int(_np.prod([s for s in sizes.values()]))
    if n % explicit != 0:
        raise ValueError("mesh axes %s (product %d) do not divide %d devices"
                         % (sizes, explicit, n))
    if "dp" in sizes and "dp" not in axis_sizes:
        sizes["dp"] = n // explicit
    elif explicit != n:
        raise ValueError("mesh axes %s use %d of %d devices"
                         % (sizes, explicit, n))
    shape = tuple(sizes[a] for a in axes)
    dev_array = _np.array(devices).reshape(shape)
    return DeviceMesh(Mesh(dev_array, axes))


def current_mesh():
    """Innermost active mesh, or None."""
    stack = _stack()
    return stack[-1] if stack else None


def surviving_devices(dead_processes, devices=None):
    """Devices NOT owned by a dead process — the raw material of a
    post-failure mesh. ``dead_processes`` are jax process indices (the
    launcher's ``MXTPU_PROC_ID`` ranks in a multi-host job)."""
    dead = set(int(p) for p in dead_processes)
    if devices is None:
        devices = jax.devices()
    return [d for d in devices if int(d.process_index) not in dead]


def shrink_mesh(mesh, dead_processes=(), devices=None):
    """Rebuild a mesh over the survivors of a host failure (the live
    resharding primitive, ISSUE 7): every device owned by a dead
    process is dropped, non-``dp`` axis sizes are preserved, and
    ``dp`` absorbs the shrink — dp is the outermost/DCN axis, the one a
    lost host subtracts from. Raises ``ValueError`` when the surviving
    device count cannot carry the model axes (tp/sp/... no longer
    divide), in which case the job must wait for a replacement instead
    of limping (reshard policy 'fail')."""
    raw = getattr(mesh, "mesh", mesh)
    if devices is None:
        devices = surviving_devices(dead_processes,
                                    list(raw.devices.ravel()))
    if not devices:
        raise ValueError("no surviving devices to rebuild the mesh on")
    sizes = {a: int(s) for a, s in dict(raw.shape).items()
             if a != "dp" and int(s) > 1}
    return create_mesh(axes=tuple(raw.axis_names), devices=list(devices),
                       **sizes)


@contextlib.contextmanager
def mesh_scope(mesh):
    with mesh:
        yield mesh
