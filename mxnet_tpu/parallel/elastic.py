"""Elastic training: preemption tolerance with live resharding.

The reference's failure story stops at ps-lite heartbeats and dead-node
queries (`ref: src/kvstore/kvstore_dist.h:121 GetDeadNodes`) — SURVEY
§5 notes it has no checkpoint-based elastic recovery, and on TPU a
missing host stalls every collective rather than limping along. This
module closes the loop the blueprint calls for (ISSUE 7), welding three
previously-parallel subsystems — faultpoints, kvstore heartbeats, the
parallel stack — into one recovery cycle:

- `CheckpointManager` — crash-consistent sharded checkpoints of the
  full train state (params, optimizer state, step, rng). Incomplete
  checkpoints are *never* restore candidates: orbax step dirs must
  carry a commit marker and fallback files must unpickle; corrupt
  leftovers are pruned on the next `save()`.
- `PreemptionGuard` — SIGTERM-aware scope that chains to (and on exit
  restores) any pre-existing handler and fires at most once per
  incarnation; the loop checkpoints synchronously and exits cleanly.
- `ElasticController` — the dead-node signal → recovery weld: polls the
  kvstore heartbeat staleness table (`AsyncKVStore.dead_nodes`), and on
  a vanished rank (or a failed collective surfacing as an exception)
  drives *live resharding*: rebuild the mesh over the survivors
  (`mesh.shrink_mesh`), re-layout params per the sharding rules
  (`sharding.relayout_params`), shrink the kvstore world
  (`AsyncKVStore.resize`), and resume from the newest crash-consistent
  checkpoint.
- `HostGradReducer` — deterministic cross-process gradient reduction
  over the async-PS transport (the off-mesh fallback data plane): every
  rank sums contributions in sorted-rank order, so replicas apply
  bitwise-identical updates and survive resizes without drifting.
- `elastic_train_loop` — wraps any step function with all of the above.

Every recovery event counts into ``profiler.metrics()['elastic']`` and
drops an ``elastic:*`` instant trace marker (profiler.bump_elastic);
the `elastic.restore` / `elastic.reshard` / `collective.allreduce`
fault points make the whole cycle chaos-testable
(tests/test_faultpoints.py, tests/test_elastic.py).
"""
from __future__ import annotations

import logging
import os
import pickle
import signal
import time

import jax
import numpy as np

from .. import profiler as _profiler
from .._debug import faultpoint as _faultpoint
from .._debug import flightrec as _flightrec
from .._debug import goodput as _goodput
from .._debug import watchdog as _watchdog
from .sharding import host_array
from ..base import getenv as _getenv

__all__ = ["CheckpointManager", "elastic_train_loop", "PreemptionGuard",
           "ElasticController", "HostGradReducer", "ReshardRequired",
           "shard_for_rank"]

# commit marker inside an orbax step dir: present iff the save ran to
# completion (written before the atomic rename publishes the dir). A
# step dir without it — e.g. a crash between multi-host shard writes by
# a non-atomic writer — is never a restore candidate.
_COMMIT = "_COMMIT"


class ReshardRequired(RuntimeError):
    """A rank vanished and the reshard policy forbids shrinking
    (``MXTPU_ELASTIC_RESHARD=fail``): the job must stop and wait for a
    replacement instead of limping on fewer hosts."""

    def __init__(self, dead_ranks, survivors):
        self.dead_ranks = sorted(dead_ranks)
        self.survivors = sorted(survivors)
        super().__init__(
            "dead ranks %s; reshard policy 'fail' forbids shrinking to "
            "survivors %s" % (self.dead_ranks, self.survivors))


class CheckpointManager:
    """Save/restore arbitrary pytrees with a monotonically increasing step.

    Directory layout: <dir>/step_<N>/ (orbax) or <dir>/step_<N>.ckpt
    (fallback). Keeps the newest `keep` checkpoints. Crash-consistent:
    publication is temp-write + atomic rename, completeness is provable
    after the fact (commit marker / unpickle check), and `restore()`
    walks past corrupt candidates to the newest complete step.
    """

    def __init__(self, directory, keep=3, use_orbax=None):
        self.directory = os.path.abspath(str(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.keep = int(keep)
        if use_orbax is None:
            try:
                import orbax.checkpoint  # noqa: F401
                use_orbax = True
            except Exception:
                use_orbax = False
        self._orbax = bool(use_orbax)
        if self._orbax:
            import orbax.checkpoint as ocp
            self._ckptr = ocp.PyTreeCheckpointer()

    # -- paths --------------------------------------------------------------
    def _step_path(self, step):
        name = "step_%d" % int(step)
        return os.path.join(self.directory,
                            name if self._orbax else name + ".ckpt")

    def _is_complete(self, path):
        """Cheap completeness probe — no deserialization. An orbax step
        dir is complete iff the commit marker landed before the rename
        published it; a fallback file iff it is non-empty and ends with
        the pickle STOP opcode (a truncated write — crash between
        multi-host shard writes — cannot)."""
        if os.path.isdir(path):
            return os.path.exists(os.path.join(path, _COMMIT))
        try:
            size = os.path.getsize(path)
            if size == 0:
                return False
            with open(path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                return f.read(1) == b"."
        except OSError:
            return False

    def all_steps(self):
        """Steps with a COMPLETE checkpoint, ascending. `.tmp` leftovers
        (partial save interrupted mid-write) and incomplete entries
        (missing commit marker / truncated pickle) are never restore
        candidates."""
        steps = []
        for n in os.listdir(self.directory):
            if not n.startswith("step_") or n.endswith(".tmp"):
                continue
            try:
                s = int(n[5:].split(".")[0])
            except ValueError:
                continue
            if self._is_complete(os.path.join(self.directory, n)):
                steps.append(s)
        return sorted(set(steps))

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save/restore -------------------------------------------------------
    def save(self, step, state):
        """Write `state` (pytree of arrays) for `step`; prunes old ones.

        Crash-consistent both ways: the full state lands on a `.tmp`
        sibling first and is atomically renamed into place, with the
        `checkpoint.save` fault point firing between write and rename —
        an injected (or real) crash mid-save leaves every previously
        published step restorable and at worst a `.tmp` leftover or a
        marker-less dir, which `all_steps()` never considers and the
        next `save()` prunes."""
        t0 = time.monotonic()
        path = self._step_path(step)
        tmp = path + ".tmp"
        host_state = jax.tree_util.tree_map(host_array, state)
        try:
            if self._orbax:
                # orbax refuses to overwrite; write then atomic-rename
                import shutil
                if os.path.exists(tmp):
                    shutil.rmtree(tmp)
                self._ckptr.save(tmp, host_state)
                with open(os.path.join(tmp, _COMMIT), "w") as f:
                    f.write("%d\n" % int(step))
                if _faultpoint.ACTIVE:
                    _faultpoint.check("checkpoint.save")
                if os.path.exists(path):
                    shutil.rmtree(path)
                os.replace(tmp, path)
            else:
                with open(tmp, "wb") as f:
                    pickle.dump(host_state, f)
                if _faultpoint.ACTIVE:
                    _faultpoint.check("checkpoint.save")
                os.replace(tmp, path)
        except BaseException:
            try:
                if os.path.isdir(tmp):
                    import shutil
                    shutil.rmtree(tmp)
                else:
                    os.remove(tmp)
            except OSError:
                pass
            raise
        _profiler.bump_elastic("checkpoint_saves",
                               args={"step": int(step)})
        self._prune()
        # checkpoint span (rare path — its own clock reads are fine):
        # the trace lane sees it while profiling runs, the flight
        # recorder always, and the goodput run ledger books the wall
        # time under 'checkpoint'
        dur_s = time.monotonic() - t0
        _profiler.record_op("elastic.checkpoint_save", dur_s * 1e6,
                            category="elastic", lane="user",
                            args={"step": int(step)})
        if _goodput.OPEN:
            _goodput.note_checkpoint(dur_s, "save")
        return path

    def restore(self, step=None):
        """Load the pytree for `step` (newest when None); (None, None)
        when nothing restorable exists. With `step=None` the walk skips
        entries that fail to load (corruption the cheap completeness
        probe missed) and falls back to the next-older complete step —
        counted as ``elastic.incomplete_skipped``."""
        if _faultpoint.ACTIVE:
            # the restore seam: an injected raise here exercises the
            # caller's recovery path exactly where a real read failure
            # (lost filesystem, corrupt bytes) would surface
            _faultpoint.check("elastic.restore")
        t0 = time.monotonic()

        def _done(state, s):
            dur_s = time.monotonic() - t0
            _profiler.record_op("elastic.checkpoint_restore",
                                dur_s * 1e6, category="elastic",
                                lane="user", args={"step": s})
            if _goodput.OPEN:
                # inside a recovery interval the interval's own clock
                # covers this time; note_checkpoint only counts then
                _goodput.note_checkpoint(dur_s, "restore")
            return state, s

        if step is not None:
            state = self._load(self._step_path(step))
            _profiler.bump_elastic("restores", args={"step": int(step)})
            return _done(state, int(step))
        for s in reversed(self.all_steps()):
            try:
                state = self._load(self._step_path(s))
            except Exception:
                # complete-looking but unreadable (e.g. corruption past
                # the STOP byte): skip to the previous step
                _profiler.bump_elastic("incomplete_skipped",
                                       args={"step": int(s)})
                continue
            _profiler.bump_elastic("restores", args={"step": int(s)})
            return _done(state, int(s))
        return None, None

    def _load(self, path):
        if self._orbax:
            return self._ckptr.restore(path)
        with open(path, "rb") as f:
            return pickle.load(f)

    def _prune(self):
        """Drop steps beyond `keep` AND every incomplete leftover — a
        `.tmp` from an interrupted save, a marker-less orbax dir, a
        truncated fallback file (the crashed sibling of the step that
        just published)."""
        import shutil

        def _rm(p):
            try:
                if os.path.isdir(p):
                    shutil.rmtree(p)
                else:
                    os.remove(p)
            except OSError:
                pass

        complete = set(self.all_steps())
        for n in os.listdir(self.directory):
            if not n.startswith("step_"):
                continue
            p = os.path.join(self.directory, n)
            if n.endswith(".tmp"):
                _rm(p)
                continue
            try:
                s = int(n[5:].split(".")[0])
            except ValueError:
                continue
            if s not in complete:
                _rm(p)
        steps = sorted(complete)
        for s in steps[:-self.keep] if self.keep > 0 else []:
            _rm(self._step_path(s))


class PreemptionGuard:
    """SIGTERM-aware scope: `guard.preempted` flips when the platform
    sends the preemption notice, so the loop can checkpoint and exit
    cleanly (the TPU replacement for ps-lite heartbeats).

    Handler discipline: the scope CHAINS to any pre-existing handler
    (it still runs, exactly once, on the first signal), restores it on
    `__exit__`, and fires at most once per incarnation — repeated
    SIGTERMs while already draining do not re-enter. The handler body
    only flips flags and tail-calls the chained handler; it takes no
    locks (a signal interrupting a lock holder must not deadlock)."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self.preempted = False
        self._fired = False
        self._signals = signals
        self._old = {}

    def _handler(self, signum, frame):
        self.preempted = True
        if self._fired:
            return
        self._fired = True
        old = self._old.get(signum)
        if callable(old):
            # chain: whatever the host process installed before us
            # (its own drain logic) still observes the signal
            old(signum, frame)

    def __enter__(self):
        for s in self._signals:
            try:
                self._old[s] = signal.signal(s, self._handler)
            except (ValueError, OSError):
                pass  # non-main thread: stay polling-only
        return self

    def __exit__(self, *exc):
        for s, old in self._old.items():
            try:
                signal.signal(s, old)
            except (ValueError, OSError):
                pass
        self._old.clear()
        return False


class ElasticController:
    """The dead-node signal → recovery weld (ISSUE 7 tentpole a).

    Owns the job's view of the live world: polls the kvstore heartbeat
    staleness table (`AsyncKVStore.dead_nodes`, rate-limited by
    ``MXTPU_ELASTIC_POLL_S``), classifies step failures, and drives the
    reshard: shrink the kvstore world, rebuild mesh + re-layout params
    through ``reshard_fn``, and hand the loop back to the newest
    checkpoint. Reshard policy ``MXTPU_ELASTIC_RESHARD``:

    - ``shrink`` (default): continue on the survivors
    - ``fail``: raise :class:`ReshardRequired` (wait for a replacement)
    """

    def __init__(self, kvstore=None, world=None, rank=None,
                 poll_interval=None, dead_timeout=None,
                 reshard_policy=None, reshard_fn=None, logger=None):
        self.kv = kvstore
        if rank is None:
            rank = int(_getenv("MXTPU_PROC_ID", "0") or 0)
        self.rank = int(rank)
        if world is None:
            n = getattr(kvstore, "num_workers", 1) if kvstore else 1
            world = range(int(n))
        self.world = sorted(int(r) for r in world)
        self.poll_interval = float(
            _getenv("MXTPU_ELASTIC_POLL_S", "1.0")
            if poll_interval is None else poll_interval)
        self.dead_timeout = float(
            _getenv("MXTPU_PS_DEAD_TIMEOUT", "3.0")
            if dead_timeout is None else dead_timeout)
        self.reshard_policy = (
            _getenv("MXTPU_ELASTIC_RESHARD", "shrink")
            if reshard_policy is None else reshard_policy)
        if self.reshard_policy not in ("shrink", "fail"):
            raise ValueError(
                "MXTPU_ELASTIC_RESHARD must be 'shrink' or 'fail', got "
                "%r" % (self.reshard_policy,))
        self.reshard_fn = reshard_fn
        self._dead = set()
        self._last_poll = 0.0
        self._log = logger or logging.getLogger("mxnet_tpu.elastic")
        self._publish_world()

    def _publish_world(self):
        """Publish the committed world view into the flight recorder's
        dump context: a post-mortem shard then names the job topology —
        world, survivors, known-dead — at the instant of death."""
        _flightrec.set_context("elastic_world", {
            "rank": self.rank,
            "world": list(self.world),
            "dead": sorted(self._dead),
            "survivors": self.survivors,
            "reshard_policy": self.reshard_policy,
        })

    @property
    def dead_ranks(self):
        return sorted(self._dead)

    @property
    def survivors(self):
        return sorted(set(self.world) - self._dead)

    def poll(self, force=False):
        """Query the heartbeat staleness table (rate-limited unless
        ``force``); returns the NEWLY dead ranks. The kvstore side
        counts ``elastic.dead_rank_detected`` and drops the trace
        marker the moment the set grows, so the controller and
        operators see the same signal."""
        if self.kv is None:
            return []
        now = time.monotonic()
        if not force and now - self._last_poll < self.poll_interval:
            return []
        self._last_poll = now
        try:
            dead = self.kv.dead_nodes(self.dead_timeout)
        except Exception as e:  # server unreachable: no verdict yet
            self._log.warning("elastic: dead-node poll failed (%s)", e)
            return []
        new = sorted(set(int(r) for r in dead) - self._dead
                     - {self.rank})
        if new:
            self._dead.update(new)
            self._log.warning("elastic: dead ranks detected: %s "
                              "(survivors %s)", new, self.survivors)
            self._publish_world()
        # only deaths inside the COMMITTED world are actionable — same
        # guard handle_failure applies: a rank already resharded away,
        # or one outside this controller's world (a sub-world scoped
        # over a shared PS), must not trigger another reshard-and-rewind
        in_world = set(self.world)
        return [r for r in new if r in in_world]

    def handle_failure(self, exc):
        """Classify a step failure: force a dead-node poll and report
        whether resharding (vs plain restore-and-retry) is the right
        recovery. A failed collective with every rank alive is a
        transient — retry; with a dead rank it is structural —
        reshard."""
        self.poll(force=True)
        # only ranks still in the COMMITTED world warrant a reshard; a
        # rank already resharded away must not re-trigger on the next
        # transient failure
        return bool(self._dead & set(self.world))

    def reshard(self, state=None):
        """Commit the world shrink: user ``reshard_fn(state,
        survivors)`` for mesh rebuild + param re-layout, THEN kvstore
        resize. Everything that can refuse — the policy check, the
        faultpoint, ``reshard_fn`` (e.g. ``shrink_mesh`` raising because
        a model axis no longer divides the survivors) — runs before any
        side effect, so a failed reshard leaves the committed world
        (kvstore size, ``self.world``, the counter) untouched. Returns
        (survivors, possibly-new state)."""
        if _faultpoint.ACTIVE:
            _faultpoint.check("elastic.reshard")
        survivors = self.survivors
        if not survivors or self.rank not in survivors:
            raise ReshardRequired(self.dead_ranks, survivors)
        if self.reshard_policy == "fail":
            raise ReshardRequired(self.dead_ranks, survivors)
        if self.reshard_fn is not None:
            new_state = self.reshard_fn(state, survivors)
            if new_state is not None:
                state = new_state
        if self.kv is not None and hasattr(self.kv, "resize"):
            self.kv.resize(len(survivors))
        _profiler.bump_elastic(
            "reshards", args={"survivors": survivors,
                              "dead": self.dead_ranks})
        self._log.warning("elastic: resharded onto %s (world was %s)",
                          survivors, self.world)
        self.world = survivors
        self._publish_world()
        return survivors, state


class HostGradReducer:
    """Deterministic cross-process gradient reduction over the async-PS
    transport — the off-mesh/elastic fallback data plane (the in-mesh
    bucketed overlap of ``parallel/overlap.py`` covers the devices one
    jax process owns; this covers processes that must survive each
    other's deaths).

    Protocol per step: push the local (already in-mesh-reduced) flat
    gradient under a per-rank key, barrier, pull every live rank's
    contribution and sum IN SORTED RANK ORDER, barrier again (fences
    this step's pulls from the next step's overwrites). Every rank
    computes the identical f32 sum, so replicas apply bitwise-identical
    updates and never drift — the property the elastic chaos test pins.

    A dead rank surfaces as a barrier abort naming the stale ranks (the
    PR 5 heartbeat autopsy) — never a hang — and the elastic loop
    reshards; with a world of one the wire is skipped entirely.

    Precondition: the transport must carry NO server-side optimizer
    (``set_optimizer``) — the server applies its updater to every
    pushed key, which would silently turn the reducer's per-rank
    scratch keys into optimizer-mangled values instead of raw
    gradients. Enforced per call."""

    def __init__(self, kvstore, name="elastic.grad"):
        self.kv = kvstore
        self._name = name

    def _key(self, rank):
        return "%s:%d" % (self._name, int(rank))

    def allreduce(self, flat, world, rank):
        """Sum one flat f32 vector across ``world`` (sorted ranks).
        Returns the identical total on every rank."""
        if _faultpoint.ACTIVE:
            # the collective seam: a failed cross-host reduction
            # surfaces here as an exception, exactly what the elastic
            # loop classifies and recovers from
            _faultpoint.check("collective.allreduce")
        if getattr(self.kv, "_optimizer", None) is not None:
            raise RuntimeError(
                "HostGradReducer needs a raw store-replace transport, "
                "but this kvstore has a server-side optimizer "
                "(set_optimizer): pushes to the reducer's scratch keys "
                "would be optimizer-applied, not stored — use a "
                "dedicated kvstore with no optimizer for the reducer")
        host = np.asarray(flat, np.float32).ravel()
        world = sorted(int(r) for r in world)
        if len(world) <= 1:
            return host
        import mxnet_tpu.ndarray as nd
        t0 = time.perf_counter() if _profiler._LIVE else None
        self.kv.push(self._key(rank), nd.array(host))
        self.kv._barrier()
        total = None
        out = nd.zeros(host.shape)
        for r in world:
            self.kv.pull(self._key(r), out=out)
            a = out.asnumpy().astype(np.float32, copy=False)
            total = a.copy() if total is None else total + a
        self.kv._barrier()
        if t0 is not None:
            _profiler.record_op(
                "elastic.host_allreduce",
                (time.perf_counter() - t0) * 1e6,
                category="kvstore", lane="kvstore",
                args={"world": len(world), "bytes": int(host.nbytes)})
        return total


def shard_for_rank(n_items, world, rank):
    """Deterministic contiguous split of ``n_items`` over the sorted
    live world — the data-assignment half of resharding. A pure
    function of ``(n_items, world, rank)``, so the assignment is
    epoch-reproducible under elastic resize: survivors agree on the new
    split without talking. Returns ``range(start, stop)``."""
    world = sorted(int(r) for r in world)
    idx = world.index(int(rank))
    n = len(world)
    base, extra = divmod(int(n_items), n)
    start = idx * base + min(idx, extra)
    stop = start + base + (1 if idx < extra else 0)
    return range(start, stop)


def elastic_train_loop(step_fn, state, batches, ckpt, save_every=100,
                       max_failures=3, on_restore=None, logger=None,
                       controller=None, data_service=None):
    """Run `state, metrics = step_fn(state, batch)` over `batches` with
    checkpoint-based recovery and (optionally) live resharding.

    - every `save_every` steps: `ckpt.save(step, state)` (set
      ``MXTPU_ELASTIC_CKPT_EVERY`` to override a ``save_every=None``)
    - on an exception (failed collective, restarted backend): restore
      the newest checkpoint, skip already-done steps, continue; gives up
      after `max_failures` consecutive failures *unless* the
      ``controller`` attributes the failure to a dead rank, in which
      case the world is resharded onto the survivors first
    - with a ``controller``: every iteration polls the dead-node table
      (rate-limited), so a vanished rank triggers resharding even when
      this rank's own step did not fail
    - on SIGTERM: save synchronously and return early with the state
    - with a ``data_service`` (``io.ShardService``): the service's
      sample cursor is **embedded in every checkpoint payload**
      (``cursor_for_checkpoint``/``apply_cursor``), so ONE atomic
      temp+rename publishes params@step and cursor@step together — a
      crash at any instant leaves either both or neither, never a torn
      pair that would replay already-trained samples; the service is
      resized onto the survivors after every reshard — the ISSUE 11
      weld that keeps the global sample sequence intact across a
      mid-epoch rank death

    `batches` must be re-iterable (a list or a factory-backed sequence)
    so recovery can rewind. Returns (state, last_step, completed: bool).
    """
    log = logger or logging.getLogger("mxnet_tpu.elastic")
    if save_every is None:
        save_every = int(_getenv("MXTPU_ELASTIC_CKPT_EVERY",
                                        "100"))
    batches = list(batches)

    # run-level goodput ledger (ISSUE 14): the loop brackets the run,
    # so every second between here and the return is attributed. An
    # already-open run (an outer harness opened one) is left alone.
    run_meta = {"loop": "elastic_train_loop", "batches": len(batches),
                "save_every": int(save_every or 0)}
    if controller is not None:
        run_meta["world"] = list(controller.world)
        run_meta["rank"] = controller.rank
    own_run = _goodput.open_run(meta=run_meta) \
        if _goodput.ENABLED and not _goodput.is_open() else None

    def _unwrap(restored):
        """Split a restored payload: adopt the embedded data cursor
        (when present) and return the bare train state. Pre-weld
        checkpoints (no wrapper) pass through unchanged."""
        if isinstance(restored, dict) \
                and "__data_cursor__" in restored:
            if data_service is not None:
                data_service.apply_cursor(restored["__data_cursor__"])
            return restored["__elastic_state__"]
        return restored

    start = 0
    # resuming a previous incarnation IS recovery badput: the interval
    # (restore probe + load + re-layout) books under 'recovery' when a
    # checkpoint existed, and is discarded when this is a fresh run. A
    # restore that RAISES (the elastic.restore faultpoint, a lost
    # filesystem) must still close the run it opened — a leaked-open
    # run would suppress every later loop's manifest in this process
    try:
        _goodput.recovery_begin()
        restored, step0 = ckpt.restore()
        if restored is not None:
            state = _retree(state, _unwrap(restored))
            start = step0 + 1
            if on_restore is not None:
                on_restore(state, step0)
            _watchdog.reset_window()
            _goodput.recovery_end(kind="resume", restored_step=step0)
            log.info("elastic: resumed from checkpoint step %d", step0)
        else:
            _goodput.recovery_end(count=False)
    except BaseException:
        # book the failed attempt's wall time as recovery (no-op when
        # the interval already ended) and publish the failed run
        _goodput.recovery_end(kind="resume", ok=False)
        if own_run is not None:
            _goodput.close_run(outcome="failed")
        raise

    def _save(step):
        payload = state
        if data_service is not None:
            # the cursor rides INSIDE the params payload: one
            # temp+rename publishes both, so no crash instant can
            # leave params@step paired with an older cursor (which
            # would replay already-trained samples on resume)
            payload = {"__elastic_state__": state,
                       "__data_cursor__":
                           data_service.cursor_for_checkpoint()}
        ckpt.save(step, payload)

    def _recover(need_reshard):
        """Reshard (when attributed to a dead rank) then rewind to the
        newest checkpoint; returns (state, next index) or None when no
        checkpoint exists (caller re-raises the original error).

        The whole interval — policy check, reshard, restore, re-layout
        — is one goodput 'recovery' span, and the watchdog's rolling
        median window resets on the way out: durations from the old
        world size must not police the resized world's cadence."""
        nonlocal state
        _goodput.recovery_begin()
        resharded = False
        s0 = None
        ok = False
        try:
            if need_reshard and controller is not None:
                if ckpt.latest_step() is None:
                    # nothing to rewind to: bail BEFORE the reshard
                    # commits a shrunk world the caller can't resume
                    # into
                    return None
                survivors, state = controller.reshard(state)
                resharded = True
                if data_service is not None:
                    # the dead rank's unconsumed shards reassign onto
                    # the survivors — pure math over committed state,
                    # so every survivor computes the identical new
                    # ownership
                    data_service.resize(survivors)
            restored, s0 = ckpt.restore()
            if restored is None:
                return None
            state = _retree(state, _unwrap(restored))
            if on_restore is not None:
                on_restore(state, s0)
            ok = True
            return state, s0 + 1
        finally:
            _watchdog.reset_window()
            _goodput.recovery_end(
                kind="reshard" if resharded else "restore",
                resharded=resharded,
                restored_step=s0 if ok else None,
                replay_span=max(0, hi - s0) if ok else 0, ok=ok)

    failures = 0
    i = start
    hi = start - 1  # highest batch index this incarnation completed
    try:
        with PreemptionGuard() as guard:
            while i < len(batches):
                if guard.preempted:
                    last = i - 1
                    if i > start or restored is not None:
                        _save(last)
                    _profiler.bump_elastic("preemptions",
                                           args={"step": last})
                    _goodput.note_event("preemption", step=last)
                    log.warning(
                        "elastic: preempted, checkpointed step %d",
                        last)
                    if own_run is not None:
                        _goodput.close_run(outcome="preempted")
                        own_run = None
                    return state, last, False
                if controller is not None and controller.poll():
                    # a rank died even though OUR step succeeded:
                    # reshard proactively and rewind to the newest
                    # checkpoint so every survivor resumes from the
                    # same consistent point
                    _goodput.note_event(
                        "rank_death", dead=controller.dead_ranks,
                        step=i)
                    rec = _recover(need_reshard=True)
                    if rec is None:
                        raise RuntimeError(
                            "elastic: rank(s) %s died before the first "
                            "checkpoint; nothing to reshard from"
                            % controller.dead_ranks)
                    state, i = rec
                    failures = 0
                    continue
                try:
                    # watchdog beacon: a step wedged in a dead-rank
                    # collective trips the stall detector and dumps the
                    # flight record while this loop is still blocked
                    # (re-entrant: a fused step_fn's own beacon nests)
                    if i <= hi:
                        # re-executing a step a restore rewound past:
                        # its wall time is rewind_replay badput, not
                        # compute — the run already did this work once
                        _goodput.mark_replay()
                    _watchdog.step_begin()
                    try:
                        state, _ = step_fn(state, batches[i])
                    finally:
                        _watchdog.step_end()
                    failures = 0
                except Exception as e:  # collective failure/dead rank
                    failures += 1
                    _profiler.bump_elastic("failures")
                    need_reshard = controller.handle_failure(e) \
                        if controller is not None else False
                    _goodput.note_event(
                        "step_failure", step=i, error=str(e)[:200],
                        reshard=bool(need_reshard))
                    log.warning(
                        "elastic: step %d failed (%s); recovery "
                        "%d/%d%s", i, e, failures, max_failures,
                        " [resharding]" if need_reshard else "")
                    if failures > max_failures and not need_reshard:
                        raise
                    rec = _recover(need_reshard)
                    if rec is None:
                        raise
                    state, i = rec
                    if need_reshard:
                        failures = 0
                    time.sleep(0.1 * failures)
                    continue
                hi = max(hi, i)
                if save_every and i % save_every == 0:
                    _save(i)
                i += 1
    except BaseException:
        if own_run is not None:
            _goodput.close_run(outcome="failed")
            own_run = None
        raise
    if own_run is not None:
        _goodput.close_run(outcome="completed")
    return state, len(batches) - 1, True


def _retree(template, restored):
    """Rebuild `restored` (possibly dict-of-dicts from orbax) with the
    template's pytree structure and on-device placement."""
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    r_leaves = jax.tree_util.tree_leaves(restored)
    if len(t_leaves) != len(r_leaves):
        raise ValueError("checkpoint/state structure mismatch: %d vs %d "
                         "leaves" % (len(r_leaves), len(t_leaves)))
    placed = []
    for t, r in zip(t_leaves, r_leaves):
        arr = np.asarray(r)
        sh = getattr(t, "sharding", None)
        if sh is not None and len(getattr(sh, "device_set", ())) > 1:
            # genuinely mesh-sharded template: restore onto its layout
            placed.append(jax.device_put(arr, sh))
        else:
            # single-device template: stay UNCOMMITTED (jnp.asarray), so
            # the restored state keeps feeding multi-device programs
            # (shard_map steps) exactly like the pre-failure values did
            placed.append(jax.numpy.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, placed)
