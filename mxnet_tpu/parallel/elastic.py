"""Elastic training: preemption tolerance with live resharding.

The reference's failure story stops at ps-lite heartbeats and dead-node
queries (`ref: src/kvstore/kvstore_dist.h:121 GetDeadNodes`) — SURVEY
§5 notes it has no checkpoint-based elastic recovery, and on TPU a
missing host stalls every collective rather than limping along. This
module closes the loop the blueprint calls for (ISSUE 7), welding three
previously-parallel subsystems — faultpoints, kvstore heartbeats, the
parallel stack — into one recovery cycle:

- `CheckpointManager` — crash-consistent sharded checkpoints of the
  full train state (params, optimizer state, step, rng). Incomplete
  checkpoints are *never* restore candidates: orbax step dirs must
  carry a commit marker and fallback files must unpickle; corrupt
  leftovers are pruned on the next `save()`.
- `PreemptionGuard` — SIGTERM-aware scope that chains to (and on exit
  restores) any pre-existing handler and fires at most once per
  incarnation; the loop checkpoints synchronously and exits cleanly.
- `ElasticController` — the dead-node signal → recovery weld: polls the
  kvstore heartbeat staleness table (`AsyncKVStore.dead_nodes`), and on
  a vanished rank (or a failed collective surfacing as an exception)
  drives *live resharding*: rebuild the mesh over the survivors
  (`mesh.shrink_mesh`), re-layout params per the sharding rules
  (`sharding.relayout_params`), shrink the kvstore world
  (`AsyncKVStore.resize`), and resume from the newest crash-consistent
  checkpoint.
- `HostGradReducer` — deterministic cross-process gradient reduction
  over the async-PS transport (the off-mesh fallback data plane): every
  rank sums contributions in sorted-rank order, so replicas apply
  bitwise-identical updates and survive resizes without drifting.
- `elastic_train_loop` — wraps any step function with all of the above.

Every recovery event counts into ``profiler.metrics()['elastic']`` and
drops an ``elastic:*`` instant trace marker (profiler.bump_elastic);
the `elastic.restore` / `elastic.reshard` / `collective.allreduce`
fault points make the whole cycle chaos-testable
(tests/test_faultpoints.py, tests/test_elastic.py).
"""
from __future__ import annotations

import hashlib
import hmac
import logging
import os
import pickle
import signal
import threading
import time

import jax
import numpy as np

from .. import profiler as _profiler
from .._debug import faultpoint as _faultpoint
from .._debug import flightrec as _flightrec
from .._debug import goodput as _goodput
from .._debug import watchdog as _watchdog
from .sharding import host_array
from ..base import getenv as _getenv

__all__ = ["CheckpointManager", "elastic_train_loop", "PreemptionGuard",
           "ElasticController", "HostGradReducer", "ReshardRequired",
           "shard_for_rank", "publish_peer_snapshot",
           "restore_from_peer"]

# commit marker inside an orbax step dir: present iff the save ran to
# completion (written before the atomic rename publishes the dir). A
# step dir without it — e.g. a crash between multi-host shard writes by
# a non-atomic writer — is never a restore candidate.
_COMMIT = "_COMMIT"


class ReshardRequired(RuntimeError):
    """A rank vanished and the reshard policy forbids shrinking
    (``MXTPU_ELASTIC_RESHARD=fail``): the job must stop and wait for a
    replacement instead of limping on fewer hosts."""

    def __init__(self, dead_ranks, survivors):
        self.dead_ranks = sorted(dead_ranks)
        self.survivors = sorted(survivors)
        super().__init__(
            "dead ranks %s; reshard policy 'fail' forbids shrinking to "
            "survivors %s" % (self.dead_ranks, self.survivors))


class CheckpointManager:
    """Save/restore arbitrary pytrees with a monotonically increasing step.

    Directory layout: <dir>/step_<N>/ (orbax) or <dir>/step_<N>.ckpt
    (fallback). Keeps the newest `keep` checkpoints. Crash-consistent:
    publication is temp-write + atomic rename, completeness is provable
    after the fact (commit marker / unpickle check), and `restore()`
    walks past corrupt candidates to the newest complete step.

    Zero-badput legs (ISSUE 19a):

    - ``async_persist`` (``MXTPU_CKPT_ASYNC``): ``save()`` splits into
      snapshot-then-persist. The blocking half is only the device→host
      copy — jax blocks that copy until the producing (donated) step's
      outputs are committed, which is exactly the safe point the memory
      ledger's donation-aware retirement tracks — and the temp-write +
      atomic rename + prune run on a background persist thread. At most
      ONE persist is in flight: when the writer falls behind, the next
      ``save()`` blocks on it (visible backpressure, counted as
      ``elastic.checkpoint_backpressure``) instead of queueing
      snapshots without bound. A persist failure is remembered and
      raised from the NEXT ``save()``/``flush()``; the ``checkpoint.
      persist`` faultpoint fires on the persist thread between snapshot
      and publish, so chaos tests prove a crash there loses only the
      unpublished step.
    - ``delta`` (``MXTPU_CKPT_DELTA``, pickle format only): a save
      whose pytree structure matches the previous FULL snapshot and
      whose changed-leaf fraction is ≤ 1/2 writes only the changed
      leaves plus a one-hop base reference (never delta-of-delta). The
      base step is pinned by a ``.base`` sidecar so ``_prune`` keeps it
      alive as long as any kept delta needs it.
    """

    # a delta payload referencing a base whose changed-leaf fraction
    # exceeds this writes a full snapshot instead (a delta carrying
    # most of the state costs full price plus a restore indirection)
    _DELTA_MAX_CHANGED = 0.5

    def __init__(self, directory, keep=3, use_orbax=None,
                 async_persist=None, delta=None):
        self.directory = os.path.abspath(str(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.keep = int(keep)
        if use_orbax is None:
            try:
                import orbax.checkpoint  # noqa: F401
                use_orbax = True
            except Exception:
                use_orbax = False
        self._orbax = bool(use_orbax)
        if self._orbax:
            import orbax.checkpoint as ocp
            self._ckptr = ocp.PyTreeCheckpointer()
        if async_persist is None:
            async_persist = _getenv("MXTPU_CKPT_ASYNC", "0") \
                not in ("0", "false", "off")
        self.async_persist = bool(async_persist)
        if delta is None:
            delta = _getenv("MXTPU_CKPT_DELTA", "0") \
                not in ("0", "false", "off")
        # delta dedup rides the pickle payload format; orbax step dirs
        # always hold full snapshots
        self.delta = bool(delta) and not self._orbax
        self._persist_thread = None
        self._persist_step = None   # step an in-flight persist publishes
        self._persist_exc = None    # surfaced on the next save()/flush()
        self._persist_lock = threading.Lock()
        self.backpressure_waits = 0
        # delta state: step + per-leaf digests + structure of the last
        # successfully PUBLISHED full snapshot (adopted by the persist,
        # never by the snapshot, so a failed publish can't become a base)
        self._base_step = None
        self._base_digests = None
        self._base_treedef = None

    # -- paths --------------------------------------------------------------
    def _step_path(self, step):
        name = "step_%d" % int(step)
        return os.path.join(self.directory,
                            name if self._orbax else name + ".ckpt")

    def _is_complete(self, path):
        """Cheap completeness probe — no deserialization. An orbax step
        dir is complete iff the commit marker landed before the rename
        published it; a fallback file iff it is non-empty and ends with
        the pickle STOP opcode (a truncated write — crash between
        multi-host shard writes — cannot)."""
        if os.path.isdir(path):
            return os.path.exists(os.path.join(path, _COMMIT))
        try:
            size = os.path.getsize(path)
            if size == 0:
                return False
            with open(path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                return f.read(1) == b"."
        except OSError:
            return False

    def all_steps(self):
        """Steps with a COMPLETE checkpoint, ascending. `.tmp` leftovers
        (partial save interrupted mid-write) and incomplete entries
        (missing commit marker / truncated pickle) are never restore
        candidates."""
        steps = []
        for n in os.listdir(self.directory):
            if not n.startswith("step_") or n.endswith(".tmp"):
                continue
            try:
                s = int(n[5:].split(".")[0])
            except ValueError:
                continue
            if self._is_complete(os.path.join(self.directory, n)):
                steps.append(s)
        return sorted(set(steps))

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save/restore -------------------------------------------------------
    def save(self, step, state):
        """Write `state` (pytree of arrays) for `step`; prunes old ones.

        Crash-consistent both ways: the full state lands on a `.tmp`
        sibling first and is atomically renamed into place, with the
        `checkpoint.save` fault point firing between write and rename —
        an injected (or real) crash mid-save leaves every previously
        published step restorable and at worst a `.tmp` leftover or a
        marker-less dir, which `all_steps()` never considers and the
        next `save()` prunes.

        With ``async_persist`` only the device→host snapshot (plus any
        backpressure wait on a still-running previous persist) blocks
        here; the write/rename/prune half runs on the persist thread
        and a failure there surfaces from the NEXT call."""
        t0 = time.monotonic()
        self._raise_persist_error()
        if self.async_persist:
            # at-most-one in-flight persist: block on the previous one
            # BEFORE taking the snapshot, so the backpressure wait is
            # visible badput on this save, never an unbounded queue
            t = self._persist_thread
            if t is not None and t.is_alive():
                self.backpressure_waits += 1
                _profiler.bump_elastic("checkpoint_backpressure",
                                       args={"step": int(step)})
                t.join()
                self._raise_persist_error()
        host_state = self._snapshot(state)
        job = self._encode(step, host_state)
        if not self.async_persist:
            self._persist(step, job)
            self._prune()
            dur_s = time.monotonic() - t0
            _profiler.record_op("elastic.checkpoint_save", dur_s * 1e6,
                                category="elastic", lane="user",
                                args={"step": int(step)})
            if _goodput.OPEN:
                _goodput.note_checkpoint(dur_s, "save")
            return self._step_path(step)
        with self._persist_lock:
            self._persist_step = int(step)
        th = threading.Thread(target=self._persist_bg,
                              args=(int(step), job),
                              name="mxtpu-ckpt-persist", daemon=True)
        self._persist_thread = th
        th.start()
        # only the blocking half books under 'checkpoint': the persist
        # overlaps training and reports through note_checkpoint(persist)
        dur_s = time.monotonic() - t0
        _profiler.record_op("elastic.checkpoint_snapshot", dur_s * 1e6,
                            category="elastic", lane="user",
                            args={"step": int(step)})
        if _goodput.OPEN:
            _goodput.note_checkpoint(dur_s, "save")
        return self._step_path(step)

    def _snapshot(self, state):
        """Device→host copy of every leaf. jax blocks the copy until
        the producing step's (donated) outputs are committed — the safe
        point. Async mode additionally copies host-resident numpy
        leaves (``host_array`` passes those through by reference, and
        the persist thread must never race the caller mutating them).
        The host copies register under the memory ledger's
        ``checkpoint`` tag so the extra resident set the async path
        holds while persisting is attributed, not invisible."""
        def _leaf(a):
            h = host_array(a)
            if self.async_persist and h is a \
                    and isinstance(a, np.ndarray):
                h = np.array(a, copy=True)
            return h
        host_state = jax.tree_util.tree_map(_leaf, state)
        try:
            from .. import storage as _storage
            _storage.ledger_register_tree(
                [l for l in jax.tree_util.tree_leaves(host_state)
                 if isinstance(l, np.ndarray)],
                "checkpoint", site="elastic.snapshot")
        except Exception:
            pass  # attribution only; the snapshot itself is committed
        return host_state

    @staticmethod
    def _digest(leaf):
        try:
            a = np.asarray(leaf)
            return hashlib.sha1(
                a.tobytes() + str((a.shape, a.dtype)).encode()
            ).hexdigest()
        except Exception:
            return hashlib.sha1(pickle.dumps(leaf)).hexdigest()

    def _encode(self, step, host_state):
        """Decide the payload for one save: ``(payload, kind, base,
        treedef, digests)``. Delta mode compares per-leaf digests to
        the last published FULL snapshot; a structure change or a
        changed fraction beyond the cap falls back to a full write."""
        if not self.delta:
            return (host_state, "full", None, None, None)
        leaves, treedef = jax.tree_util.tree_flatten(host_state)
        digests = [self._digest(l) for l in leaves]
        if self._base_digests is not None \
                and treedef == self._base_treedef \
                and len(digests) == len(self._base_digests):
            changed = {i: leaves[i] for i, d in enumerate(digests)
                       if d != self._base_digests[i]}
            if len(changed) <= self._DELTA_MAX_CHANGED * max(
                    1, len(leaves)):
                payload = {"__mxtpu_delta__": 1,
                           "base": int(self._base_step),
                           "n": len(leaves), "leaves": changed}
                return (payload, "delta", int(self._base_step),
                        treedef, None)
        return (host_state, "full", None, treedef, digests)

    def _write_sidecar(self, step, base):
        """Pin a delta's base step in a crash-safe ``.base`` sidecar —
        written BEFORE the delta publishes (an orphan sidecar of an
        unpublished step is inert and pruned), read by ``_prune`` so a
        kept delta's base survives the keep policy."""
        p = self._step_path(step) + ".base"
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            f.write("%d\n" % int(base))
        os.replace(tmp, p)

    def _delta_base_of(self, step):
        try:
            with open(self._step_path(step) + ".base") as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def _persist(self, step, job):
        """The durable half: temp-write + atomic rename + commit
        marker. Runs inline (sync mode) or on the persist thread."""
        payload, kind, base, treedef, digests = job
        path = self._step_path(step)
        tmp = path + ".tmp"
        try:
            if kind == "delta":
                self._write_sidecar(step, base)
            if self._orbax:
                # orbax refuses to overwrite; write then atomic-rename
                import shutil
                if os.path.exists(tmp):
                    shutil.rmtree(tmp)
                self._ckptr.save(tmp, payload)
                with open(os.path.join(tmp, _COMMIT), "w") as f:
                    f.write("%d\n" % int(step))
                if _faultpoint.ACTIVE:
                    _faultpoint.check("checkpoint.save")
                if os.path.exists(path):
                    shutil.rmtree(path)
                os.replace(tmp, path)
            else:
                with open(tmp, "wb") as f:
                    pickle.dump(payload, f)
                if _faultpoint.ACTIVE:
                    _faultpoint.check("checkpoint.save")
                os.replace(tmp, path)
        except BaseException:
            try:
                if os.path.isdir(tmp):
                    import shutil
                    shutil.rmtree(tmp)
                else:
                    os.remove(tmp)
            except OSError:
                pass
            raise
        # base bookkeeping only AFTER the publish committed — a failed
        # persist must never become the base a later delta references
        if kind == "full" and digests is not None:
            self._base_step = int(step)
            self._base_digests = digests
            self._base_treedef = treedef
        _profiler.bump_elastic("checkpoint_saves",
                               args={"step": int(step), "kind": kind})

    def _persist_bg(self, step, job):
        """Persist-thread body: faultpoint (the snapshot→persist gap —
        a crash here loses exactly one unpublished step), publish,
        clear the in-flight marker, then re-prune (the prune that was
        skipped while this step was in flight)."""
        t0 = time.monotonic()
        try:
            if _faultpoint.ACTIVE:
                _faultpoint.check("checkpoint.persist")
            self._persist(step, job)
        except BaseException as e:  # surfaced on next save()/flush()
            with self._persist_lock:
                self._persist_exc = e
                self._persist_step = None
            _profiler.bump_elastic("persist_failures",
                                   args={"step": int(step)})
            return
        with self._persist_lock:
            self._persist_step = None
        dur_s = time.monotonic() - t0
        _profiler.record_op("elastic.checkpoint_persist", dur_s * 1e6,
                            category="elastic", lane="user",
                            args={"step": int(step)})
        if _goodput.OPEN:
            _goodput.note_checkpoint(dur_s, "persist")
        self._prune()

    def flush(self, raise_error=True):
        """Block until any in-flight persist published (or failed);
        with ``raise_error`` re-raise a recorded persist failure. Call
        before relying on ``latest_step()`` durability (loop exits,
        preemption drains)."""
        t = self._persist_thread
        if t is not None:
            t.join()
            self._persist_thread = None
        if raise_error:
            self._raise_persist_error()

    def _raise_persist_error(self):
        with self._persist_lock:
            e, self._persist_exc = self._persist_exc, None
        if e is not None:
            raise RuntimeError(
                "async checkpoint persist failed: %s: %s"
                % (type(e).__name__, e)) from e

    def restore(self, step=None):
        """Load the pytree for `step` (newest when None); (None, None)
        when nothing restorable exists. With `step=None` the walk skips
        entries that fail to load (corruption the cheap completeness
        probe missed) and falls back to the next-older complete step —
        counted as ``elastic.incomplete_skipped``. An in-flight async
        persist is drained first so the newest step is visible; a
        recorded persist FAILURE does not fail the restore (the walk
        simply lands on the newest step that did publish)."""
        self.flush(raise_error=False)
        if _faultpoint.ACTIVE:
            # the restore seam: an injected raise here exercises the
            # caller's recovery path exactly where a real read failure
            # (lost filesystem, corrupt bytes) would surface
            _faultpoint.check("elastic.restore")
        t0 = time.monotonic()

        def _done(state, s):
            dur_s = time.monotonic() - t0
            _profiler.record_op("elastic.checkpoint_restore",
                                dur_s * 1e6, category="elastic",
                                lane="user", args={"step": s})
            if _goodput.OPEN:
                # inside a recovery interval the interval's own clock
                # covers this time; note_checkpoint only counts then
                _goodput.note_checkpoint(dur_s, "restore")
            return state, s

        if step is not None:
            path = self._step_path(step)
            if not self._is_complete(path):
                # same clear verdict the step=None walk gives: an
                # incomplete/marker-less candidate is NOT restorable —
                # without this probe a raw deserialize error (orbax
                # missing-file, pickle EOF) leaks instead
                raise FileNotFoundError(
                    "checkpoint step %d is incomplete or missing (%s): "
                    "no commit marker / truncated payload — it was "
                    "never published; pass step=None to restore the "
                    "newest complete step" % (int(step), path))
            state = self._load(path)
            _profiler.bump_elastic("restores", args={"step": int(step)})
            return _done(state, int(step))
        for s in reversed(self.all_steps()):
            try:
                state = self._load(self._step_path(s))
            except Exception:
                # complete-looking but unreadable (e.g. corruption past
                # the STOP byte): skip to the previous step
                _profiler.bump_elastic("incomplete_skipped",
                                       args={"step": int(s)})
                continue
            _profiler.bump_elastic("restores", args={"step": int(s)})
            return _done(state, int(s))
        return None, None

    def _load(self, path):
        if self._orbax:
            return self._ckptr.restore(path)
        with open(path, "rb") as f:
            obj = pickle.load(f)
        if isinstance(obj, dict) and obj.get("__mxtpu_delta__") == 1:
            # one-hop delta: the base is always a FULL snapshot
            with open(self._step_path(obj["base"]), "rb") as f:
                base_state = pickle.load(f)
            if isinstance(base_state, dict) \
                    and base_state.get("__mxtpu_delta__") == 1:
                raise ValueError(
                    "delta checkpoint base step %d is itself a delta "
                    "(corrupt chain; deltas are one-hop by contract)"
                    % obj["base"])
            leaves, treedef = jax.tree_util.tree_flatten(base_state)
            if len(leaves) != obj["n"]:
                raise ValueError(
                    "delta checkpoint leaf count %d does not match "
                    "base %d" % (obj["n"], len(leaves)))
            for i, v in obj["leaves"].items():
                leaves[int(i)] = v
            return jax.tree_util.tree_unflatten(treedef, leaves)
        return obj

    def _prune(self):
        """Drop steps beyond `keep` AND every incomplete leftover — a
        `.tmp` from an interrupted save, a marker-less orbax dir, a
        truncated fallback file (the crashed sibling of the step that
        just published). Two steps are NEVER touched: the step a
        concurrent async persist is about to publish (its `.tmp` is
        being written right now; the persist re-prunes on completion),
        and the full base any kept delta references (pinned by its
        `.base` sidecar — pruning it would orphan the delta)."""
        import shutil

        def _rm(p):
            try:
                if os.path.isdir(p):
                    shutil.rmtree(p)
                else:
                    os.remove(p)
            except OSError:
                pass

        with self._persist_lock:
            inflight = self._persist_step
        complete = set(self.all_steps())
        kept = set(sorted(complete)[-self.keep:]) if self.keep > 0 \
            else set(complete)
        if inflight is not None:
            kept.add(inflight)
        protected = set(kept)
        for s in list(kept):
            b = self._delta_base_of(s)
            if b is not None:
                protected.add(b)
        for n in os.listdir(self.directory):
            if not n.startswith("step_"):
                continue
            p = os.path.join(self.directory, n)
            try:
                s = int(n[5:].split(".")[0])
            except ValueError:
                continue
            if s == inflight:
                continue
            if n.endswith(".tmp"):
                _rm(p)
                continue
            if s not in complete and not n.endswith(".base"):
                _rm(p)
        for s in sorted(complete):
            if s in protected:
                continue
            _rm(self._step_path(s))
            _rm(self._step_path(s) + ".base")
        # sidecars whose step vanished (pruned above or crashed before
        # publishing) are dead weight once no kept step needs them
        for n in os.listdir(self.directory):
            if not n.endswith(".base"):
                continue
            try:
                s = int(n[5:].split(".")[0])
            except ValueError:
                continue
            if s != inflight and s not in complete:
                _rm(os.path.join(self.directory, n))


class PreemptionGuard:
    """SIGTERM-aware scope: `guard.preempted` flips when the platform
    sends the preemption notice, so the loop can checkpoint and exit
    cleanly (the TPU replacement for ps-lite heartbeats).

    Handler discipline: the scope CHAINS to any pre-existing handler
    (it still runs, exactly once, on the first signal), restores it on
    `__exit__`, and fires at most once per incarnation — repeated
    SIGTERMs while already draining do not re-enter. The handler body
    only flips flags and stamps the arrival time (time.monotonic is a
    plain syscall) before tail-calling the chained handler; it takes no
    locks (a signal interrupting a lock holder must not deadlock).

    Grace budget (ISSUE 20b): ``MXTPU_PREEMPT_GRACE_S`` (or the
    ``grace_s`` argument) is the platform's announced SIGTERM→SIGKILL
    window. ``grace_left()`` is the drain time remaining; the elastic
    loop uses it to decide whether a final checkpoint can still land
    before the kill arrives. 0 (the default) means "no known budget" —
    the pre-ISSUE-20 behavior, drain unconditionally."""

    def __init__(self, signals=(signal.SIGTERM,), grace_s=None):
        self.preempted = False
        self.preempted_at = None
        self.grace_s = float(_getenv("MXTPU_PREEMPT_GRACE_S", "0")
                             or 0) if grace_s is None else float(grace_s)
        self._fired = False
        self._signals = signals
        self._old = {}

    def grace_left(self):
        """Seconds of drain budget remaining; ``inf`` before the signal
        arrived or when no budget is configured."""
        if self.preempted_at is None or self.grace_s <= 0:
            return float("inf")
        return self.grace_s - (time.monotonic() - self.preempted_at)

    def _handler(self, signum, frame):
        if self.preempted_at is None:
            self.preempted_at = time.monotonic()
        self.preempted = True
        if self._fired:
            return
        self._fired = True
        old = self._old.get(signum)
        if callable(old):
            # chain: whatever the host process installed before us
            # (its own drain logic) still observes the signal
            old(signum, frame)

    def __enter__(self):
        for s in self._signals:
            try:
                self._old[s] = signal.signal(s, self._handler)
            except (ValueError, OSError):
                pass  # non-main thread: stay polling-only
        return self

    def __exit__(self, *exc):
        for s, old in self._old.items():
            try:
                signal.signal(s, old)
            except (ValueError, OSError):
                pass
        self._old.clear()
        return False


class ElasticController:
    """The dead-node signal → recovery weld (ISSUE 7 tentpole a).

    Owns the job's view of the live world: polls the kvstore heartbeat
    staleness table (`AsyncKVStore.dead_nodes`, rate-limited by
    ``MXTPU_ELASTIC_POLL_S``), classifies step failures, and drives the
    reshard: shrink the kvstore world, rebuild mesh + re-layout params
    through ``reshard_fn``, and hand the loop back to the newest
    checkpoint. Reshard policy ``MXTPU_ELASTIC_RESHARD``:

    - ``shrink`` (default): continue on the survivors
    - ``fail``: raise :class:`ReshardRequired` (wait for a replacement)
    """

    def __init__(self, kvstore=None, world=None, rank=None,
                 poll_interval=None, dead_timeout=None,
                 reshard_policy=None, reshard_fn=None, logger=None):
        self.kv = kvstore
        if rank is None:
            rank = int(_getenv("MXTPU_PROC_ID", "0") or 0)
        self.rank = int(rank)
        if world is None:
            n = getattr(kvstore, "num_workers", 1) if kvstore else 1
            world = range(int(n))
        self.world = sorted(int(r) for r in world)
        self.poll_interval = float(
            _getenv("MXTPU_ELASTIC_POLL_S", "1.0")
            if poll_interval is None else poll_interval)
        self.dead_timeout = float(
            _getenv("MXTPU_PS_DEAD_TIMEOUT", "3.0")
            if dead_timeout is None else dead_timeout)
        self.reshard_policy = (
            _getenv("MXTPU_ELASTIC_RESHARD", "shrink")
            if reshard_policy is None else reshard_policy)
        if self.reshard_policy not in ("shrink", "fail"):
            raise ValueError(
                "MXTPU_ELASTIC_RESHARD must be 'shrink' or 'fail', got "
                "%r" % (self.reshard_policy,))
        self.reshard_fn = reshard_fn
        self._dead = set()
        self._last_poll = 0.0
        self._log = logger or logging.getLogger("mxnet_tpu.elastic")
        self._publish_world()

    def _publish_world(self):
        """Publish the committed world view into the flight recorder's
        dump context: a post-mortem shard then names the job topology —
        world, survivors, known-dead — at the instant of death."""
        _flightrec.set_context("elastic_world", {
            "rank": self.rank,
            "world": list(self.world),
            "dead": sorted(self._dead),
            "survivors": self.survivors,
            "reshard_policy": self.reshard_policy,
        })

    @property
    def dead_ranks(self):
        return sorted(self._dead)

    @property
    def survivors(self):
        return sorted(set(self.world) - self._dead)

    def poll(self, force=False):
        """Query the heartbeat staleness table (rate-limited unless
        ``force``); returns the NEWLY dead ranks. The kvstore side
        counts ``elastic.dead_rank_detected`` and drops the trace
        marker the moment the set grows, so the controller and
        operators see the same signal."""
        if self.kv is None:
            return []
        now = time.monotonic()
        if not force and now - self._last_poll < self.poll_interval:
            return []
        self._last_poll = now
        try:
            dead = self.kv.dead_nodes(self.dead_timeout)
        except Exception as e:  # server unreachable: no verdict yet
            self._log.warning("elastic: dead-node poll failed (%s)", e)
            return []
        new = sorted(set(int(r) for r in dead) - self._dead
                     - {self.rank})
        if new:
            self._dead.update(new)
            self._log.warning("elastic: dead ranks detected: %s "
                              "(survivors %s)", new, self.survivors)
            self._publish_world()
        # only deaths inside the COMMITTED world are actionable — same
        # guard handle_failure applies: a rank already resharded away,
        # or one outside this controller's world (a sub-world scoped
        # over a shared PS), must not trigger another reshard-and-rewind
        in_world = set(self.world)
        return [r for r in new if r in in_world]

    def handle_failure(self, exc):
        """Classify a step failure: force a dead-node poll and report
        whether resharding (vs plain restore-and-retry) is the right
        recovery. A failed collective with every rank alive is a
        transient — retry; with a dead rank it is structural —
        reshard."""
        self.poll(force=True)
        # only ranks still in the COMMITTED world warrant a reshard; a
        # rank already resharded away must not re-trigger on the next
        # transient failure
        return bool(self._dead & set(self.world))

    def reshard(self, state=None):
        """Commit the world shrink: user ``reshard_fn(state,
        survivors)`` for mesh rebuild + param re-layout, THEN kvstore
        resize. Everything that can refuse — the policy check, the
        faultpoint, ``reshard_fn`` (e.g. ``shrink_mesh`` raising because
        a model axis no longer divides the survivors) — runs before any
        side effect, so a failed reshard leaves the committed world
        (kvstore size, ``self.world``, the counter) untouched. Returns
        (survivors, possibly-new state)."""
        if _faultpoint.ACTIVE:
            _faultpoint.check("elastic.reshard")
        survivors = self.survivors
        if not survivors or self.rank not in survivors:
            raise ReshardRequired(self.dead_ranks, survivors)
        if self.reshard_policy == "fail":
            raise ReshardRequired(self.dead_ranks, survivors)
        if self.reshard_fn is not None:
            new_state = self.reshard_fn(state, survivors)
            if new_state is not None:
                state = new_state
        if self.kv is not None and hasattr(self.kv, "resize"):
            self.kv.resize(len(survivors))
        _profiler.bump_elastic(
            "reshards", args={"survivors": survivors,
                              "dead": self.dead_ranks})
        self._log.warning("elastic: resharded onto %s (world was %s)",
                          survivors, self.world)
        self.world = survivors
        self._publish_world()
        return survivors, state


class HostGradReducer:
    """Deterministic cross-process gradient reduction over the async-PS
    transport — the off-mesh/elastic fallback data plane (the in-mesh
    bucketed overlap of ``parallel/overlap.py`` covers the devices one
    jax process owns; this covers processes that must survive each
    other's deaths).

    Protocol per step: push the local (already in-mesh-reduced) flat
    gradient under a per-rank key, barrier, pull every live rank's
    contribution and sum IN SORTED RANK ORDER, barrier again (fences
    this step's pulls from the next step's overwrites). Every rank
    computes the identical f32 sum, so replicas apply bitwise-identical
    updates and never drift — the property the elastic chaos test pins.

    A dead rank surfaces as a barrier abort naming the stale ranks (the
    PR 5 heartbeat autopsy) — never a hang — and the elastic loop
    reshards; with a world of one the wire is skipped entirely.

    Precondition: the transport must carry NO server-side optimizer
    (``set_optimizer``) — the server applies its updater to every
    pushed key, which would silently turn the reducer's per-rank
    scratch keys into optimizer-mangled values instead of raw
    gradients. Enforced per call."""

    def __init__(self, kvstore, name="elastic.grad"):
        self.kv = kvstore
        self._name = name

    def _key(self, rank):
        return "%s:%d" % (self._name, int(rank))

    def allreduce(self, flat, world, rank):
        """Sum one flat f32 vector across ``world`` (sorted ranks).
        Returns the identical total on every rank."""
        if _faultpoint.ACTIVE:
            # the collective seam: a failed cross-host reduction
            # surfaces here as an exception, exactly what the elastic
            # loop classifies and recovers from
            _faultpoint.check("collective.allreduce")
        if getattr(self.kv, "_optimizer", None) is not None:
            raise RuntimeError(
                "HostGradReducer needs a raw store-replace transport, "
                "but this kvstore has a server-side optimizer "
                "(set_optimizer): pushes to the reducer's scratch keys "
                "would be optimizer-applied, not stored — use a "
                "dedicated kvstore with no optimizer for the reducer")
        host = np.asarray(flat, np.float32).ravel()
        world = sorted(int(r) for r in world)
        if len(world) <= 1:
            return host
        import mxnet_tpu.ndarray as nd
        t0 = time.perf_counter() if _profiler._LIVE else None
        self.kv.push(self._key(rank), nd.array(host))
        self.kv._barrier()
        total = None
        out = nd.zeros(host.shape)
        for r in world:
            self.kv.pull(self._key(r), out=out)
            a = out.asnumpy().astype(np.float32, copy=False)
            total = a.copy() if total is None else total + a
        self.kv._barrier()
        if t0 is not None:
            _profiler.record_op(
                "elastic.host_allreduce",
                (time.perf_counter() - t0) * 1e6,
                category="kvstore", lane="kvstore",
                args={"world": len(world), "bytes": int(host.nbytes)})
        return total


def shard_for_rank(n_items, world, rank):
    """Deterministic contiguous split of ``n_items`` over the sorted
    live world — the data-assignment half of resharding. A pure
    function of ``(n_items, world, rank)``, so the assignment is
    epoch-reproducible under elastic resize: survivors agree on the new
    split without talking. Returns ``range(start, stop)``."""
    world = sorted(int(r) for r in world)
    idx = world.index(int(rank))
    n = len(world)
    base, extra = divmod(int(n_items), n)
    start = idx * base + min(idx, extra)
    stop = start + base + (1 if idx < extra else 0)
    return range(start, stop)


def _peer_restore_enabled():
    return _getenv("MXTPU_PEER_RESTORE", "0") not in ("0", "false",
                                                      "off")


def _snapshot_secret():
    s = _getenv("MXTPU_PS_SECRET", "")
    return s.encode() if s else None


def publish_peer_snapshot(kv, step, state):
    """Publish this rank's state to the kvstore snapshot table (ISSUE
    19c) so a recovering peer can restore from our in-memory replica
    instead of the filesystem. Best-effort: the blob is the pickled
    host-staged payload with an HMAC-SHA256 prefix under
    ``MXTPU_PS_SECRET`` (the ``set_optimizer`` authentication idiom —
    the server never unpickles, only the restoring CLIENT does, after
    verifying the MAC). Returns True on publish; every failure path is
    counted, never raised — losing a snapshot costs a fallback to the
    filesystem, not the step."""
    put = getattr(kv, "publish_snapshot", None)
    secret = _snapshot_secret()
    if put is None or secret is None:
        return False
    try:
        host = jax.tree_util.tree_map(host_array, state)
        body = pickle.dumps((int(step), host),
                            protocol=pickle.HIGHEST_PROTOCOL)
        mac = hmac.new(secret, body, hashlib.sha256).digest()
        put(int(step), mac + body)
        return True
    except Exception:
        _profiler.bump_elastic("peer_snapshot_failures",
                               args={"step": int(step)})
        return False


def restore_from_peer(kv):
    """Ask a live peer for its newest published snapshot; returns
    ``(state, step)`` or ``None`` (fall back to the filesystem —
    counted as ``elastic.peer_restore_fallbacks``). ``None`` covers an
    old server without the snapshot opcode (its ``_RE_ERR`` reply
    surfaces as the RuntimeError caught here — the v0/v1 interop
    contract), no live publisher, and a MAC mismatch (an
    unauthenticated blob must never reach ``pickle.loads``)."""
    get = getattr(kv, "peer_snapshot", None)
    secret = _snapshot_secret()
    if get is None or secret is None:
        return None

    def _fallback(why):
        _profiler.bump_elastic("peer_restore_fallbacks",
                               args={"why": why})
        return None

    try:
        got = get()
    except Exception:
        return _fallback("transport")
    if not got:
        return _fallback("no_snapshot")
    peer_rank, step, blob = got
    mac, body = bytes(blob[:32]), bytes(blob[32:])
    want = hmac.new(secret, body, hashlib.sha256).digest()
    if not hmac.compare_digest(mac, want):
        return _fallback("hmac_mismatch")
    try:
        sstep, host = pickle.loads(body)
    except Exception:
        return _fallback("decode")
    _profiler.bump_elastic("peer_restores",
                           args={"step": int(sstep),
                                 "peer": int(peer_rank)})
    return host, int(sstep)


def elastic_train_loop(step_fn, state, batches, ckpt, save_every=100,
                       max_failures=3, on_restore=None, logger=None,
                       controller=None, data_service=None):
    """Run `state, metrics = step_fn(state, batch)` over `batches` with
    checkpoint-based recovery and (optionally) live resharding.

    - every `save_every` steps: `ckpt.save(step, state)` (set
      ``MXTPU_ELASTIC_CKPT_EVERY`` to override a ``save_every=None``)
    - on an exception (failed collective, restarted backend): restore
      the newest checkpoint, skip already-done steps, continue; gives up
      after `max_failures` consecutive failures *unless* the
      ``controller`` attributes the failure to a dead rank, in which
      case the world is resharded onto the survivors first
    - with a ``controller``: every iteration polls the dead-node table
      (rate-limited), so a vanished rank triggers resharding even when
      this rank's own step did not fail
    - on SIGTERM: save synchronously and return early with the state
    - with a ``data_service`` (``io.ShardService``): the service's
      sample cursor is **embedded in every checkpoint payload**
      (``cursor_for_checkpoint``/``apply_cursor``), so ONE atomic
      temp+rename publishes params@step and cursor@step together — a
      crash at any instant leaves either both or neither, never a torn
      pair that would replay already-trained samples; the service is
      resized onto the survivors after every reshard — the ISSUE 11
      weld that keeps the global sample sequence intact across a
      mid-epoch rank death

    `batches` must be re-iterable (a list or a factory-backed sequence)
    so recovery can rewind. Returns (state, last_step, completed: bool).
    """
    log = logger or logging.getLogger("mxnet_tpu.elastic")
    if save_every is None:
        save_every = int(_getenv("MXTPU_ELASTIC_CKPT_EVERY",
                                        "100"))
    batches = list(batches)

    # run-level goodput ledger (ISSUE 14): the loop brackets the run,
    # so every second between here and the return is attributed. An
    # already-open run (an outer harness opened one) is left alone.
    run_meta = {"loop": "elastic_train_loop", "batches": len(batches),
                "save_every": int(save_every or 0)}
    if controller is not None:
        run_meta["world"] = list(controller.world)
        run_meta["rank"] = controller.rank
    own_run = _goodput.open_run(meta=run_meta) \
        if _goodput.ENABLED and not _goodput.is_open() else None

    def _unwrap(restored):
        """Split a restored payload: adopt the embedded data cursor
        (when present) and return the bare train state. Pre-weld
        checkpoints (no wrapper) pass through unchanged."""
        if isinstance(restored, dict) \
                and "__data_cursor__" in restored:
            if data_service is not None:
                data_service.apply_cursor(restored["__data_cursor__"])
            return restored["__elastic_state__"]
        return restored

    start = 0
    # resuming a previous incarnation IS recovery badput: the interval
    # (restore probe + load + re-layout) books under 'recovery' when a
    # checkpoint existed, and is discarded when this is a fresh run. A
    # restore that RAISES (the elastic.restore faultpoint, a lost
    # filesystem) must still close the run it opened — a leaked-open
    # run would suppress every later loop's manifest in this process
    try:
        _goodput.recovery_begin()
        restored, step0 = ckpt.restore()
        if restored is not None:
            state = _retree(state, _unwrap(restored))
            start = step0 + 1
            if on_restore is not None:
                on_restore(state, step0)
            _watchdog.reset_window()
            _goodput.recovery_end(kind="resume", restored_step=step0)
            log.info("elastic: resumed from checkpoint step %d", step0)
        else:
            _goodput.recovery_end(count=False)
    except BaseException:
        # book the failed attempt's wall time as recovery (no-op when
        # the interval already ended) and publish the failed run
        _goodput.recovery_end(kind="resume", ok=False)
        if own_run is not None:
            _goodput.close_run(outcome="failed")
        raise

    # peer-restore (ISSUE 19c): publish this rank's state to the
    # kvstore snapshot table so a recovering peer restores from a live
    # replica before touching the filesystem. Cadence defaults to every
    # step — the publish is one wire round trip of host-staged state,
    # cheap next to a durable write, and a tight cadence is what
    # shrinks the peer path's rewind_replay below the filesystem's.
    peer_kv = controller.kv if controller is not None else None
    peer_on = _peer_restore_enabled() and peer_kv is not None \
        and hasattr(peer_kv, "publish_snapshot")
    peer_every = max(1, int(_getenv("MXTPU_PEER_SNAPSHOT_EVERY", "1")))

    def _payload():
        if data_service is not None:
            # the cursor rides INSIDE the params payload: one
            # temp+rename publishes both, so no crash instant can
            # leave params@step paired with an older cursor (which
            # would replay already-trained samples on resume)
            return {"__elastic_state__": state,
                    "__data_cursor__":
                        data_service.cursor_for_checkpoint()}
        return state

    def _save(step):
        ckpt.save(step, _payload())

    def _flush_ckpt():
        # async-persist drain at loop exits: the durability point the
        # caller observes. Failures are logged, never raised over the
        # loop's own exit path.
        fl = getattr(ckpt, "flush", None)
        if callable(fl):
            try:
                fl()
            except Exception as e:
                log.warning("elastic: checkpoint flush failed: %s", e)

    def _recover(need_reshard):
        """Reshard (when attributed to a dead rank) then rewind to the
        newest checkpoint; returns (state, next index) or None when no
        checkpoint exists (caller re-raises the original error).

        The whole interval — policy check, reshard, restore, re-layout
        — is one goodput 'recovery' span, and the watchdog's rolling
        median window resets on the way out: durations from the old
        world size must not police the resized world's cadence."""
        nonlocal state
        _goodput.recovery_begin()
        resharded = False
        via_peer = False
        s0 = None
        ok = False
        try:
            can_rewind = ckpt.latest_step() is not None \
                or (peer_on and hi >= start)
            if need_reshard and controller is not None:
                if not can_rewind:
                    # nothing to rewind to: bail BEFORE the reshard
                    # commits a shrunk world the caller can't resume
                    # into
                    return None
                survivors, state = controller.reshard(state)
                resharded = True
                if data_service is not None:
                    # the dead rank's unconsumed shards reassign onto
                    # the survivors — pure math over committed state,
                    # so every survivor computes the identical new
                    # ownership
                    data_service.resize(survivors)
            restored = None
            if peer_on:
                # a live peer's in-memory replica beats the filesystem
                # twice: no durable-read latency, and a tighter publish
                # cadence rewinds fewer steps. Every miss falls back to
                # the filesystem, counted.
                got = restore_from_peer(peer_kv)
                if got is not None:
                    restored, s0 = got
                    via_peer = True
            if restored is None:
                restored, s0 = ckpt.restore()
            if restored is None:
                return None
            state = _retree(state, _unwrap(restored))
            if on_restore is not None:
                on_restore(state, s0)
            ok = True
            return state, s0 + 1
        finally:
            _watchdog.reset_window()
            _goodput.recovery_end(
                kind="peer" if via_peer
                else ("reshard" if resharded else "restore"),
                resharded=resharded,
                restored_step=s0 if ok else None,
                replay_span=max(0, hi - s0) if ok else 0, ok=ok)

    failures = 0
    i = start
    hi = start - 1  # highest batch index this incarnation completed
    try:
        with PreemptionGuard() as guard:
            while i < len(batches):
                if guard.preempted:
                    # the in-flight fused step already finished — the
                    # guard is checked between steps, so the drain
                    # below always starts from a step boundary
                    last = i - 1
                    grace = guard.grace_left()
                    # coordinated drain (ISSUE 20b), cheapest-first:
                    # 1) broadcast the preemption notice so peers'
                    #    dead-node polls see this rank NOW and reshard
                    #    proactively instead of burning the heartbeat
                    #    timeout (one wire round trip, never raises);
                    if peer_kv is not None and hasattr(
                            peer_kv, "announce_preemption"):
                        acked = peer_kv.announce_preemption(last)
                        _profiler.bump_elastic(
                            "preempt_notices", args={"step": last,
                                                     "acked": acked})
                    if i > start or restored is not None:
                        # 2) make the exit durable while the grace
                        #    budget lasts: final checkpoint + the peer
                        #    snapshot a survivor can restore from
                        #    without touching the filesystem. With the
                        #    budget already blown (grace <= 0) the
                        #    save is SKIPPED — a SIGKILL mid-publish
                        #    would tear it, and the previous published
                        #    step is the safer resume point.
                        if grace > 0:
                            _save(last)
                            if peer_on:
                                publish_peer_snapshot(peer_kv, last,
                                                      _payload())
                            _flush_ckpt()  # the exit must be durable
                        else:
                            _profiler.bump_elastic(
                                "preempt_grace_exhausted",
                                args={"step": last})
                    _profiler.bump_elastic("preemptions",
                                           args={"step": last})
                    _goodput.note_event(
                        "preemption", step=last,
                        grace_s=None if grace == float("inf")
                        else round(grace, 3))
                    log.warning(
                        "elastic: preempted, checkpointed step %d",
                        last)
                    if own_run is not None:
                        _goodput.close_run(outcome="preempted")
                        own_run = None
                    return state, last, False
                if controller is not None and controller.poll():
                    # a rank died even though OUR step succeeded:
                    # reshard proactively and rewind to the newest
                    # checkpoint so every survivor resumes from the
                    # same consistent point
                    _goodput.note_event(
                        "rank_death", dead=controller.dead_ranks,
                        step=i)
                    rec = _recover(need_reshard=True)
                    if rec is None:
                        raise RuntimeError(
                            "elastic: rank(s) %s died before the first "
                            "checkpoint; nothing to reshard from"
                            % controller.dead_ranks)
                    state, i = rec
                    failures = 0
                    continue
                try:
                    # watchdog beacon: a step wedged in a dead-rank
                    # collective trips the stall detector and dumps the
                    # flight record while this loop is still blocked
                    # (re-entrant: a fused step_fn's own beacon nests)
                    if i <= hi:
                        # re-executing a step a restore rewound past:
                        # its wall time is rewind_replay badput, not
                        # compute — the run already did this work once
                        _goodput.mark_replay()
                    _watchdog.step_begin()
                    try:
                        state, _ = step_fn(state, batches[i])
                    finally:
                        _watchdog.step_end()
                    failures = 0
                except Exception as e:  # collective failure/dead rank
                    failures += 1
                    _profiler.bump_elastic("failures")
                    need_reshard = controller.handle_failure(e) \
                        if controller is not None else False
                    _goodput.note_event(
                        "step_failure", step=i, error=str(e)[:200],
                        reshard=bool(need_reshard))
                    log.warning(
                        "elastic: step %d failed (%s); recovery "
                        "%d/%d%s", i, e, failures, max_failures,
                        " [resharding]" if need_reshard else "")
                    if failures > max_failures and not need_reshard:
                        raise
                    rec = _recover(need_reshard)
                    if rec is None:
                        raise
                    state, i = rec
                    if need_reshard:
                        failures = 0
                    time.sleep(0.1 * failures)
                    continue
                hi = max(hi, i)
                if save_every and i % save_every == 0:
                    _save(i)
                if peer_on and i % peer_every == 0:
                    publish_peer_snapshot(peer_kv, i, _payload())
                i += 1
    except BaseException:
        if own_run is not None:
            _goodput.close_run(outcome="failed")
            own_run = None
        raise
    _flush_ckpt()
    if own_run is not None:
        _goodput.close_run(outcome="completed")
    return state, len(batches) - 1, True


def _retree(template, restored):
    """Rebuild `restored` (possibly dict-of-dicts from orbax) with the
    template's pytree structure and on-device placement."""
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    r_leaves = jax.tree_util.tree_leaves(restored)
    if len(t_leaves) != len(r_leaves):
        raise ValueError("checkpoint/state structure mismatch: %d vs %d "
                         "leaves" % (len(r_leaves), len(t_leaves)))
    placed = []
    for t, r in zip(t_leaves, r_leaves):
        arr = np.asarray(r)
        sh = getattr(t, "sharding", None)
        if sh is not None and len(getattr(sh, "device_set", ())) > 1:
            # genuinely mesh-sharded template: restore onto its layout
            placed.append(jax.device_put(arr, sh))
        else:
            # single-device template: stay UNCOMMITTED (jnp.asarray), so
            # the restored state keeps feeding multi-device programs
            # (shard_map steps) exactly like the pre-failure values did
            placed.append(jax.numpy.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, placed)
