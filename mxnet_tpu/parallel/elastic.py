"""Elastic training: checkpoint-based failure recovery + preemption save.

The reference's failure story is ps-lite heartbeats only — dead-node
queries (`ref: src/kvstore/kvstore_dist.h:121 GetDeadNodes`) and
recovered-server rejoin guards (`ref: kvstore_dist.h:52
ps::Postoffice::is_recovery`); SURVEY §5 notes it has **no**
checkpoint-based elastic recovery. This module provides the TPU-native
upgrade the blueprint calls for:

- `CheckpointManager` — periodic sharded checkpoints of the full train
  state (params, optimizer state, step, rng), orbax-backed when available
  (async, multi-host safe) with a pure-numpy fallback.
- `elastic_train_loop` — wraps any step function: on an exception from a
  failed collective/restart it restores the newest checkpoint and resumes;
  on SIGTERM (TPU preemption notice) it checkpoints synchronously before
  exiting, so the next incarnation continues where it stopped.

On Cloud TPU, preemption delivers SIGTERM ahead of the VM going away —
checkpoint-on-signal plus restore-on-restart IS the elastic recovery
model; there is no ICI analog of a parameter server limping along without
one worker, because a missing chip stalls every collective.
"""
from __future__ import annotations

import logging
import os
import pickle
import signal
import time

import jax
import numpy as np

__all__ = ["CheckpointManager", "elastic_train_loop", "PreemptionGuard"]


class CheckpointManager:
    """Save/restore arbitrary pytrees with a monotonically increasing step.

    Directory layout: <dir>/step_<N>/ (orbax) or <dir>/step_<N>.ckpt
    (fallback). Keeps the newest `keep` checkpoints.
    """

    def __init__(self, directory, keep=3, use_orbax=None):
        self.directory = os.path.abspath(str(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.keep = int(keep)
        if use_orbax is None:
            try:
                import orbax.checkpoint  # noqa: F401
                use_orbax = True
            except Exception:
                use_orbax = False
        self._orbax = bool(use_orbax)
        if self._orbax:
            import orbax.checkpoint as ocp
            self._ckptr = ocp.PyTreeCheckpointer()

    # -- paths --------------------------------------------------------------
    def _step_path(self, step):
        name = "step_%d" % int(step)
        return os.path.join(self.directory,
                            name if self._orbax else name + ".ckpt")

    def all_steps(self):
        steps = []
        for n in os.listdir(self.directory):
            if not n.startswith("step_") or n.endswith(".tmp"):
                # .tmp = partial save interrupted mid-write; never a
                # restore candidate
                continue
            try:
                steps.append(int(n[5:].split(".")[0]))
            except ValueError:
                pass
        return sorted(set(steps))

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save/restore -------------------------------------------------------
    def save(self, step, state):
        """Write `state` (pytree of arrays) for `step`; prunes old ones.

        Crash-consistent both ways: the full state lands on a `.tmp`
        sibling first and is atomically renamed into place, with the
        `checkpoint.save` fault point firing between write and rename —
        an injected (or real) crash mid-save leaves every previously
        published step restorable and at worst a `.tmp` leftover, which
        `all_steps()` never considers a restore candidate."""
        from .._debug import faultpoint as _faultpoint
        path = self._step_path(step)
        tmp = path + ".tmp"
        try:
            if self._orbax:
                # orbax refuses to overwrite; write then atomic-rename
                import shutil
                if os.path.exists(tmp):
                    shutil.rmtree(tmp)
                self._ckptr.save(tmp, jax.tree_util.tree_map(np.asarray,
                                                             state))
                if _faultpoint.ACTIVE:
                    _faultpoint.check("checkpoint.save")
                if os.path.exists(path):
                    shutil.rmtree(path)
                os.replace(tmp, path)
            else:
                with open(tmp, "wb") as f:
                    pickle.dump(jax.tree_util.tree_map(np.asarray, state),
                                f)
                if _faultpoint.ACTIVE:
                    _faultpoint.check("checkpoint.save")
                os.replace(tmp, path)
        except BaseException:
            try:
                if os.path.isdir(tmp):
                    import shutil
                    shutil.rmtree(tmp)
                else:
                    os.remove(tmp)
            except OSError:
                pass
            raise
        self._prune()
        return path

    def restore(self, step=None):
        """Load the pytree for `step` (newest when None); None if empty."""
        if step is None:
            step = self.latest_step()
            if step is None:
                return None, None
        path = self._step_path(step)
        if self._orbax:
            state = self._ckptr.restore(path)
        else:
            with open(path, "rb") as f:
                state = pickle.load(f)
        return state, int(step)

    def _prune(self):
        steps = self.all_steps()
        for s in steps[:-self.keep] if self.keep > 0 else []:
            p = self._step_path(s)
            try:
                if os.path.isdir(p):
                    import shutil
                    shutil.rmtree(p)
                else:
                    os.remove(p)
            except OSError:
                pass


class PreemptionGuard:
    """SIGTERM-aware scope: `guard.preempted` flips when the platform
    sends the preemption notice, so the loop can checkpoint and exit
    cleanly (the TPU replacement for ps-lite heartbeats)."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self.preempted = False
        self._signals = signals
        self._old = {}

    def __enter__(self):
        def handler(signum, frame):
            self.preempted = True
        for s in self._signals:
            try:
                self._old[s] = signal.signal(s, handler)
            except (ValueError, OSError):
                pass  # non-main thread: stay polling-only
        return self

    def __exit__(self, *exc):
        for s, old in self._old.items():
            try:
                signal.signal(s, old)
            except (ValueError, OSError):
                pass
        return False


def elastic_train_loop(step_fn, state, batches, ckpt, save_every=100,
                       max_failures=3, on_restore=None, logger=None):
    """Run `state, metrics = step_fn(state, batch)` over `batches` with
    checkpoint-based recovery.

    - every `save_every` steps: `ckpt.save(step, state)`
    - on an exception (failed collective, restarted backend): restore the
      newest checkpoint, skip already-done steps, continue; gives up after
      `max_failures` consecutive failures
    - on SIGTERM: save synchronously and return early with the state

    `batches` must be re-iterable (a list or a factory-backed sequence) so
    recovery can rewind. Returns (state, last_step, completed: bool).
    """
    log = logger or logging.getLogger("mxnet_tpu.elastic")
    batches = list(batches)
    start = 0
    restored, step0 = ckpt.restore()
    if restored is not None:
        state = _retree(state, restored)
        start = step0 + 1
        if on_restore is not None:
            on_restore(state, step0)
        log.info("elastic: resumed from checkpoint step %d", step0)

    failures = 0
    i = start
    with PreemptionGuard() as guard:
        while i < len(batches):
            if guard.preempted:
                ckpt.save(i - 1, state)
                log.warning("elastic: preempted, checkpointed step %d",
                            i - 1)
                return state, i - 1, False
            try:
                state, _ = step_fn(state, batches[i])
                failures = 0
            except Exception as e:  # collective failure / device restart
                failures += 1
                log.warning("elastic: step %d failed (%s); recovery %d/%d",
                            i, e, failures, max_failures)
                if failures > max_failures:
                    raise
                restored, step0 = ckpt.restore()
                if restored is None:
                    raise
                state = _retree(state, restored)
                i = step0 + 1
                time.sleep(0.1 * failures)
                continue
            if save_every and i % save_every == 0:
                ckpt.save(i, state)
            i += 1
    return state, len(batches) - 1, True


def _retree(template, restored):
    """Rebuild `restored` (possibly dict-of-dicts from orbax) with the
    template's pytree structure and on-device placement."""
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    r_leaves = jax.tree_util.tree_leaves(restored)
    if len(t_leaves) != len(r_leaves):
        raise ValueError("checkpoint/state structure mismatch: %d vs %d "
                         "leaves" % (len(r_leaves), len(t_leaves)))
    placed = []
    for t, r in zip(t_leaves, r_leaves):
        arr = np.asarray(r)
        if hasattr(t, "sharding"):
            placed.append(jax.device_put(arr, t.sharding))
        else:
            placed.append(jax.numpy.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, placed)
