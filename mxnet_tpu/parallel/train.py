"""Sharded training step: gluon Block + optimizer -> one pjit'd update.

This is the TPU answer to the reference's whole update pipeline —
Module.update -> kvstore.push/pull -> Comm reduce / ps-lite -> optimizer on
server (ref: python/mxnet/model.py:150 _update_params_on_kvstore,
src/kvstore/kvstore_dist_server.h:346 ApplyUpdates). One jitted function
computes forward, backward, gradient psum over 'dp' (GSPMD-inserted), and
the optimizer update against sharded state, with parameter buffers donated
(≙ in-place server update).

Any registered mxnet_tpu optimizer works unchanged inside the jit: its
`update(i, weight, grad, state)` mutates NDArray wrappers whose `._data`
are tracers — the functional-core/imperative-shell trick.
"""
from __future__ import annotations

import time as _time

import jax
import jax.numpy as jnp

from ..ndarray import NDArray
from .. import autograd
from .. import profiler as _profiler
from ..gluon.block import Block
from .. import random as _random
from .sharding import ShardingStrategy, data_parallel

__all__ = ["functional_call", "extract_params", "attach_params",
           "ShardedTrainStep"]


def extract_params(block):
    """{structural_path: jax.Array} for all params of a Block (stable keys,
    same space as save_parameters)."""
    out = {}
    for path, p in block._collect_params_with_prefix().items():
        out[path] = p.data()._data
    return out


def attach_params(block, params):
    """Write a {path: array} pytree back into the Block's parameters."""
    pmap = block._collect_params_with_prefix()
    for path, arr in params.items():
        pmap[path].data()._data = arr


def functional_call(block, params, inputs, training=False, rng=None,
                    return_aux=False):
    """Run `block` as a PURE function of (params, inputs).

    Implementation: temporarily swap `params[path]` arrays into the Block's
    parameters, run the eager forward (which traces into whatever jit is
    active), then restore. Aux-state writes (BatchNorm moving stats) are
    captured and returned instead of applied when `return_aux`.
    """
    from ..gluon.block import _AUX
    pmap = block._collect_params_with_prefix()
    originals = {}
    for path, arr in params.items():
        p = pmap[path]
        originals[path] = p.data()._data
        p.data()._data = arr
    if rng is None:
        rng = _random.next_key()
    _random.push_trace_key(rng)
    collected = []
    _AUX.stack.append(collected)
    prev_rec = autograd.set_recording(False)
    prev_train = autograd.set_training(training)
    try:
        nd_inputs = [x if isinstance(x, NDArray) else NDArray(jnp.asarray(x))
                     for x in (inputs if isinstance(inputs, (tuple, list))
                               else [inputs])]
        out = Block.__call__(block, *nd_inputs)
    finally:
        autograd.set_training(prev_train)
        autograd.set_recording(prev_rec)
        _AUX.stack.pop()
        _random.pop_trace_key()
        for path, arr in originals.items():
            pmap[path].data()._data = arr
    outs = out if isinstance(out, (tuple, list)) else (out,)
    res = tuple(o._data for o in outs)
    res = res[0] if len(res) == 1 else res
    if return_aux:
        inv = {id(p): path for path, p in pmap.items()}
        aux = {}
        for p, new in collected:
            path = inv.get(id(p))
            if path is not None:
                aux[path] = new
        return res, aux
    return res


class ShardedTrainStep:
    """Compiled distributed training step.

    step = ShardedTrainStep(net, loss_fn, optimizer, strategy)
    loss = step(x, y)        # runs ONE fused XLA program on the mesh

    - params live as a sharded pytree (strategy.param_rules)
    - gradients reduce over strategy.grad_reduce_axes via GSPMD
    - optimizer state is created per-param and sharded like the param
      (so FSDP automatically gives ZeRO-sharded optimizer state)
    - batch-norm style aux updates are applied functionally each step
    """

    def __init__(self, block, loss_fn, optimizer, strategy=None, mesh=None,
                 donate=True, remat_policy=None, overlap_grads=False,
                 bucket_bytes=None):
        """overlap_grads=True builds the step inside ``shard_map`` with
        the gradient reduction issued as size-capped bucketed
        collectives placed MID-BACKWARD (parallel/overlap.py): each
        bucket's all-reduce is data-ready the moment its backward
        segment completes, so it can hide under the remaining backward
        compute — the SCALING_r05 overlap story. Requires a pure
        data-parallel strategy (replicated params, batch on 'dp');
        ``bucket_bytes`` caps each bucket (default
        ``MXTPU_ELASTIC_BUCKET_MB``). Gradient math matches the GSPMD
        path (mean over the global batch) up to float reassociation
        for per-sample losses; two semantics differ by construction:
        dropout draws — each shard folds its 'dp' axis index into the
        rng key (a replicated key would hand every shard identical
        mask values) — and cross-batch normalization (BatchNorm):
        inside ``shard_map`` the block sees only its local shard, so
        BN normalizes with LOCAL-batch statistics and the moving stats
        are a pmean of per-shard estimates (standard DDP semantics;
        the GSPMD path computes true global-batch statistics). Models
        whose training depends on global-batch BN should keep
        ``overlap_grads=False`` or use a cross-replica norm.

        remat_policy="conv_outs" wraps the forward in jax.checkpoint
        saving ONLY checkpoint_name-tagged values (conv_out/pool_out/
        bn_stat — see ops/nn.py _ckpt_name): backward recomputes the
        elementwise normalize/activation chains from raw conv outputs,
        fused into the consuming matmuls, instead of persisting them in
        HBM (round-4 ResNet HBM-traffic work). Any other string is
        passed to jax.checkpoint_policies.save_only_these_names as a
        comma-separated name list."""
        if strategy is None:
            if mesh is None:
                raise ValueError("need strategy or mesh")
            strategy = data_parallel(mesh)
        self._remat_policy = remat_policy
        self.block = block
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.strategy = strategy
        self.mesh = strategy.mesh
        raw_mesh = getattr(self.mesh, "mesh", self.mesh)

        params = extract_params(block)
        self._param_paths = sorted(params)
        shardings = strategy.param_sharding(params)
        # place params according to strategy
        self.params = {k: jax.device_put(v, shardings[k])
                       for k, v in params.items()}
        # optimizer states per param, sharded like their param where same
        # shape, replicated otherwise
        self.opt_states = {}
        self._state_shardings = {}
        for i, path in enumerate(self._param_paths):
            w = NDArray(self.params[path])
            st = optimizer.create_state_multi_precision(i, w)
            st_arrays = _state_to_arrays(st)
            placed = []
            for a in st_arrays:
                sh = shardings[path] if a.shape == self.params[path].shape \
                    else _replicated(raw_mesh)
                placed.append(jax.device_put(a, sh))
            self.opt_states[path] = _arrays_to_state(st, placed)
        self._shardings = shardings
        self._batch_sharding = strategy.batch_sharding()
        self._jitted = None
        self._donate = donate
        self._overlap = bool(overlap_grads)
        self._bucket_bytes = bucket_bytes
        if self._overlap:
            replicated = all(all(p is None for p in sh.spec)
                             for sh in shardings.values())
            if strategy.batch_axes != ("dp",) or not replicated:
                raise ValueError(
                    "overlap_grads needs a pure data-parallel strategy "
                    "(replicated params, batch on 'dp'); got %r"
                    % (strategy,))

    def _build(self):
        block, loss_fn, optimizer = self.block, self.loss_fn, self.optimizer
        paths = self._param_paths

        remat_policy = self._remat_policy

        def train_step(params, opt_states, x, y, rng):
            def loss_of(ps):
                out, aux = functional_call(block, ps, [x], training=True,
                                           rng=rng, return_aux=True)
                out0 = out[0] if isinstance(out, tuple) else out
                loss = loss_fn(NDArray(out0), NDArray(y))._data
                return jnp.mean(loss), aux

            if remat_policy:
                names = ("conv_out", "pool_out", "bn_stat") \
                    if remat_policy == "conv_outs" \
                    else tuple(remat_policy.split(","))
                loss_of = jax.checkpoint(
                    loss_of,
                    policy=jax.checkpoint_policies.save_only_these_names(
                        *names))
            (loss, aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            new_params, new_states = {}, {}
            for i, path in enumerate(paths):
                w = NDArray(params[path])
                g = NDArray(grads[path])
                st = opt_states[path]
                st_nd = _state_to_nd(st)
                optimizer.update_multi_precision(i, w, g, st_nd)
                new_params[path] = w._data
                new_states[path] = _nd_to_state(st, st_nd)
            # apply aux (moving stats) updates functionally
            for path, new in aux.items():
                if path in new_params:
                    new_params[path] = new
            return new_params, new_states, loss

        raw_mesh = getattr(self.mesh, "mesh", self.mesh)
        param_sh = {k: self._shardings[k] for k in self.params}
        state_sh = jax.tree_util.tree_map(
            lambda a: self._shardings_for_state(a), self.opt_states,
            is_leaf=lambda l: hasattr(l, "shape"))
        with raw_mesh:
            # mxlint: disable=MX005 (one sharded train step per ShardedTrainStep instance; shapes fixed by the strategy, single key)
            jf = jax.jit(
                train_step,
                in_shardings=(param_sh, state_sh, self._batch_sharding,
                              self._batch_sharding, None),
                out_shardings=(param_sh, state_sh, None),
                donate_argnums=(0, 1) if self._donate else ())
        self._jitted = self._compile_probe(jf, "gspmd")

    def _compile_probe(self, jf, mode):
        """One-shot first-call wrapper (the register._compile_probe
        convention): the first ``step()`` of a fresh program pays trace
        + XLA compile + first run — record that wall time in the
        compile-attribution registry, then unwrap so steady-state calls
        pay nothing. ``lower`` is forwarded for the AOT inspection
        seam, which bypasses the probe entirely."""
        def probe(*args):
            t0 = _time.perf_counter()
            out = jf(*args)
            if self._jitted is probe:
                self._jitted = jf
            _profiler.record_compile(
                "sharded_step:%s" % mode,
                key="%d params" % len(self._param_paths),
                dur_us=(_time.perf_counter() - t0) * 1e6)
            return out
        probe.lower = jf.lower
        return probe

    def _build_overlapped(self):
        """The overlap_grads=True program: same math as ``_build``, but
        inside ``shard_map`` over the mesh with the gradient reduction
        issued as bucketed mid-backward collectives
        (overlap.tag_gradient_buckets) instead of GSPMD's
        one-AR-per-grad-after-backward lowering."""
        from jax import lax
        from .compat import PartitionSpec as P
        from . import overlap as _overlap
        from .compat import shard_map as _shard_map
        block, loss_fn, optimizer = self.block, self.loss_fn, self.optimizer
        paths = self._param_paths
        raw_mesh = getattr(self.mesh, "mesh", self.mesh)
        plan = _overlap.bucket_plan([self.params[p] for p in paths],
                                    self._bucket_bytes)

        def train_step(params, opt_states, x, y, rng):
            # per-shard rng: a replicated key would hand every 'dp'
            # shard identical dropout masks
            rng = jax.random.fold_in(rng, lax.axis_index("dp"))

            def loss_of(ps):
                # bucket markers BETWEEN the grad variables and their
                # use: each bucket's pmean fires in the backward right
                # after its segment produces the last cotangent
                tagged = _overlap.tag_gradient_buckets(
                    [ps[p] for p in paths], "dp", plan=plan, op="mean")
                out, aux = functional_call(block, dict(zip(paths, tagged)),
                                           [x], training=True, rng=rng,
                                           return_aux=True)
                out0 = out[0] if isinstance(out, tuple) else out
                loss = loss_fn(NDArray(out0), NDArray(y))._data
                # mean over the LOCAL shard; grads pmean over 'dp' via
                # the markers == grad of the global-batch mean
                return jnp.mean(loss), aux

            (loss, aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            loss = lax.pmean(loss, "dp")
            new_params, new_states = {}, {}
            for i, path in enumerate(paths):
                w = NDArray(params[path])
                g = NDArray(grads[path])
                st = opt_states[path]
                st_nd = _state_to_nd(st)
                optimizer.update_multi_precision(i, w, g, st_nd)
                new_params[path] = w._data
                new_states[path] = _nd_to_state(st, st_nd)
            # aux (moving stats) are per-shard estimates: average them
            # so every replica applies the identical update
            for path, new in aux.items():
                if path in new_params:
                    new_params[path] = lax.pmean(new, "dp")
            return new_params, new_states, loss

        p_spec = {k: P() for k in paths}
        state_spec = jax.tree_util.tree_map(
            lambda a: P(), self.opt_states,
            is_leaf=lambda l: hasattr(l, "shape"))
        body = _shard_map(
            train_step, raw_mesh,
            in_specs=(p_spec, state_spec, P("dp"), P("dp"), P()),
            out_specs=(p_spec, state_spec, P()), check_vma=False)
        param_sh = {k: self._shardings[k] for k in self.params}
        state_sh = jax.tree_util.tree_map(
            lambda a: self._shardings_for_state(a), self.opt_states,
            is_leaf=lambda l: hasattr(l, "shape"))
        with raw_mesh:
            # mxlint: disable=MX005 (one overlapped train step per ShardedTrainStep instance; shapes fixed by the strategy, single key)
            jf = jax.jit(
                body,
                in_shardings=(param_sh, state_sh, self._batch_sharding,
                              self._batch_sharding, None),
                out_shardings=(param_sh, state_sh, None),
                donate_argnums=(0, 1) if self._donate else ())
        self._jitted = self._compile_probe(jf, "overlap")

    def _shardings_for_state(self, a):
        # states were placed at construction; reuse their current sharding
        return a.sharding

    def _ensure_built(self):
        if self._jitted is None:
            if self._overlap:
                self._build_overlapped()
            else:
                self._build()

    def step(self, x, y):
        """One async update; returns the loss as a device scalar (no host
        sync — the NDArray wait-to-read discipline, ref: SURVEY §3.1)."""
        self._ensure_built()
        xd = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yd = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        if getattr(xd, "sharding", None) != self._batch_sharding:
            xd = jax.device_put(xd, self._batch_sharding)
            yd = jax.device_put(yd, self._batch_sharding)
        rng = _random.next_key()
        self.params, self.opt_states, loss = self._jitted(
            self.params, self.opt_states, xd, yd, rng)
        return loss

    def lower(self, x, y):
        """AOT-lower the step for inspection (cost analysis, optimized
        HLO) without running it — profiling seam for benchmark/."""
        self._ensure_built()
        xd, yd = self.place_batch(x, y)
        return self._jitted.lower(self.params, self.opt_states, xd, yd,
                                  _random.next_key())

    def place_batch(self, x, y):
        """Pre-shard a host batch onto the mesh (double-buffer helper)."""
        xd = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        yd = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        return (jax.device_put(xd, self._batch_sharding),
                jax.device_put(yd, self._batch_sharding))

    def __call__(self, x, y):
        return float(self.step(x, y))

    def sync_to_block(self):
        """Copy trained params back into the Block (for save_parameters)."""
        attach_params(self.block, self.params)


def _state_to_arrays(st):
    if st is None:
        return []
    if isinstance(st, NDArray):
        return [st._data]
    if isinstance(st, (list, tuple)):
        out = []
        for s in st:
            out.extend(_state_to_arrays(s))
        return out
    return []


def _arrays_to_state(template, arrays):
    it = iter(arrays)

    def rebuild(t):
        if t is None:
            return None
        if isinstance(t, NDArray):
            return next(it)
        if isinstance(t, tuple):
            return tuple(rebuild(s) for s in t)
        if isinstance(t, list):
            return [rebuild(s) for s in t]
        return t

    return rebuild(template)


def _state_to_nd(st):
    if st is None:
        return None
    if hasattr(st, "shape") and not isinstance(st, NDArray):
        return NDArray(st)
    if isinstance(st, tuple):
        return tuple(_state_to_nd(s) for s in st)
    if isinstance(st, list):
        return [_state_to_nd(s) for s in st]
    return st


def _nd_to_state(template, st_nd):
    if st_nd is None:
        return None
    if isinstance(st_nd, NDArray):
        return st_nd._data
    if isinstance(st_nd, tuple):
        return tuple(_nd_to_state(None, s) for s in st_nd)
    if isinstance(st_nd, list):
        return [_nd_to_state(None, s) for s in st_nd]
    return st_nd


def _replicated(mesh):
    from .compat import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())
