"""Expert parallelism: Mixture-of-Experts FFN sharded over the 'ep' axis.

Not present in the reference (2019-era, SURVEY.md §2.4 item 7) but part of
the required capability surface. Design: experts live on the 'ep' mesh axis
(weights [E, ...] sharded P('ep', ...)); routing is computed densely and
tokens reach their experts via einsum dispatch/combine (Shazeer et al.
arXiv:1701.06538, GShard arXiv:2006.16668). GSPMD turns the dispatch einsum
into an all-to-all over ICI. Dense dispatch keeps shapes static — the XLA
requirement — with capacity_factor bounding per-expert load.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["top_k_routing", "moe_ffn", "MoELayer"]


def top_k_routing(logits, k=2, capacity=None):
    """Token->expert assignment with capacity. logits: [T, E].

    Returns (dispatch [T, E, C] one-hot, combine [T, E, C] weights, aux_loss).
    aux_loss is the load-balancing loss (mean_prob * mean_assignment * E).
    """
    T, E = logits.shape
    if capacity is None:
        capacity = max(1, (k * T + E - 1) // E)
    probs = jax.nn.softmax(logits, axis=-1)
    gates, experts = jax.lax.top_k(probs, k)            # [T, k]
    # position of each token within its expert's queue
    onehot = jax.nn.one_hot(experts, E, dtype=jnp.int32)     # [T, k, E]
    # cumulative count per expert across (token, choice) in order
    flat = onehot.reshape(T * k, E)
    pos_in_expert = jnp.cumsum(flat, axis=0) - flat          # [T*k, E]
    pos = (pos_in_expert * flat).sum(-1).reshape(T, k)       # [T, k]
    keep = pos < capacity
    gates = gates * keep
    denom = jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    gates = gates / denom
    disp = jnp.zeros((T, E, capacity), jnp.float32)
    comb = jnp.zeros((T, E, capacity), jnp.float32)
    t_idx = jnp.arange(T)[:, None].repeat(k, 1)
    disp = disp.at[t_idx, experts, jnp.clip(pos, 0, capacity - 1)].add(
        keep.astype(jnp.float32))
    comb = comb.at[t_idx, experts, jnp.clip(pos, 0, capacity - 1)].add(
        gates * keep)
    # load-balance aux loss
    me = probs.mean(0)                                   # [E]
    ce = flat.reshape(T, k, E).sum(1).astype(jnp.float32).mean(0)
    aux = (me * ce).sum() * E
    return disp, comb, aux


def moe_ffn(x, router_w, w1, w2, k=2, capacity_factor=1.25,
            activation=jax.nn.gelu):
    """MoE FFN. x: [B, S, D]; router_w: [D, E]; w1: [E, D, F]; w2: [E, F, D].
    Shard w1/w2 P('ep', None, 'tp')/P('ep', 'tp', None) for ep x tp."""
    B, S, D = x.shape
    E = router_w.shape[1]
    T = B * S
    xt = x.reshape(T, D)
    logits = xt @ router_w                               # [T, E]
    capacity = max(1, int(capacity_factor * k * T / E))
    disp, comb, aux = top_k_routing(logits, k=k, capacity=capacity)
    # dispatch: [E, C, D] expert inputs (GSPMD: all-to-all over 'ep')
    xe = jnp.einsum("td,tec->ecd", xt, disp)
    h = activation(jnp.einsum("ecd,edf->ecf", xe, w1))
    ye = jnp.einsum("ecf,efd->ecd", h, w2)
    yt = jnp.einsum("ecd,tec->td", ye, comb)
    return yt.reshape(B, S, D), aux


class MoELayer:
    """Functional MoE layer bundle (params created via init())."""

    def __init__(self, dim, hidden, num_experts, k=2, capacity_factor=1.25):
        self.dim, self.hidden = dim, hidden
        self.num_experts, self.k = num_experts, k
        self.capacity_factor = capacity_factor

    def init(self, key):
        import jax.random as jr
        k1, k2, k3 = jr.split(key, 3)
        scale = self.dim ** -0.5
        return {
            "router": jr.normal(k1, (self.dim, self.num_experts)) * scale,
            "w1": jr.normal(k2, (self.num_experts, self.dim,
                                 self.hidden)) * scale,
            "w2": jr.normal(k3, (self.num_experts, self.hidden,
                                 self.dim)) * (self.hidden ** -0.5),
        }

    def __call__(self, params, x):
        return moe_ffn(x, params["router"], params["w1"], params["w2"],
                       k=self.k, capacity_factor=self.capacity_factor)
