"""JAX version-compat shims for the parallel stack.

``shard_map`` moved twice across jax releases: it lives at
``jax.experimental.shard_map.shard_map`` through 0.4.x/0.5.x (with a
``check_rep`` kwarg) and at top-level ``jax.shard_map`` from 0.6 on
(where the kwarg was renamed ``check_vma``). Every shard_map call in
this package goes through this one shim so the rest of the code can
use the modern spelling (``check_vma=``) on either jax.

This module is also the ONE import point for the ``jax.sharding``
names the package uses (``Mesh``/``NamedSharding``/``PartitionSpec``):
hot modules import them from here instead of from jax directly, so a
future relocation (as happened to shard_map twice) means editing one
file. mxlint **MX020** enforces the routing statically.
"""
from __future__ import annotations

import functools

# the sharding type names, re-exported for the whole package (MX020)
from jax.sharding import Mesh, NamedSharding, PartitionSpec  # noqa: F401

try:  # jax >= 0.6: top-level export taking check_vma
    from jax import shard_map as _shard_map
    _KWARG = "check_vma"
except ImportError:  # jax <= 0.5: experimental export taking check_rep
    from jax.experimental.shard_map import shard_map as _shard_map
    _KWARG = "check_rep"

__all__ = ["shard_map", "Mesh", "NamedSharding", "PartitionSpec"]


@functools.wraps(_shard_map)
def shard_map(f, mesh, in_specs, out_specs, check_vma=True, **kwargs):
    kwargs[_KWARG] = check_vma
    # accept the package's DeviceMesh wrapper transparently (every
    # caller otherwise repeats the getattr unwrap by hand)
    mesh = getattr(mesh, "mesh", mesh)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
