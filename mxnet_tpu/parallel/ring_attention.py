"""Ring attention: exact attention over a sequence sharded across a mesh axis.

The reference scales sequence length only by bucketing and fused RNN kernels
(ref: python/mxnet/module/bucketing_module.py:40, src/operator/rnn-inl.h) —
it has no context parallelism. Here long context is first-class: the
sequence dim is sharded over the 'sp' mesh axis and K/V blocks rotate around
the ICI ring with `ppermute` while each device accumulates its queries'
attention online (flash-attention style m/l/o running softmax, Liu et al.
arXiv:2310.01889). Compute on one block overlaps the transfer of the next —
XLA schedules ppermute as async collective-permute.

Use inside `shard_map` over the 'sp' axis, or via `ring_self_attention`
which wraps the shard_map given a mesh.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from .compat import PartitionSpec as P

__all__ = ["ring_attention", "ring_self_attention", "blockwise_attention"]


def _attn_block(q, k, v, bias, m_prev, l_prev, o_prev, scale):
    """One q-block x kv-block step of online-softmax attention.
    q: [B,H,Sq,D] k,v: [B,H,Sk,D] bias: [B,1|H,Sq,Sk] or None."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m_cur = jnp.max(s, axis=-1)                      # [B,H,Sq]
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows (m == -inf) against nan exp
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])               # [B,H,Sq,Sk]
    if bias is not None:
        p = jnp.where(jnp.isneginf(s), 0.0, p)
    corr = jnp.exp(jnp.where(jnp.isneginf(m_prev), -jnp.inf,
                             m_prev - m_safe))
    corr = jnp.where(jnp.isneginf(m_prev), 0.0, corr)
    l_new = corr * l_prev + jnp.sum(p, axis=-1)
    o_new = corr[..., None] * o_prev + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name, causal=False, scale=None,
                   q_offset=None):
    """Exact attention with K/V rotating around `axis_name`.

    Must run inside shard_map/pmap. Shapes per device:
      q, k, v: [B, H, S_local, D] → out [B, H, S_local, D]

    causal=True masks by GLOBAL position: device i holds queries
    [i*S_local, (i+1)*S_local); kv blocks carry their origin index around
    the ring so the mask is computed per step.
    """
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if q_offset is None:
        q_offset = my_idx * Sq

    m0 = jnp.full((B, H, Sq), -jnp.inf, q.dtype)
    l0 = jnp.zeros((B, H, Sq), q.dtype)
    o0 = jnp.zeros((B, H, Sq, D), q.dtype)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = q_offset + jnp.arange(Sq)                # [Sq] global q positions

    def step(carry, _):
        m, l, o, kk, vv, kv_idx = carry
        if causal:
            k_pos = kv_idx * Sk + jnp.arange(Sk)
            bias = jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0,
                             -jnp.inf)[None, None]   # [1,1,Sq,Sk]
        else:
            bias = None
        m, l, o = _attn_block(q, kk, vv, bias, m, l, o, scale)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        kv_idx = lax.ppermute(kv_idx, axis_name, perm)
        return (m, l, o, kk, vv, kv_idx), None

    carry = (m0, l0, o0, k, v, my_idx)
    carry, _ = lax.scan(step, carry, None, length=n)
    m, l, o = carry[0], carry[1], carry[2]
    l = jnp.where(l == 0.0, 1.0, l)                  # fully-masked rows → 0
    return o / l[..., None]


def ring_self_attention(q, k, v, mesh, axis_name="sp", causal=False,
                        scale=None, batch_axis="dp", head_axis="tp"):
    """shard_map wrapper: q/k/v are [B, H, S, D] arrays (sharded or not);
    sequence axis is sharded over `axis_name`, batch over `batch_axis`,
    heads over `head_axis`."""
    from .compat import shard_map
    spec = P(batch_axis, head_axis, axis_name, None)
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal, scale=scale)
    return shard_map(fn, mesh=getattr(mesh, "mesh", mesh),
                     in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)(q, k, v)


def blockwise_attention(q, k, v, block_size=512, causal=False, scale=None):
    """Single-device memory-efficient attention: lax.scan over KV blocks with
    the same online-softmax accumulation (the local building block of ring
    attention; also useful alone to fit long context in HBM)."""
    B, H, S, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    nblk = -(-S // block_size)
    pad = nblk * block_size - S
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(B, H, nblk, block_size, D)
    vb = v.reshape(B, H, nblk, block_size, D)
    q_pos = jnp.arange(S)

    m0 = jnp.full((B, H, S), -jnp.inf, q.dtype)
    l0 = jnp.zeros((B, H, S), q.dtype)
    o0 = jnp.zeros((B, H, S, D), q.dtype)

    def step(carry, blk):
        m, l, o = carry
        kk, vv, idx = blk
        k_pos = idx * block_size + jnp.arange(block_size)
        valid = k_pos < S
        if causal:
            ok = (q_pos[:, None] >= k_pos[None, :]) & valid[None, :]
        else:
            ok = jnp.broadcast_to(valid[None, :], (S, block_size))
        bias = jnp.where(ok, 0.0, -jnp.inf)[None, None]
        m, l, o = _attn_block(q, kk, vv, bias, m, l, o, scale)
        return (m, l, o), None

    idxs = jnp.arange(nblk)
    (m, l, o), _ = lax.scan(step, (m0, l0, o0),
                            (kb.transpose(2, 0, 1, 3, 4),
                             vb.transpose(2, 0, 1, 3, 4), idxs))
    l = jnp.where(l == 0.0, 1.0, l)
    return o / l[..., None]
