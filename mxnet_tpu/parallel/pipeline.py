"""Pipeline parallelism: layer stages sharded over the 'pp' mesh axis.

The reference's only model parallelism is manual per-layer device placement
(group2ctx, ref: src/executor/graph_executor.cc:388 ctx_map +
src/operator/cross_device_copy.cc). The TPU-native design makes the stage
dimension a MESH AXIS: all stages' params are stacked on a leading axis
sharded over 'pp', and a shard_map GPipe loop rotates microbatch activations
stage-to-stage with ppermute (collective-permute over ICI neighbors).

Schedule: classic GPipe fill-drain. With M microbatches and K stages the
loop runs M+K-1 ticks; each tick every stage processes one microbatch
(bubble at ends). Activations travel in a rotating buffer.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from .compat import PartitionSpec as P

__all__ = ["pipeline_stages", "PipelineStage", "gpipe_loop"]


class PipelineStage:
    """Descriptor for one pipeline stage: a pure fn(params, x) -> x."""

    def __init__(self, fn, params):
        self.fn = fn
        self.params = params


def gpipe_loop(stage_fn, x_mb, axis_name):
    """GPipe fill-drain tick loop; runs INSIDE shard_map on the stage axis.

    stage_fn: fn(x[mb, ...]) -> y, this device's stage (params closed over)
    x_mb: [M, mb, ...] microbatch queue (replicated over `axis_name`)
    Returns final-stage outputs [M, mb, ...], replicated over `axis_name`.
    """
    k = lax.psum(1, axis_name)              # number of stages
    idx = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    ticks = M + k - 1
    perm = [(i, (i + 1) % k) for i in range(k)]

    out0 = jnp.zeros((M,) + x_mb.shape[1:], x_mb.dtype)

    def tick(carry, t):
        buf, outs = carry
        # stage 0 injects microbatch t (if in range); others take the
        # activation handed to them by the previous stage last tick.
        mb_in = x_mb[jnp.clip(t, 0, M - 1)]
        inject = jnp.logical_and(idx == 0, t < M)
        cur = jnp.where(inject, mb_in, buf)
        y = stage_fn(cur)
        # last stage writes its result for microbatch (t - k + 1)
        out_t = t - (k - 1)
        is_out = jnp.logical_and(idx == k - 1,
                                 jnp.logical_and(out_t >= 0, out_t < M))
        outs = lax.cond(
            is_out,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y.astype(o.dtype), jnp.clip(out_t, 0, M - 1), 0),
            lambda o: o, outs)
        # rotate activations to the next stage
        buf = lax.ppermute(y, axis_name, perm)
        return (buf, outs), None

    (_, outs), _ = lax.scan(tick, (jnp.zeros_like(x_mb[0]), out0),
                            jnp.arange(ticks))
    # all-reduce outs over the stage axis so every stage returns the full
    # result (only the last stage wrote non-zeros)
    return lax.psum(outs, axis_name)


def _gpipe_local(stage_fn, stacked_params, x_mb, axis_name):
    my_params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)
    return gpipe_loop(lambda x: stage_fn(my_params, x), x_mb, axis_name)


def pipeline_stages(stage_fn, stacked_params, x, mesh, n_microbatch,
                    axis_name="pp", batch_axis="dp"):
    """GPipe over the 'pp' mesh axis.

    stage_fn: pure fn(stage_params, x[mb, ...]) -> y with y.shape == x.shape
              (homogeneous stages — the transformer-block case)
    stacked_params: pytree with leading dim = n_stages on every leaf,
              sharded P('pp', ...)
    x: [B, ...] batch (sharded on dp); B % n_microbatch == 0
    """
    from .compat import shard_map
    raw_mesh = getattr(mesh, "mesh", mesh)
    B = x.shape[0]
    assert B % n_microbatch == 0, "batch %d not divisible into %d mb" % (
        B, n_microbatch)
    x_mb = x.reshape((n_microbatch, B // n_microbatch) + x.shape[1:])

    pspec = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params,
        is_leaf=lambda l: hasattr(l, "shape"))
    fn = functools.partial(_gpipe_local, stage_fn, axis_name=axis_name)
    # microbatches replicated over pp; sharded over dp on the batch dim
    xspec = P(None, batch_axis)
    y_mb = shard_map(fn, mesh=raw_mesh,
                     in_specs=(pspec, xspec), out_specs=xspec, check_vma=False)(stacked_params, x_mb)
    return y_mb.reshape((B,) + y_mb.shape[2:])
