"""Detection data pipeline: label-aware augmenters + ImageDetIter.

ref: python/mxnet/image/detection.py — DetAugmenter hierarchy (:41),
DetBorrowAug (:67), DetRandomSelectAug (:92), DetHorizontalFlipAug
(:128), DetRandomCropAug (:154), DetRandomPadAug (:325),
CreateMultiRandCropAugmenter (:419), CreateDetAugmenter (:484),
ImageDetIter (:626). The C++ twin is src/io/iter_image_det_recordio.cc.

Label convention matches the reference: the raw record label is
``[header_width, obj_width, <extra header...>, id, xmin, ymin, xmax,
ymax, <extra...>] * N`` with coordinates normalized to [0, 1]; parsed
labels are float arrays ``[N, obj_width]`` whose row is
``(class_id, xmin, ymin, xmax, ymax, ...)``. Batches pad the object
axis with -1 rows (the SSD target layers treat id < 0 as absent).
"""
from __future__ import annotations

import json
import random as _pyrandom
from math import sqrt

import numpy as np

from .image import (Augmenter, ImageIter, ResizeAug, ForceResizeAug,
                    CastAug, ColorJitterAug, LightingAug,
                    ColorNormalizeAug, RandomGrayAug, HueJitterAug,
                    fixed_crop, imresize, _np_img)
from .ndarray import array as nd_array

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateMultiRandCropAugmenter", "CreateDetAugmenter",
           "ImageDetIter"]


class DetAugmenter:
    """Detection base augmenter (ref: detection.py:41) — takes and
    returns (image, label) so geometry changes stay label-consistent."""

    def __init__(self, **kwargs):
        self._kwargs = {}
        for k, v in kwargs.items():
            if isinstance(v, np.ndarray):
                v = v.tolist()
            self._kwargs[k] = v

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError("Must override implementation.")


class DetBorrowAug(DetAugmenter):
    """Lift a label-invariant classification augmenter (ref: :67)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise TypeError("Borrowing from invalid Augmenter")
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def dumps(self):
        return [self.__class__.__name__.lower(), self.augmenter.dumps()]

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Apply one randomly chosen augmenter, or none (ref: :92)."""

    def __init__(self, aug_list, skip_prob=0):
        super().__init__(skip_prob=skip_prob)
        if not isinstance(aug_list, (list, tuple)):
            aug_list = [aug_list]
        for aug in aug_list:
            if not isinstance(aug, DetAugmenter):
                raise ValueError("Allow DetAugmenter in list only")
        if not aug_list:
            skip_prob = 1
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def dumps(self):
        return [self.__class__.__name__.lower(),
                [x.dumps() for x in self.aug_list]]

    def __call__(self, src, label):
        if _pyrandom.random() < self.skip_prob:
            return src, label
        _pyrandom.shuffle(self.aug_list)
        return self.aug_list[0](src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Random horizontal flip of image AND boxes (ref: :128)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            src = nd_array(_np_img(src)[:, ::-1].copy())
            label = label.copy()
            tmp = 1.0 - label[:, 1]
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = tmp
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Constrained random crop (ref: :154): the crop must cover at
    least `min_object_covered` of some box; boxes with post-crop
    coverage below `min_eject_coverage` are dropped."""

    def __init__(self, min_object_covered=0.1,
                 aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 1.0),
                 min_eject_coverage=0.3, max_attempts=50):
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.enabled = (area_range[1] > 0
                        and area_range[0] <= area_range[1]
                        and 0 < aspect_ratio_range[0]
                        <= aspect_ratio_range[1])

    def __call__(self, src, label):
        img = _np_img(src)
        crop = self._random_crop_proposal(label, img.shape[0],
                                          img.shape[1])
        if crop:
            x, y, w, h, label = crop
            src = fixed_crop(src, x, y, w, h, None)
        return src, label

    @staticmethod
    def _areas(boxes):
        return (np.maximum(0, boxes[:, 3] - boxes[:, 1])
                * np.maximum(0, boxes[:, 2] - boxes[:, 0]))

    @staticmethod
    def _intersect(boxes, xmin, ymin, xmax, ymax):
        left = np.maximum(boxes[:, 0], xmin)
        right = np.minimum(boxes[:, 2], xmax)
        top = np.maximum(boxes[:, 1], ymin)
        bot = np.minimum(boxes[:, 3], ymax)
        invalid = np.where(np.logical_or(left >= right, top >= bot))[0]
        out = boxes.copy()
        out[:, 0], out[:, 1], out[:, 2], out[:, 3] = left, top, right, bot
        out[invalid, :] = 0
        return out

    def _satisfies(self, label, xmin, ymin, xmax, ymax, width, height):
        if (xmax - xmin) * (ymax - ymin) < 2:
            return False
        x1, y1 = xmin / width, ymin / height
        x2, y2 = xmax / width, ymax / height
        areas = self._areas(label[:, 1:])
        valid = np.where(areas * width * height > 2)[0]
        if valid.size < 1:
            return False
        inter = self._intersect(label[valid, 1:], x1, y1, x2, y2)
        cov = self._areas(inter) / areas[valid]
        cov = cov[np.where(cov > 0)[0]]
        return cov.size > 0 and np.amin(cov) > self.min_object_covered

    def _update_labels(self, label, crop_box, height, width):
        xmin = crop_box[0] / width
        ymin = crop_box[1] / height
        w = crop_box[2] / width
        h = crop_box[3] / height
        out = label.copy()
        out[:, (1, 3)] -= xmin
        out[:, (2, 4)] -= ymin
        out[:, (1, 3)] /= w
        out[:, (2, 4)] /= h
        out[:, 1:5] = np.clip(out[:, 1:5], 0, 1)
        cov = self._areas(out[:, 1:]) * w * h / self._areas(label[:, 1:])
        valid = np.logical_and(out[:, 3] > out[:, 1],
                               out[:, 4] > out[:, 2])
        valid = np.where(np.logical_and(valid,
                                        cov > self.min_eject_coverage))[0]
        if valid.size < 1:
            return None
        return out[valid, :]

    def _random_crop_proposal(self, label, height, width):
        if not self.enabled or height <= 0 or width <= 0:
            return ()
        min_area = self.area_range[0] * height * width
        max_area = self.area_range[1] * height * width
        for _ in range(self.max_attempts):
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            if ratio <= 0:
                continue
            h = int(round(sqrt(min_area / ratio)))
            max_h = int(round(sqrt(max_area / ratio)))
            if round(max_h * ratio) > width:
                max_h = int((width + 0.4999999) / ratio)
            max_h = min(max_h, height)
            h = min(h, max_h)
            if h < max_h:
                h = _pyrandom.randint(h, max_h)
            w = int(round(h * ratio))
            area = w * h
            if area < min_area:
                h += 1
                w = int(round(h * ratio))
                area = w * h
            if area > max_area:
                h -= 1
                w = int(round(h * ratio))
                area = w * h
            if not (min_area <= area <= max_area and 0 <= w <= width
                    and 0 <= h <= height):
                continue
            y = _pyrandom.randint(0, max(0, height - h))
            x = _pyrandom.randint(0, max(0, width - w))
            if self._satisfies(label, x, y, x + w, y + h, width, height):
                new_label = self._update_labels(label, (x, y, w, h),
                                                height, width)
                if new_label is not None:
                    return (x, y, w, h, new_label)
        return ()


class DetRandomPadAug(DetAugmenter):
    """Random expansion padding (ref: :325) — the inverse zoom of
    random crop; boxes shrink into the padded canvas."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(128, 128, 128)):
        if not isinstance(pad_val, (list, tuple)):
            pad_val = (pad_val,)
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.pad_val = pad_val
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.enabled = (area_range[1] > 1.0
                        and area_range[0] <= area_range[1]
                        and 0 < aspect_ratio_range[0]
                        <= aspect_ratio_range[1])

    def __call__(self, src, label):
        img = _np_img(src)
        height, width = img.shape[:2]
        pad = self._random_pad_proposal(label, height, width)
        if pad:
            x, y, w, h, label = pad
            canvas = np.empty((h, w, img.shape[2]), img.dtype)
            canvas[...] = np.asarray(self.pad_val, img.dtype)
            canvas[y:y + height, x:x + width] = img
            src = nd_array(canvas)
        return src, label

    @staticmethod
    def _update_labels(label, pad_box, height, width):
        out = label.copy()
        out[:, (1, 3)] = (out[:, (1, 3)] * width + pad_box[0]) / pad_box[2]
        out[:, (2, 4)] = (out[:, (2, 4)] * height + pad_box[1]) / pad_box[3]
        return out

    def _random_pad_proposal(self, label, height, width):
        if not self.enabled or height <= 0 or width <= 0:
            return ()
        min_area = self.area_range[0] * height * width
        max_area = self.area_range[1] * height * width
        for _ in range(self.max_attempts):
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            if ratio <= 0:
                continue
            h = int(round(sqrt(min_area / ratio)))
            max_h = int(round(sqrt(max_area / ratio)))
            if round(h * ratio) < width:
                h = int((width + 0.499999) / ratio)
            h = max(h, height)
            h = min(h, max_h)
            if h < max_h:
                h = _pyrandom.randint(h, max_h)
            w = int(round(h * ratio))
            if (h - height) < 2 or (w - width) < 2:
                continue
            y = _pyrandom.randint(0, max(0, h - height))
            x = _pyrandom.randint(0, max(0, w - width))
            new_label = self._update_labels(label, (x, y, w, h), height,
                                            width)
            return (x, y, w, h, new_label)
        return ()


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0):
    """One DetRandomSelectAug over per-constraint croppers (ref: :419).
    Scalar args broadcast; list args must agree in length."""
    def _as_list(v):
        return list(v) if isinstance(v, (list, tuple)) and \
            isinstance(v[0], (list, tuple)) else [v]

    mocs = min_object_covered if isinstance(min_object_covered,
                                            (list, tuple)) \
        else [min_object_covered]
    arrs = _as_list(aspect_ratio_range)
    ars = _as_list(area_range)
    mecs = min_eject_coverage if isinstance(min_eject_coverage,
                                            (list, tuple)) \
        else [min_eject_coverage]
    mas = max_attempts if isinstance(max_attempts, (list, tuple)) \
        else [max_attempts]
    n = max(len(mocs), len(arrs), len(ars), len(mecs), len(mas))
    for name, lst in (("min_object_covered", mocs),
                      ("aspect_ratio_range", arrs), ("area_range", ars),
                      ("min_eject_coverage", mecs), ("max_attempts", mas)):
        if len(lst) not in (1, n):
            raise ValueError(
                "%s has %d entries; list arguments must agree in length "
                "(%d) or be scalar" % (name, len(lst), n))

    def pick(lst, i):
        return lst[i] if len(lst) == n else lst[0]

    crops = [DetRandomCropAug(min_object_covered=pick(mocs, i),
                              aspect_ratio_range=pick(arrs, i),
                              area_range=pick(ars, i),
                              min_eject_coverage=pick(mecs, i),
                              max_attempts=pick(mas, i))
             for i in range(n)]
    return DetRandomSelectAug(crops, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Standard detection augmenter list (ref: detection.py:484):
    resize -> color jitter -> random crop (prob rand_crop) -> random
    pad (prob rand_pad) -> flip -> force-resize to data_shape ->
    cast/normalize."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval,
                                                eigvec)))
    if hue > 0:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if rand_crop > 0:
        crop_augs = CreateMultiRandCropAugmenter(
            min_object_covered=min_object_covered,
            aspect_ratio_range=aspect_ratio_range,
            area_range=(min(area_range[0], 1.0),
                        min(area_range[1], 1.0)),
            min_eject_coverage=min_eject_coverage,
            max_attempts=max_attempts, skip_prob=(1 - rand_crop))
        auglist.append(crop_augs)
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        pad_aug = DetRandomPadAug(
            aspect_ratio_range=aspect_ratio_range,
            area_range=(max(area_range[0], 1.0),
                        max(area_range[1], 1.0)),
            max_attempts=max_attempts, pad_val=pad_val)
        auglist.append(DetRandomSelectAug([pad_aug],
                                          skip_prob=(1 - rand_pad)))
    # force resize AFTER geometry augs (labels are normalized, so a
    # resize is label-invariant)
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    has_mean = mean is not None and np.asarray(mean).any()
    has_std = std is not None and (np.asarray(std) != 1.0).any()
    if has_mean or has_std:
        # std-only normalization is valid (ref CreateDetAugmenter
        # appends the normalizer for either)
        auglist.append(DetBorrowAug(ColorNormalizeAug(
            mean if mean is not None else np.zeros(3),
            std if std is not None else np.ones(3))))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator over .rec/.lst sources (ref: detection.py:626).

    Labels batch as [batch, max_objects, obj_width] padded with -1
    rows; `label_shape` is estimated from the dataset on construction.
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, path_imgidx=None,
                 shuffle=False, aug_list=None, data_name="data",
                 label_name="label", **kwargs):
        aug_keys = ("resize", "rand_crop", "rand_pad", "rand_gray",
                    "rand_mirror", "mean", "std", "brightness", "contrast",
                    "saturation", "pca_noise", "hue", "inter_method",
                    "min_object_covered", "aspect_ratio_range",
                    "area_range", "min_eject_coverage", "max_attempts",
                    "pad_val")
        det_kwargs = {k: v for k, v in kwargs.items() if k in aug_keys}
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         shuffle=shuffle, aug_list=[],
                         **{k: v for k, v in kwargs.items()
                            if k not in aug_keys})
        self.auglist = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape, **det_kwargs)
        self._data_name = data_name
        self._label_name = label_name
        self.label_shape = self._estimate_label_shape()

    @property
    def provide_label(self):
        from .io.io import DataDesc
        return [DataDesc(self._label_name,
                         (self.batch_size,) + self.label_shape)]

    @staticmethod
    def _check_valid_label(label):
        """ref: detection.py _check_valid_label."""
        if len(label.shape) != 2 or label.shape[1] < 5:
            raise RuntimeError("Label with shape (1+, 5+) required, %s "
                               "received." % str(label))
        valid = np.where(np.logical_and(label[:, 0] >= 0,
                                        np.logical_and(
                                            label[:, 3] > label[:, 1],
                                            label[:, 4] > label[:, 2])))[0]
        if valid.size < 1:
            raise RuntimeError("Invalid label occurs.")

    @staticmethod
    def _parse_label(label):
        """Raw header-prefixed flat label -> [N, obj_width]
        (ref: detection.py _parse_label)."""
        raw = np.asarray(label, np.float32).ravel()
        if raw.size < 7:
            raise RuntimeError("Label shape is invalid: %s"
                               % (raw.shape,))
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if (raw.size - header_width) % obj_width != 0:
            raise RuntimeError(
                "Label shape %s inconsistent with annotation width %d."
                % (raw.shape, obj_width))
        out = raw[header_width:].reshape(-1, obj_width)
        valid = np.where(np.logical_and(out[:, 3] > out[:, 1],
                                        out[:, 4] > out[:, 2]))[0]
        if valid.size < 1:
            raise RuntimeError("Encounter sample with no valid label.")
        out = out[valid, :]
        ImageDetIter._check_valid_label(out)
        return out

    def _estimate_label_shape(self):
        """Scan the dataset labels for max object count (ref: :706).
        Reads ONLY the record headers — no image decode."""
        max_count, obj_width = 0, 5
        for item in self._items:
            parsed = self._parse_label(self._raw_label(item))
            max_count = max(max_count, parsed.shape[0])
            obj_width = parsed.shape[1]
        return (max_count, obj_width)

    def _raw_label(self, item):
        kind, payload = item
        if kind == "rec":
            from .recordio import unpack
            return unpack(payload)[0].label
        return payload[1]

    def _raw_sample(self, item):
        kind, payload = item
        if kind == "rec":
            from .recordio import unpack
            from .image import imdecode
            header, buf = unpack(payload)
            return imdecode(buf), header.label
        from .image import imread
        fn, label = payload
        return imread(fn), label

    def _load(self, item):
        img, label = self._raw_sample(item)
        label = self._parse_label(label)
        for aug in self.auglist:
            img, label = aug(img, label)
        arr = _np_img(img)
        if arr.ndim == 3 and arr.shape[-1] in (1, 3):
            arr = arr.transpose(2, 0, 1)
        padded = np.full(self.label_shape, -1.0, np.float32)
        n = min(label.shape[0], self.label_shape[0])
        padded[:n, :label.shape[1]] = label[:n]
        return arr.astype(np.float32), padded

    def reshape(self, data_shape=None, label_shape=None):
        """ref: detection.py reshape."""
        if data_shape is not None:
            self.data_shape = tuple(data_shape)
        if label_shape is not None:
            self.label_shape = tuple(label_shape)

    def sync_label_shape(self, it, verbose=False):
        """Sync label padding with another ImageDetIter (train/val
        pairs must batch identically; ref: detection.py
        sync_label_shape)."""
        assert isinstance(it, ImageDetIter)
        train_shape = self.label_shape
        val_shape = it.label_shape
        shape = (max(train_shape[0], val_shape[0]),
                 max(train_shape[1], val_shape[1]))
        self.reshape(label_shape=shape)
        it.reshape(label_shape=shape)
        return it
