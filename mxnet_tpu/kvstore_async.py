"""Host-driven asynchronous parameter server: real `dist_async`.

ref: src/kvstore/kvstore_dist_server.h:346-359 — in async mode the
server applies each worker's push IMMEDIATELY (`ApplyUpdates` without
the NumWorkers aggregation barrier), so workers train on stale weights;
convergence behavior genuinely differs from dist_sync. The ICI
collectives that back dist_sync are inherently synchronous, so — as
SURVEY §5 prescribes — async runs over a host-side transport: a server
thread in the rank-0 process owns the weights and applies updates as
messages arrive over TCP; pulls return whatever mix of updates has
landed. This is the ps-lite worker/server split with the scheduler
folded into the launcher's coordinator env.

Wire protocol (no pickle on the data plane — a remote peer can never
make the server deserialize executable objects from a push/pull):

  frame   := u32_be length | payload
  payload := opcode:u8 | fields
  key     := 0x00 i64_be        (int key)
           | 0x01 u16_be utf8   (str key)
  array   := u8 dtype-name-len | dtype-name | u8 ndim | u32_be dims...
           | raw C-order bytes

The ONE message that must carry a Python object — `set_optimizer`, the
reference's pickled-optimizer-to-server UX (python/mxnet/kvstore_server.py
``_controller``) — is authenticated: payload is HMAC-SHA256(secret,
blob) || blob, and the server refuses to unpickle unless the MAC
verifies. The secret comes from ``MXTPU_PS_SECRET`` (distributed to all
ranks by the launcher env pass-through, tools/launch.py); rank 0
generates one when unset so single-host runs are safe by default.
"""
from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import secrets as _secrets
import socket
import struct
import threading
import warnings

import numpy as np

__all__ = ["AsyncPSServer", "AsyncPSClient", "serve_if_rank0"]

# request opcodes
_OP_INIT = 1
_OP_PUSH = 2
_OP_PULL = 3
_OP_SET_OPT = 4
_OP_STATS = 5
_OP_DONE = 6
_OP_WAIT_DONE = 7
_OP_STOP = 8
# reserved for the sparse/compressed wire (row-sparse push/pull and
# 2-bit compressed push ride the same framing)
_OP_PUSH_RSP = 9
_OP_PULL_RSP = 10
_OP_PUSH_2BIT = 11
_OP_PROFILER = 12

# response opcodes
_RE_OK = 0x10
_RE_ARR = 0x11
_RE_INT = 0x12
_RE_ERR = 0x1F


def _ps_secret():
    s = os.environ.get("MXTPU_PS_SECRET", "")
    return s.encode() if s else None


def _pack_key(key):
    if isinstance(key, (int, np.integer)):
        return b"\x00" + struct.pack(">q", int(key))
    kb = str(key).encode()
    return b"\x01" + struct.pack(">H", len(kb)) + kb


def _unpack_key(buf, off):
    tag = buf[off]
    off += 1
    if tag == 0:
        return struct.unpack_from(">q", buf, off)[0], off + 8
    (n,) = struct.unpack_from(">H", buf, off)
    off += 2
    return buf[off:off + n].decode(), off + n


def _pack_arr(a):
    a = np.ascontiguousarray(a)
    dt = a.dtype.name.encode()
    out = [struct.pack(">B", len(dt)), dt, struct.pack(">B", a.ndim)]
    out.append(struct.pack(">%dI" % a.ndim, *a.shape))
    out.append(a.tobytes())
    return b"".join(out)


def _unpack_arr(buf, off):
    n = buf[off]
    off += 1
    dt = np.dtype(buf[off:off + n].decode())
    off += n
    ndim = buf[off]
    off += 1
    shape = struct.unpack_from(">%dI" % ndim, buf, off)
    off += 4 * ndim
    count = int(np.prod(shape, dtype=np.int64)) if ndim else 1
    nbytes = count * dt.itemsize
    arr = np.frombuffer(buf, dtype=dt, count=count, offset=off
                        ).reshape(shape).copy()
    return arr, off + nbytes


def _send_frame(sock, payload):
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock):
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    n = struct.unpack(">I", hdr)[0]
    return _recv_exact(sock, n)


class AsyncPSServer:
    """Weight owner + immediate-apply update loop (the reference's
    KVStoreDistServer in async mode).

    Binds to ``bind_host`` only (loopback by default) — never to
    0.0.0.0 unless the launcher explicitly passes the coordinator
    interface, so the update endpoint is not exposed beyond the
    training fabric."""

    def __init__(self, port=0, bind_host="127.0.0.1"):
        self._store = {}
        self._updater = None
        self._lock = threading.Lock()
        if _ps_secret() is None:
            # same-host workers inherit this via the environment; the
            # launcher passes MXTPU_* through for remote ranks
            os.environ["MXTPU_PS_SECRET"] = _secrets.token_hex(32)
        # pinned at construction: later env mutation must not change
        # what the server trusts
        self._secret = _ps_secret()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind_host, port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        self.updates_applied = 0          # observability for tests
        self.workers_done = 0

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                self._srv.settimeout(0.2)
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn):
        while not self._stop.is_set():
            try:
                buf = _recv_frame(conn)
            except OSError:
                return
            if buf is None or not len(buf):
                return
            try:
                self._handle(conn, buf)
            except Exception as e:  # noqa: BLE001 — reply, don't die
                msg = ("%s: %s" % (type(e).__name__, e)).encode()[:4096]
                try:
                    _send_frame(conn, struct.pack(">BH", _RE_ERR, len(msg))
                                + msg)
                except OSError:
                    return
            if buf[0] == _OP_STOP:
                return

    def _handle(self, conn, buf):
        op, off = buf[0], 1
        if op == _OP_INIT:
            key, off = _unpack_key(buf, off)
            arr, off = _unpack_arr(buf, off)
            with self._lock:
                self._store.setdefault(key, arr)
            _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_PUSH:
            key, off = _unpack_key(buf, off)
            grad, off = _unpack_arr(buf, off)
            # IMMEDIATE apply — no cross-worker barrier (async
            # semantics, kvstore_dist_server.h:358)
            with self._lock:
                if self._updater is not None:
                    self._apply(key, grad)
                else:
                    # same store-replace semantics as the sync
                    # KVStore without an optimizer (kvstore.py push)
                    self._store[key] = grad.copy()
                self.updates_applied += 1
            _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_PULL:
            key, off = _unpack_key(buf, off)
            with self._lock:
                val = np.array(self._store[key], copy=True)
            _send_frame(conn, bytes([_RE_ARR]) + _pack_arr(val))
        elif op == _OP_SET_OPT:
            # the reference pickles the optimizer worker-side and the
            # server builds its updater from it (kvstore_server.py).
            # The blob is executable on unpickle, so it MUST carry a
            # valid HMAC — an unauthenticated peer cannot reach
            # pickle.loads.
            mac, blob = buf[off:off + 32], buf[off + 32:]
            if self._secret is None:
                raise RuntimeError(
                    "server has no MXTPU_PS_SECRET; refusing pickled "
                    "optimizer (launcher must distribute the secret)")
            want = hmac.new(self._secret, blob, hashlib.sha256).digest()
            if not hmac.compare_digest(mac, want):
                raise PermissionError("set_optimizer HMAC mismatch")
            import mxnet_tpu.optimizer as opt
            optimizer = pickle.loads(blob)
            self._optimizer = optimizer
            self._updater = opt.get_updater(optimizer)
            _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_STATS:
            with self._lock:
                n = self.updates_applied
            _send_frame(conn, struct.pack(">Bq", _RE_INT, n))
        elif op == _OP_DONE:
            with self._lock:
                self.workers_done += 1
            _send_frame(conn, bytes([_RE_OK]))
        elif op == _OP_WAIT_DONE:
            n, timeout = struct.unpack_from(">qd", buf, off)
            import time as _t
            deadline = _t.monotonic() + timeout
            reached = 0
            while True:  # condition first: timeout=0 is a valid poll
                with self._lock:
                    if self.workers_done >= n:
                        reached = 1
                        break
                if _t.monotonic() >= deadline:
                    break
                _t.sleep(0.02)
            _send_frame(conn, struct.pack(">Bq", _RE_INT, reached))
        elif op == _OP_STOP:
            _send_frame(conn, bytes([_RE_OK]))
            self._stop.set()
        else:
            raise ValueError("unknown opcode %d" % op)

    def _apply(self, key, grad):
        import mxnet_tpu as mx
        w = mx.nd.array(self._store[key])
        g = mx.nd.array(grad)
        from .kvstore import _str_key_int
        self._updater(key if isinstance(key, int) else _str_key_int(key),
                      g, w)
        self._store[key] = w.asnumpy()

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


class AsyncPSClient:
    """Worker-side connection (the reference's ps::KVWorker)."""

    def __init__(self, host, port, retries=50):
        import time
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        for attempt in range(retries):
            try:
                self._sock.connect((host, port))
                break
            except ConnectionRefusedError:
                if attempt == retries - 1:
                    raise
                time.sleep(0.1)  # server still coming up on rank 0
        self._lock = threading.Lock()

    def _call(self, payload):
        with self._lock:
            _send_frame(self._sock, payload)
            resp = _recv_frame(self._sock)
        if resp is None:
            raise ConnectionError("async PS server closed the connection")
        code = resp[0]
        if code == _RE_OK:
            return None
        if code == _RE_INT:
            return struct.unpack_from(">q", resp, 1)[0]
        if code == _RE_ARR:
            arr, _ = _unpack_arr(resp, 1)
            return arr
        if code == _RE_ERR:
            (n,) = struct.unpack_from(">H", resp, 1)
            raise RuntimeError(resp[3:3 + n].decode())
        raise ConnectionError("bad response opcode %d" % code)

    def init(self, key, arr):
        self._call(bytes([_OP_INIT]) + _pack_key(key)
                   + _pack_arr(np.asarray(arr)))

    def push(self, key, grad):
        self._call(bytes([_OP_PUSH]) + _pack_key(key)
                   + _pack_arr(np.asarray(grad)))

    def pull(self, key):
        return self._call(bytes([_OP_PULL]) + _pack_key(key))

    def set_optimizer(self, optimizer):
        secret = _ps_secret()
        if secret is None:
            raise RuntimeError(
                "MXTPU_PS_SECRET is not set; cannot authenticate the "
                "pickled optimizer (serve_if_rank0 generates one — set "
                "it in the launcher env for multi-host runs)")
        blob = pickle.dumps(optimizer)
        mac = hmac.new(secret, blob, hashlib.sha256).digest()
        self._call(bytes([_OP_SET_OPT]) + mac + blob)

    def updates_applied(self):
        return self._call(bytes([_OP_STATS]))

    def done(self):
        self._call(bytes([_OP_DONE]))

    def wait_done(self, n, timeout=None):
        """Wait until `n` workers called done(); returns True if they
        did before the deadline (default MXTPU_PS_DONE_TIMEOUT, 120s —
        matching the reference's barrier-before-exit patience)."""
        if timeout is None:
            timeout = float(os.environ.get("MXTPU_PS_DONE_TIMEOUT", "120"))
        reached = self._call(struct.pack(">Bqd", _OP_WAIT_DONE, n,
                                         float(timeout)))
        if not reached:
            warnings.warn(
                "async PS shutdown: %d worker done() signals did not "
                "arrive within %.0fs; stopping anyway" % (n, timeout),
                RuntimeWarning, stacklevel=2)
        return bool(reached)

    def stop_server(self):
        try:
            self._call(bytes([_OP_STOP]))
        except (ConnectionError, OSError):
            pass


class AsyncKVStore:
    """KVStore-shaped facade over the async PS (the `dist_async` type
    returned by mx.kv.create). Each push is applied server-side
    immediately; pull returns the current (possibly stale) weights —
    the reference's async convergence semantics, not sync's."""

    def __init__(self):
        rank = int(os.environ.get("MXTPU_PROC_ID", "0"))
        nproc = int(os.environ.get("MXTPU_NUM_PROCS", "1"))
        self._rank = rank
        self._num_workers = nproc
        self._server, self._client = serve_if_rank0(rank)
        self._optimizer = None
        self._done_sent = False

    # identity
    @property
    def type(self):
        return "dist_async"

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    # data plane
    def init(self, key, value):
        from .kvstore import _ctype_key_value
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            self._client.init(k, vlist[0].asnumpy())

    def push(self, key, value, priority=0):
        from .kvstore import _ctype_key_value
        import mxnet_tpu.ndarray as nd
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            merged = vlist[0] if len(vlist) == 1 else nd.add_n(*vlist)
            self._client.push(k, merged.asnumpy())

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from .kvstore import _ctype_key_value
        import jax.numpy as jnp
        assert out is not None
        keys, outs = _ctype_key_value(key, out)
        for k, olist in zip(keys, outs):
            arr = jnp.asarray(self._client.pull(k))
            for o in olist:
                o._data = arr
        return out

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out=out, priority=priority)
        return out

    def broadcast(self, key, value, out=None, priority=0):
        self.init(key, value)
        if out is not None:
            self.pull(key, out=out)
        return out

    def set_optimizer(self, optimizer):
        """Pickle the optimizer to the server, which applies it per push
        (ref: python/mxnet/kvstore_server.py _controller). The blob is
        HMAC-authenticated on the wire — see module docstring."""
        self._optimizer = optimizer
        self._client.set_optimizer(optimizer)

    # the rest of the KVStore surface callers touch (Module/Trainer) —
    # same contracts as kvstore.py
    def set_gradient_compression(self, compression_params):
        raise NotImplementedError(
            "gradient compression over the async PS transport is not "
            "implemented; use dist_sync for compressed pushes "
            "(ref: gradient_compression.h applies to the sync path)")

    def set_updater(self, updater):
        raise NotImplementedError(
            "dist_async applies updates server-side; set_optimizer() "
            "ships the optimizer to the server (kvstore_server.py UX)")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        import pickle as _p
        with open(fname, "wb") as f:
            _p.dump(self._optimizer if dump_optimizer else None, f)

    def load_optimizer_states(self, fname):
        import pickle as _p
        with open(fname, "rb") as f:
            o = _p.load(f)
        if o is not None:
            self.set_optimizer(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        raise NotImplementedError(
            "row_sparse_pull over the async PS is not implemented; "
            "use dist_sync (kvstore.py row_sparse_pull)")

    def updates_applied(self):
        return self._client.updates_applied()

    def done(self):
        """Signal this worker finished (coordination for clean server
        shutdown — the reference's Postoffice barrier-before-exit)."""
        if not self._done_sent:
            self._done_sent = True
            self._client.done()

    def close(self):
        # Count our own rank as done so a Trainer/Module exit that never
        # called done() explicitly doesn't stall waiting for itself.
        self.done()
        if self._server is not None:
            self._client.wait_done(self._num_workers)
            self._client.stop_server()
            self._server.stop()


def serve_if_rank0(rank, port_env="MXTPU_ASYNC_PS_PORT"):
    """Launcher hook: rank 0 hosts the server; every rank returns a
    client. The port is derived deterministically from the launcher's
    coordinator port (DMLC_PS_ROOT_PORT analog) so non-zero ranks know
    it before the server even starts — they retry until rank 0 binds.

    The server binds to the coordinator interface when one is
    configured (multi-host), else loopback — never 0.0.0.0."""
    coord = os.environ.get("MXTPU_COORDINATOR", "")
    if coord and ":" in coord:
        host, cport = coord.rsplit(":", 1)
        host = host or "127.0.0.1"
        port = int(os.environ.get(port_env, 0)) or (int(cport) + 1001)
    else:
        host, port = "127.0.0.1", int(os.environ.get(port_env, 0))
    if rank == 0 and "MXTPU_PS_SECRET" not in os.environ:
        # generated before fork/spawn of local workers; multi-host
        # launchers pass MXTPU_* env through (tools/launch.py)
        os.environ["MXTPU_PS_SECRET"] = _secrets.token_hex(32)
    if rank == 0:
        bind = host if host not in ("127.0.0.1", "localhost") else "127.0.0.1"
        server = AsyncPSServer(port, bind_host=bind)
        os.environ[port_env] = str(server.port)
        return server, AsyncPSClient(bind, server.port)
    return None, AsyncPSClient(host, port)
