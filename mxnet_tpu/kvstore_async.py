"""Host-driven asynchronous parameter server: real `dist_async`.

ref: src/kvstore/kvstore_dist_server.h:346-359 — in async mode the
server applies each worker's push IMMEDIATELY (`ApplyUpdates` without
the NumWorkers aggregation barrier), so workers train on stale weights;
convergence behavior genuinely differs from dist_sync. The ICI
collectives that back dist_sync are inherently synchronous, so — as
SURVEY §5 prescribes — async runs over a host-side transport: a server
thread in the rank-0 process owns the weights and applies updates as
pickled (push) messages arrive over TCP; pulls return whatever mix of
updates has landed. This is the ps-lite worker/server split with the
scheduler folded into the launcher's coordinator env.

Wire protocol: 4-byte big-endian length + pickled tuple
  ("init", key, np_array) / ("push", key, np_array)
  ("pull", key) -> np_array        ("set_optimizer", pickled_bytes)
  ("barrier",) -> ok               ("stop",)
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading

import numpy as np

__all__ = ["AsyncPSServer", "AsyncPSClient", "serve_if_rank0"]


def _send(sock, obj):
    data = pickle.dumps(obj)
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv(sock):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    n = struct.unpack(">I", hdr)[0]
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return pickle.loads(buf)


class AsyncPSServer:
    """Weight owner + immediate-apply update loop (the reference's
    KVStoreDistServer in async mode)."""

    def __init__(self, port=0):
        self._store = {}
        self._updater = None
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("0.0.0.0", port))  # reachable from other hosts
        # under the ssh launcher (the coordinator host dials in)
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        self.updates_applied = 0          # observability for tests
        self.workers_done = 0

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                self._srv.settimeout(0.2)
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn):
        while not self._stop.is_set():
            try:
                msg = _recv(conn)
            except OSError:
                return
            if msg is None:
                return
            try:
                self._handle(conn, msg)
            except Exception as e:  # noqa: BLE001 — reply, don't die
                try:
                    _send(conn, ("err", "%s: %s" % (type(e).__name__, e)))
                except OSError:
                    return
            if msg[0] == "stop":
                return

    def _handle(self, conn, msg):
            op = msg[0]
            if op == "init":
                _, key, arr = msg
                with self._lock:
                    self._store.setdefault(key, np.array(arr, copy=True))
                _send(conn, ("ok",))
            elif op == "push":
                _, key, grad = msg
                # IMMEDIATE apply — no cross-worker barrier (async
                # semantics, kvstore_dist_server.h:358)
                with self._lock:
                    if self._updater is not None:
                        self._apply(key, np.asarray(grad))
                    else:
                        # same store-replace semantics as the sync
                        # KVStore without an optimizer (kvstore.py push)
                        self._store[key] = np.array(grad, copy=True)
                    self.updates_applied += 1
                _send(conn, ("ok",))
            elif op == "pull":
                _, key = msg
                with self._lock:
                    _send(conn, ("val", np.array(self._store[key],
                                                 copy=True)))
            elif op == "set_optimizer":
                # the reference pickles the optimizer worker-side and the
                # server builds its updater from it (kvstore_server.py)
                _, blob = msg
                import mxnet_tpu.optimizer as opt
                optimizer = pickle.loads(blob)
                self._opt_states = {}
                self._optimizer = optimizer
                self._updater = opt.get_updater(optimizer)
                _send(conn, ("ok",))
            elif op == "stats":
                with self._lock:
                    _send(conn, ("val", self.updates_applied))
            elif op == "done":
                with self._lock:
                    self.workers_done += 1
                _send(conn, ("ok",))
            elif op == "wait_done":
                _, n = msg
                import time as _t
                deadline = _t.monotonic() + 120
                while _t.monotonic() < deadline:
                    with self._lock:
                        if self.workers_done >= n:
                            break
                    _t.sleep(0.02)
                _send(conn, ("ok",))
            elif op == "stop":
                _send(conn, ("ok",))
                self._stop.set()
            else:
                _send(conn, ("err", "unknown op %r" % (op,)))

    def _apply(self, key, grad):
        import mxnet_tpu as mx
        w = mx.nd.array(self._store[key])
        g = mx.nd.array(grad)
        from .kvstore import _str_key_int
        self._updater(key if isinstance(key, int) else _str_key_int(key),
                      g, w)
        self._store[key] = w.asnumpy()

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


class AsyncPSClient:
    """Worker-side connection (the reference's ps::KVWorker)."""

    def __init__(self, host, port, retries=50):
        import time
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        for attempt in range(retries):
            try:
                self._sock.connect((host, port))
                break
            except ConnectionRefusedError:
                if attempt == retries - 1:
                    raise
                time.sleep(0.1)  # server still coming up on rank 0
        self._lock = threading.Lock()

    def _call(self, *msg):
        with self._lock:
            _send(self._sock, msg)
            resp = _recv(self._sock)
        if resp is None:
            raise ConnectionError("async PS server closed the connection")
        if resp[0] == "err":
            raise RuntimeError(resp[1])
        return resp[1] if len(resp) > 1 else None

    def init(self, key, arr):
        self._call("init", key, np.asarray(arr))

    def push(self, key, grad):
        self._call("push", key, np.asarray(grad))

    def pull(self, key):
        return self._call("pull", key)

    def set_optimizer(self, optimizer):
        self._call("set_optimizer", pickle.dumps(optimizer))

    def updates_applied(self):
        return self._call("stats")

    def done(self):
        self._call("done")

    def wait_done(self, n):
        self._call("wait_done", n)

    def stop_server(self):
        try:
            self._call("stop")
        except (ConnectionError, OSError):
            pass


class AsyncKVStore:
    """KVStore-shaped facade over the async PS (the `dist_async` type
    returned by mx.kv.create). Each push is applied server-side
    immediately; pull returns the current (possibly stale) weights —
    the reference's async convergence semantics, not sync's."""

    def __init__(self):
        rank = int(os.environ.get("MXTPU_PROC_ID", "0"))
        nproc = int(os.environ.get("MXTPU_NUM_PROCS", "1"))
        self._rank = rank
        self._num_workers = nproc
        self._server, self._client = serve_if_rank0(rank)
        self._optimizer = None

    # identity
    @property
    def type(self):
        return "dist_async"

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    # data plane
    def init(self, key, value):
        from .kvstore import _ctype_key_value
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            self._client.init(k, vlist[0].asnumpy())

    def push(self, key, value, priority=0):
        from .kvstore import _ctype_key_value
        from .ndarray import NDArray
        import mxnet_tpu.ndarray as nd
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            merged = vlist[0] if len(vlist) == 1 else nd.add_n(*vlist)
            self._client.push(k, merged.asnumpy())

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from .kvstore import _ctype_key_value
        import jax.numpy as jnp
        assert out is not None
        keys, outs = _ctype_key_value(key, out)
        for k, olist in zip(keys, outs):
            arr = jnp.asarray(self._client.pull(k))
            for o in olist:
                o._data = arr
        return out

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out=out, priority=priority)
        return out

    def broadcast(self, key, value, out=None, priority=0):
        self.init(key, value)
        if out is not None:
            self.pull(key, out=out)
        return out

    def set_optimizer(self, optimizer):
        """Pickle the optimizer to the server, which applies it per push
        (ref: python/mxnet/kvstore_server.py _controller)."""
        self._optimizer = optimizer
        self._client.set_optimizer(optimizer)

    # the rest of the KVStore surface callers touch (Module/Trainer) —
    # same contracts as kvstore.py
    def set_gradient_compression(self, compression_params):
        raise NotImplementedError(
            "gradient compression over the async PS transport is not "
            "implemented; use dist_sync for compressed pushes "
            "(ref: gradient_compression.h applies to the sync path)")

    def set_updater(self, updater):
        raise NotImplementedError(
            "dist_async applies updates server-side; set_optimizer() "
            "ships the optimizer to the server (kvstore_server.py UX)")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        import pickle as _p
        with open(fname, "wb") as f:
            _p.dump(self._optimizer if dump_optimizer else None, f)

    def load_optimizer_states(self, fname):
        import pickle as _p
        with open(fname, "rb") as f:
            o = _p.load(f)
        if o is not None:
            self.set_optimizer(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        raise NotImplementedError(
            "row_sparse_pull over the async PS is not implemented; "
            "use dist_sync (kvstore.py row_sparse_pull)")

    def updates_applied(self):
        return self._client.updates_applied()

    def done(self):
        """Signal this worker finished (coordination for clean server
        shutdown — the reference's Postoffice barrier-before-exit)."""
        self._client.done()

    def close(self):
        if self._server is not None:
            self._client.wait_done(self._num_workers - 1)
            self._client.stop_server()
            self._server.stop()


def serve_if_rank0(rank, port_env="MXTPU_ASYNC_PS_PORT"):
    """Launcher hook: rank 0 hosts the server; every rank returns a
    client. The port is derived deterministically from the launcher's
    coordinator port (DMLC_PS_ROOT_PORT analog) so non-zero ranks know
    it before the server even starts — they retry until rank 0 binds."""
    coord = os.environ.get("MXTPU_COORDINATOR", "")
    if coord and ":" in coord:
        host, cport = coord.rsplit(":", 1)
        host = host or "127.0.0.1"
        port = int(os.environ.get(port_env, 0)) or (int(cport) + 1001)
    else:
        host, port = "127.0.0.1", int(os.environ.get(port_env, 0))
    if rank == 0:
        server = AsyncPSServer(port)
        os.environ[port_env] = str(server.port)
        return server, AsyncPSClient("127.0.0.1", server.port)
    return None, AsyncPSClient(host, port)
